"""Connector framework: properties -> split enumeration -> readers ->
parsers -> chunks, with per-split offsets in checkpoint state.

Reference: src/connector/src/source/base.rs — ``SourceProperties``
(:66, per-connector config), ``SplitEnumerator`` (:116, discover
partitions), ``SplitReader`` (:336, stream of messages); parsers in
src/connector/src/parser/ (JSON/CSV/...); the datagen connector
(source/datagen/) and partitioned-log sources (kafka/).

TPU re-design: readers return host COLUMNS (numpy), not row messages —
rows only exist inside parsers. One ``GenericSourceExecutor`` turns any
(enumerator, reader, parser) triple into barrier-aligned StreamChunks
with offsets committed per epoch through the same StateDelta path as
device state, so recovery resumes every split exactly (the first half
of exactly-once, source_executor.rs + state_table_handler.rs).
"""

from __future__ import annotations

import csv
import io
import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.array.composite import encode_column
from risingwave_tpu.array.dictionary import StringDictionary
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.storage.state_table import Checkpointable, StateDelta
from risingwave_tpu.types import Op
from risingwave_tpu.types import Schema


# ---------------------------------------------------------------------------
# framework traits
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SplitMeta:
    """One unit of source parallelism (base.rs SplitMetaData)."""

    split_id: str
    props: dict = field(default_factory=dict)


class SplitEnumerator:
    """Discovers the current split set (base.rs:116). Called at source
    build and by periodic discovery (SourceManager re-assignment)."""

    def list_splits(self) -> List[SplitMeta]:
        raise NotImplementedError


class SplitReader:
    """Reads one split from an offset (base.rs:336).

    ``read(split, offset, max_rows)`` returns (raw_rows, new_offset)
    where raw_rows is a list of parser inputs (str lines / dicts).
    Readers are stateless: all position lives in the offset, so a
    recovered offset resumes exactly."""

    def read(self, split: SplitMeta, offset: int, max_rows: int):
        raise NotImplementedError


class Parser:
    """Raw message -> column values in schema order (parser/ crate)."""

    def __init__(self, schema: Schema):
        self.schema = schema

    def parse(self, raw) -> Optional[Tuple]:
        """One message -> row tuple (schema order), or None to drop."""
        raise NotImplementedError

    @staticmethod
    def binary_raw(raw) -> Optional[bytes]:
        """Normalize a raw message for BINARY parsers: text-carried
        sources (file logs) deliver hex strings; None = undecodable."""
        if isinstance(raw, bytes):
            return raw
        if isinstance(raw, str):
            try:
                return bytes.fromhex(raw)
            except ValueError:
                return None
        return None


# ---------------------------------------------------------------------------
# parsers
# ---------------------------------------------------------------------------


class JsonParser(Parser):
    """One JSON object per message (parser/json_parser.rs); missing
    fields become NULL, unknown fields are ignored."""

    def parse(self, raw) -> Optional[Tuple]:
        if isinstance(raw, (bytes, str)):
            try:
                obj = json.loads(raw)
            except json.JSONDecodeError:
                return None  # dead-letter drop (non-strict parse mode)
        else:
            obj = raw
        if not isinstance(obj, dict):
            return None
        return tuple(
            self._coerce(f, obj.get(f.name)) for f in self.schema.fields
        )

    @staticmethod
    def _coerce(f, v):
        """Type-check a cell into the field's lane domain; bad cells
        become NULL (CsvParser convention) instead of blowing up
        encode_column AFTER source offsets have advanced."""
        if v is None:
            return None
        kind = f.dtype.value
        try:
            if kind == "jsonb":
                # encode_column canonicalizes the RAW value (key-order-
                # insensitive codes) — stringifying would double-encode
                return v
            if kind == "struct":
                return v if isinstance(v, dict) else None
            if kind == "list":
                cap = getattr(f, "list_cap", None)
                if not isinstance(v, (list, tuple)):
                    return None
                return v if cap is None or len(v) <= cap else None
            if kind == "interval":
                # JSON has no interval literal; only an already-built
                # Interval survives (encode_column requires one)
                from risingwave_tpu.types import Interval

                return v if isinstance(v, Interval) else None
            if kind == "varchar":
                return v if isinstance(v, str) else json.dumps(v)
            if kind in ("float32", "float64"):
                return float(v)
            if kind == "boolean":
                if isinstance(v, bool):
                    return v
                if isinstance(v, (int, float)) and v in (0, 1):
                    return bool(v)
                if isinstance(v, str):
                    low = v.lower()
                    if low in ("t", "true", "1"):
                        return True
                    if low in ("f", "false", "0"):
                        return False
                return None  # bool("false") is True — never truthiness
            if kind == "decimal":
                from decimal import Decimal, InvalidOperation

                text = v if isinstance(v, str) else repr(v)
                try:
                    if not Decimal(text).is_finite():
                        return None  # NaN/Infinity would blow scaling
                except (TypeError, ValueError, InvalidOperation):
                    return None
                return text
            # int lanes: reject non-numeric strings; a non-integral
            # float becomes NULL (bad-cell convention) — never silently
            # truncate 3.7 -> 3
            if isinstance(v, float) and not v.is_integer():
                return None
            return int(v)
        except (TypeError, ValueError):
            return None


class ChangeParser(Parser):
    """Parser emitting CHANGE events rather than plain rows:
    ``parse_changes(raw) -> [(op, row), ...]`` (parser/unified/ in the
    reference — one upstream record may yield several ops)."""

    def parse(self, raw):  # pragma: no cover - changes path only
        raise TypeError("ChangeParser: use parse_changes")

    def parse_changes(self, raw):
        raise NotImplementedError

    @staticmethod
    def _decode_obj(raw):
        """bytes/str -> dict, None when undecodable (non-strict)."""
        if isinstance(raw, (bytes, str)):
            try:
                raw = json.loads(raw)
            except json.JSONDecodeError:
                return None
        return raw if isinstance(raw, dict) else None


class DebeziumJsonParser(ChangeParser):
    """Debezium CDC envelope (reference: parser/debezium/ +
    source/cdc/): ``{"before": .., "after": .., "op": "c|r|u|d"}``.

    - ``c`` (create) and ``r`` (read) -> INSERT of ``after``. ``r`` is
      the CDC BACKFILL lane: the connector snapshots the upstream
      table as reads before streaming changes (cdc backfill contract,
      src/stream/src/executor/backfill/cdc/), so a fresh MV converges
      to the source table and then follows its changes;
    - ``u`` -> UPDATE_DELETE of ``before`` + UPDATE_INSERT of
      ``after``;
    - ``d`` -> DELETE of ``before``.

    Tolerates the schema-ful envelope (``{"schema":.., "payload":..}``)
    and drops undecodable records (non-strict mode)."""

    def __init__(self, schema: Schema):
        super().__init__(schema)
        self._rows = JsonParser(schema)

    def parse_changes(self, raw):
        obj = self._decode_obj(raw)
        if obj is None:
            return []
        payload = obj.get("payload", obj)
        if not isinstance(payload, dict):
            return []
        op = payload.get("op")
        before = payload.get("before")
        after = payload.get("after")
        out = []
        if op in ("c", "r") and isinstance(after, dict):
            out.append((int(Op.INSERT), self._rows.parse(after)))
        elif op == "d" and isinstance(before, dict):
            out.append((int(Op.DELETE), self._rows.parse(before)))
        elif op == "u" and isinstance(before, dict) and isinstance(
            after, dict
        ):
            out.append((int(Op.UPDATE_DELETE), self._rows.parse(before)))
            out.append((int(Op.UPDATE_INSERT), self._rows.parse(after)))
        if any(r is None for _, r in out):
            # drop the WHOLE change: emitting one half of an update
            # pair would strand a stale row downstream
            return []
        return out


class UpsertJsonParser(ChangeParser):
    """Upsert-keyed JSON (reference: parser/ upsert_json + the Kafka
    upsert model): each record is ``{"key": {...}, "value": {...}}``;
    a NULL/absent value is a DELETE of the key (the key fields fill
    the row, value fields NULL). Plain objects (no key envelope) fall
    back to inserts.

    CONTRACT (same as the reference's upsert sources, which REQUIRE a
    PRIMARY KEY): the first consumer must be a pk-keyed materialize —
    an upsert emits a plain INSERT with NO retraction of the prior
    value (overwrite-by-pk resolves it), and a tombstone's value
    columns are NULL. Feeding an aggregation directly would
    double-count."""

    def __init__(self, schema: Schema):
        super().__init__(schema)
        self._rows = JsonParser(schema)

    def parse_changes(self, raw):
        obj = self._decode_obj(raw)
        if obj is None:
            return []
        key = obj.get("key")
        # a DICT-valued "key" member marks the envelope (value may be
        # absent/null — producers with null-omitting serializers emit
        # tombstones as bare {"key": ...}); a non-dict/absent key is a
        # plain record (a schema may have a scalar column named "key")
        if not isinstance(key, dict):
            row = self._rows.parse(obj)
            return [(int(Op.INSERT), row)] if row is not None else []
        val = obj.get("value")
        if val is None:
            row = self._rows.parse(key)
            return [(int(Op.DELETE), row)] if row is not None else []
        if not isinstance(val, dict):
            return []
        row = self._rows.parse({**key, **val})
        return [(int(Op.INSERT), row)] if row is not None else []


class ProtobufParser(Parser):
    """Protobuf-encoded messages (reference: parser/protobuf/): decode
    with a compiled message class (the descriptor the reference loads
    from a schema registry maps to gencode here), then coerce fields
    by name through the same lane rules as JSON."""

    def __init__(self, schema: Schema, message_cls):
        super().__init__(schema)
        self.message_cls = message_cls

    def parse(self, raw) -> Optional[Tuple]:
        raw = self.binary_raw(raw)
        if raw is None:
            return None
        msg = self.message_cls()
        try:
            msg.ParseFromString(raw)
        except Exception:
            return None  # dead-letter drop (non-strict mode)
        out = []
        for f in self.schema.fields:
            try:
                # proto3 semantics: a scalar field always HAS a value
                # (0/empty is the default, not NULL) — NULL only when
                # the message type lacks the field entirely
                v = getattr(msg, f.name)
            except AttributeError:
                v = None
            out.append(JsonParser._coerce(f, self._pythonize(v)))
        return tuple(out)

    @staticmethod
    def _pythonize(v):
        """Protobuf containers -> plain python so the shared lane rules
        apply: nested messages become dicts (SET fields only — walking
        every descriptor field would recurse forever on
        self-referential types), map fields dicts, repeated fields
        lists. Manual walk, not MessageToDict: the proto3-JSON mapping
        would stringify int64 and base64 bytes."""
        if v is None or isinstance(v, (int, float, str, bytes, bool)):
            return v
        # message check FIRST: a message with a field literally named
        # "items" would otherwise duck-type as a map container
        if hasattr(v, "DESCRIPTOR"):
            return {
                fd.name: ProtobufParser._pythonize(val)
                for fd, val in v.ListFields()
            }
        if hasattr(v, "items"):  # map<k,v> containers are dict-like
            return {
                k: ProtobufParser._pythonize(x) for k, x in v.items()
            }
        try:  # repeated containers
            return [ProtobufParser._pythonize(x) for x in v]
        except TypeError:
            return v


class CsvParser(Parser):
    """Delimited text (parser/csv_parser.rs); columns positional in
    schema order; empty fields become NULL."""

    def __init__(self, schema: Schema, delimiter: str = ","):
        super().__init__(schema)
        self.delimiter = delimiter

    def parse(self, raw) -> Optional[Tuple]:
        text = raw.decode() if isinstance(raw, bytes) else raw
        try:
            row = next(csv.reader(io.StringIO(text), delimiter=self.delimiter))
            out = []
            for f, cell in zip(self.schema.fields, row):
                if cell == "":
                    out.append(None)
                elif f.dtype.value in ("varchar", "jsonb"):
                    out.append(cell)
                elif f.dtype.value in ("float32", "float64"):
                    out.append(float(cell))
                elif f.dtype.value == "boolean":
                    out.append(cell.lower() in ("t", "true", "1"))
                elif f.dtype.value == "decimal":
                    out.append(cell)  # Decimal-exact via composite encode
                else:
                    out.append(int(cell))
        except (StopIteration, ValueError, csv.Error):
            # bad cell/empty message -> dead-letter drop, same as the
            # JSON parser: one malformed line must never poison the
            # batch (offsets have already advanced past it)
            return None
        out.extend([None] * (len(self.schema.fields) - len(out)))
        return tuple(out)


# ---------------------------------------------------------------------------
# connectors
# ---------------------------------------------------------------------------


class DatagenSource(SplitEnumerator, SplitReader):
    """Schema-driven deterministic generator (source/datagen/): each
    field gets a sequence or seeded-random stream; splits partition the
    sequence space so multi-split reads never collide."""

    def __init__(
        self,
        schema: Schema,
        split_num: int = 1,
        seed: int = 7,
        fields: Optional[Dict[str, dict]] = None,
    ):
        self.schema = schema
        self.split_num = split_num
        self.seed = seed
        # field name -> {"kind": "sequence"|"random", "start", "end"}
        self.fields = fields or {}

    def list_splits(self) -> List[SplitMeta]:
        return [SplitMeta(str(i)) for i in range(self.split_num)]

    def read(self, split: SplitMeta, offset: int, max_rows: int):
        sid = int(split.split_id)
        n = max_rows
        # global row ids: interleaved across splits (datagen splits
        # partition the sequence space)
        ids = offset + np.arange(n, dtype=np.int64)
        gids = ids * self.split_num + sid
        rows = []
        for j in range(n):
            row = {}
            for f in self.schema.fields:
                spec = self.fields.get(f.name, {"kind": "sequence"})
                if spec.get("kind") == "random":
                    lo = int(spec.get("start", 0))
                    hi = int(spec.get("end", 1 << 20))
                    # field identity in the seed: same-range fields
                    # must draw INDEPENDENT streams. crc32, not hash():
                    # recovery re-reads committed offsets and must
                    # regenerate IDENTICAL rows across process restarts
                    import zlib

                    fseed = (
                        zlib.crc32(f.name.encode()) ^ self.seed
                    ) & 0x7FFFFFFF
                    rng = np.random.default_rng(
                        fseed * 1_000_003 + int(gids[j])
                    )
                    row[f.name] = int(rng.integers(lo, hi))
                else:
                    row[f.name] = int(spec.get("start", 0)) + int(gids[j])
            rows.append(row)  # dict rows: parser-compatible messages
        return rows, offset + n


class FileLogSource(SplitEnumerator, SplitReader):
    """Partitioned append-only log directory — the kafka-shaped source
    (source/kafka/ without brokers): ``<dir>/partition-<i>.log`` holds
    one message per line; the BYTE position after the last consumed
    line is the offset, so committed offsets resume exactly after
    recovery and each poll seeks straight to the frontier. Independent
    producers append concurrently."""

    def __init__(self, directory: str):
        self.directory = directory

    def list_splits(self) -> List[SplitMeta]:
        out = []
        for name in sorted(os.listdir(self.directory)):
            if name.startswith("partition-") and name.endswith(".log"):
                out.append(SplitMeta(name[len("partition-"):-len(".log")]))
        return out

    def read(self, split: SplitMeta, offset: int, max_rows: int):
        """``offset`` is a BYTE position: each poll seeks directly to
        the frontier (a line index would re-scan the whole file every
        poll — quadratic over the source lifetime). Lines missing their
        trailing newline are in-flight producer writes and wait."""
        path = os.path.join(
            self.directory, f"partition-{split.split_id}.log"
        )
        rows: List[str] = []
        pos = offset
        if os.path.exists(path):
            with open(path, "rb") as f:
                f.seek(offset)
                while len(rows) < max_rows:
                    line = f.readline()
                    if not line or not line.endswith(b"\n"):
                        break
                    pos = f.tell()
                    text = line[:-1].decode()
                    if text:
                        rows.append(text)
        return rows, pos

    @staticmethod
    def append(directory: str, partition: int, messages: Iterable[str]):
        """Producer-side helper (tests / demos)."""
        path = os.path.join(directory, f"partition-{partition}.log")
        with open(path, "a") as f:
            for m in messages:
                f.write(m + "\n")


def _split_code(split_id: str) -> int:
    """Stable int64 code for a split id — survives process restarts
    (python hash() is salted per process and would orphan every
    checkpointed offset)."""
    import hashlib

    if split_id.isdigit():
        return int(split_id)
    digest = hashlib.sha1(split_id.encode()).digest()
    return int.from_bytes(digest[:7], "big")


# ---------------------------------------------------------------------------
# the generic source executor
# ---------------------------------------------------------------------------


class GenericSourceExecutor(Executor, Checkpointable):
    """(enumerator, reader, parser) -> barrier-aligned chunks with
    committed per-split offsets (source_executor.rs role for any
    connector built on the framework)."""

    def __init__(
        self,
        connector,  # SplitEnumerator & SplitReader
        parser: Parser,
        table_id: str = "source.generic",
        strings: Optional[StringDictionary] = None,
    ):
        self.connector = connector
        self.parser = parser
        self.table_id = table_id
        self.strings = strings or StringDictionary()
        self.splits = connector.list_splits()
        self.offsets: Dict[str, int] = {s.split_id: 0 for s in self.splits}
        self._committed = dict(self.offsets)
        # source throttling (the reference's Mutation::Throttle /
        # ALTER ... SET rate_limit, common/rate_limit.rs): a token
        # bucket in source RECORDS/sec, refilled on wall time, burst
        # capped at one second's worth. None = unthrottled.
        self.rate_limit: Optional[int] = None
        self._bucket = 0.0
        self._bucket_t: Optional[float] = None
        self._poll_rr = 0  # fair-start rotation under throttling

    def set_rate_limit(self, rows_per_s: Optional[int]) -> None:
        """Throttle change (applies from the next poll — the barrier-
        mutation analogue in the host-pumped model)."""
        self.rate_limit = rows_per_s
        self._bucket = float(rows_per_s) if rows_per_s else 0.0
        self._bucket_t = None

    def _throttle_allowance(self) -> Optional[int]:
        if self.rate_limit is None:
            return None
        import time as _time

        now = _time.monotonic()
        if self._bucket_t is not None:
            self._bucket = min(
                float(self.rate_limit),
                self._bucket + (now - self._bucket_t) * self.rate_limit,
            )
        self._bucket_t = now
        return int(self._bucket)

    def discover(self) -> List[SplitMeta]:
        """Re-enumerate splits (SourceManager periodic discovery): new
        partitions start at offset 0; existing offsets are kept."""
        self.splits = self.connector.list_splits()
        for s in self.splits:
            self.offsets.setdefault(s.split_id, 0)
        return self.splits

    def poll(
        self,
        max_rows_per_split: int,
        capacity: int,
        only: Optional[set] = None,
    ) -> List[StreamChunk]:
        """Read every split once (or the ``only`` subset — a parallel
        source worker reads just its ASSIGNED splits, SourceManager
        contract); returns at most one chunk per split."""
        out: List[StreamChunk] = []
        staged: Dict[str, int] = {}
        allowance = self._throttle_allowance()
        splits = self.splits
        if allowance is not None and splits:
            # fairness under throttling: rotate the starting split per
            # poll, or a busy early split starves every later one (the
            # reference's per-reader rate limit has no such coupling)
            r = self._poll_rr % len(splits)
            splits = splits[r:] + splits[:r]
            self._poll_rr += 1
        for s in splits:
            if only is not None and s.split_id not in only:
                continue
            limit = max_rows_per_split
            if allowance is not None:
                if allowance <= 0:
                    break  # bucket dry: later splits wait for refill
                limit = min(limit, allowance)
            raw, new_off = self.connector.read(
                s, self.offsets[s.split_id], limit
            )
            if allowance is not None:
                allowance -= len(raw)
                self._bucket -= len(raw)
            if isinstance(self.parser, ChangeParser):
                pairs = [
                    p
                    for r in raw
                    for p in self.parser.parse_changes(r)
                ]
                rows = [r for _, r in pairs]
                all_ops = [o for o, _ in pairs]
            else:
                rows = [
                    r for r in map(self.parser.parse, raw) if r is not None
                ]
                all_ops = None
            # an update envelope doubles its row count: slice into
            # capacity-bounded chunks so a full poll window of updates
            # cannot overflow DataChunk.from_numpy
            for at in range(0, len(rows), capacity):
                part = rows[at : at + capacity]
                lanes: Dict[str, np.ndarray] = {}
                nulls: Dict[str, np.ndarray] = {}
                for j, f in enumerate(self.schema.fields):
                    cl, cn = encode_column(
                        f, [r[j] for r in part], self.strings
                    )
                    lanes.update(cl)
                    if cn:
                        nulls.update(cn)
                ops_arr = (
                    np.asarray(all_ops[at : at + capacity], np.int32)
                    if all_ops is not None
                    else None
                )
                out.append(
                    StreamChunk.from_numpy(
                        lanes, capacity, ops=ops_arr, nulls=nulls or None
                    )
                )
            staged[s.split_id] = new_off
        # offsets advance only after EVERY split encoded: a failure on
        # split k must not strand splits < k (their chunks were never
        # returned) past offsets the next checkpoint would commit — the
        # whole failed poll re-reads instead (exact-resume contract)
        self.offsets.update(staged)
        return out

    @property
    def schema(self) -> Schema:
        return self.parser.schema

    # -- checkpoint/restore ----------------------------------------------
    def state_digest(self) -> int:
        """Durable logical state is the per-split offset map."""
        from risingwave_tpu.integrity import host_obj_digest

        return host_obj_digest(dict(self.offsets))

    def checkpoint_delta(self) -> List[StateDelta]:
        if self.offsets == self._committed:
            return []
        self._committed = dict(self.offsets)
        ids = sorted(self.offsets)
        codes = np.asarray([_split_code(i) for i in ids], np.int64)
        return [
            StateDelta(
                self.table_id,
                {"split": codes},
                {"offset": np.asarray([self.offsets[i] for i in ids], np.int64)},
                np.zeros(len(ids), bool),
                ("split",),
            )
        ]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        if not key_cols:
            return
        by_code = {_split_code(i): i for i in self.offsets}
        for code, offset in zip(
            key_cols["split"].tolist(), value_cols["offset"].tolist()
        ):
            sid = by_code.get(int(code))
            if sid is not None:
                self.offsets[sid] = int(offset)
        self._committed = dict(self.offsets)
        from risingwave_tpu.event_log import EVENT_LOG

        EVENT_LOG.record(
            "offset_resume",
            table_id=str(self.table_id),
            splits=len(self.offsets),
        )
