"""Avro binary decoding for source parsers.

Reference: src/connector/src/parser/avro/ (schema-registry Avro with
resolution). This is a dependency-free decoder for the subset the
engine's lane types need: records of null/boolean/int/long/float/
double/string/bytes/enum + unions-with-null (nullable fields) +
arrays of those. Schemas are plain Avro JSON schema documents; the
registry's wire framing (magic 0 + 4-byte schema id) is recognized
and skipped when present.

Zigzag varints, IEEE floats and length-prefixed bytes follow the Avro
1.11 binary spec.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Optional, Tuple

from risingwave_tpu.connectors.framework import JsonParser, Parser
from risingwave_tpu.types import Schema


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos : self.pos + n]
        if len(b) != n:
            raise EOFError("truncated avro record")
        self.pos += n
        return b

    def zigzag(self) -> int:
        """Avro long: little-endian base-128 varint, zigzag-coded."""
        shift = 0
        acc = 0
        while True:
            (byte,) = self.read(1)
            acc |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
            if shift > 70:
                raise ValueError("varint too long")
        return (acc >> 1) ^ -(acc & 1)


def _decode_value(r: _Reader, sch) -> object:
    if isinstance(sch, list):  # union: index picks the branch
        idx = r.zigzag()
        if not 0 <= idx < len(sch):
            raise ValueError(f"union branch {idx} out of range")
        return _decode_value(r, sch[idx])
    if isinstance(sch, dict):
        t = sch["type"]
        if t == "record":
            return {
                f["name"]: _decode_value(r, f["type"])
                for f in sch["fields"]
            }
        if t == "array":
            out: List[object] = []
            while True:
                n = r.zigzag()
                if n == 0:
                    break
                if n < 0:  # block with byte-size prefix
                    n = -n
                    r.zigzag()  # skip the size
                for _ in range(n):
                    out.append(_decode_value(r, sch["items"]))
            return out
        if t == "enum":
            syms = sch["symbols"]
            i = r.zigzag()
            if not 0 <= i < len(syms):
                raise ValueError("enum index out of range")
            return syms[i]
        t_inner = t  # {"type": "long"} wrapper form
        return _decode_value(r, t_inner)
    if sch == "null":
        return None
    if sch == "boolean":
        return r.read(1) != b"\x00"
    if sch in ("int", "long"):
        return r.zigzag()
    if sch == "float":
        return struct.unpack("<f", r.read(4))[0]
    if sch == "double":
        return struct.unpack("<d", r.read(8))[0]
    if sch in ("string", "bytes"):
        n = r.zigzag()
        if n < 0:
            raise ValueError("negative length")
        b = r.read(n)
        return b.decode() if sch == "string" else b
    raise ValueError(f"unsupported avro type {sch!r}")


def decode_record(blob: bytes, schema: dict) -> Optional[dict]:
    """One binary-encoded record -> field dict; None when undecodable.
    Confluent wire framing (0x00 + schema id) is skipped if present."""
    try:
        r = _Reader(blob)
        if len(blob) > 5 and blob[0] == 0:
            r.pos = 5  # magic byte + 4-byte registry schema id
            try:
                return _decode_value(_Reader(blob, 5), schema)
            except (EOFError, ValueError):
                r = _Reader(blob)  # not framed after all
        v = _decode_value(r, schema)
        return v if isinstance(v, dict) else None
    except (EOFError, ValueError, struct.error):
        return None


class AvroParser(Parser):
    """Avro-encoded source messages: decode the record against its
    writer schema (an Avro JSON schema document), then coerce fields
    by name through the shared JSON lane rules."""

    def __init__(self, schema: Schema, avro_schema):
        super().__init__(schema)
        if isinstance(avro_schema, str):
            avro_schema = json.loads(avro_schema)
        if avro_schema.get("type") != "record":
            raise ValueError("AvroParser needs a record schema")
        self.avro_schema = avro_schema

    def parse(self, raw) -> Optional[Tuple]:
        if isinstance(raw, str):
            try:
                raw = bytes.fromhex(raw)  # file-log sources carry text
            except ValueError:
                return None
        rec = decode_record(raw, self.avro_schema)
        if rec is None:
            return None
        return tuple(
            JsonParser._coerce(f, rec.get(f.name))
            for f in self.schema.fields
        )
