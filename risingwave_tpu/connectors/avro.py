"""Avro binary decoding for source parsers.

Reference: src/connector/src/parser/avro/ (schema-registry Avro with
resolution). This is a dependency-free decoder for the subset the
engine's lane types need: records of null/boolean/int/long/float/
double/string/bytes/enum + unions-with-null (nullable fields) +
arrays of those. Schemas are plain Avro JSON schema documents; the
registry's wire framing (magic 0 + 4-byte schema id) is a DECLARED
source property (``registry_framed=True``), never sniffed — an
unframed record whose first field encodes as byte 0 would misdecode.

Zigzag varints, IEEE floats and length-prefixed bytes follow the Avro
1.11 binary spec.
"""

from __future__ import annotations

import json
import struct
from typing import List, Optional, Tuple

from risingwave_tpu.connectors.framework import JsonParser, Parser
from risingwave_tpu.types import Schema


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos : self.pos + n]
        if len(b) != n:
            raise EOFError("truncated avro record")
        self.pos += n
        return b

    def zigzag(self) -> int:
        """Avro long: little-endian base-128 varint, zigzag-coded."""
        shift = 0
        acc = 0
        while True:
            (byte,) = self.read(1)
            acc |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
            if shift > 70:
                raise ValueError("varint too long")
        return (acc >> 1) ^ -(acc & 1)


def _decode_value(r: _Reader, sch) -> object:
    if isinstance(sch, list):  # union: index picks the branch
        idx = r.zigzag()
        if not 0 <= idx < len(sch):
            raise ValueError(f"union branch {idx} out of range")
        return _decode_value(r, sch[idx])
    if isinstance(sch, dict):
        t = sch["type"]
        if t == "record":
            return {
                f["name"]: _decode_value(r, f["type"])
                for f in sch["fields"]
            }
        if t == "array":
            out: List[object] = []
            while True:
                n = r.zigzag()
                if n == 0:
                    break
                if n < 0:  # block with byte-size prefix
                    n = -n
                    r.zigzag()  # skip the size
                for _ in range(n):
                    out.append(_decode_value(r, sch["items"]))
            return out
        if t == "enum":
            syms = sch["symbols"]
            i = r.zigzag()
            if not 0 <= i < len(syms):
                raise ValueError("enum index out of range")
            return syms[i]
        t_inner = t  # {"type": "long"} wrapper form
        return _decode_value(r, t_inner)
    if sch == "null":
        return None
    if sch == "boolean":
        return r.read(1) != b"\x00"
    if sch in ("int", "long"):
        return r.zigzag()
    if sch == "float":
        return struct.unpack("<f", r.read(4))[0]
    if sch == "double":
        return struct.unpack("<d", r.read(8))[0]
    if sch in ("string", "bytes"):
        n = r.zigzag()
        if n < 0:
            raise ValueError("negative length")
        b = r.read(n)
        return b.decode() if sch == "string" else b
    raise ValueError(f"unsupported avro type {sch!r}")


def decode_record(
    blob: bytes, schema: dict, framed: bool = False
) -> Optional[dict]:
    """One binary-encoded record -> field dict; None when undecodable.

    ``framed`` declares Confluent wire framing (0x00 magic + 4-byte
    registry schema id) — an EXPLICIT source property, never sniffed:
    a legitimate unframed record whose first field encodes as byte 0
    (long 0, false, empty string, union branch 0) would otherwise
    misdecode silently. The record must consume the whole buffer
    (single-record message contract)."""
    try:
        r = _Reader(blob, 5 if framed else 0)
        if framed and (len(blob) < 5 or blob[0] != 0):
            return None
        v = _decode_value(r, schema)
        if r.pos != len(blob):
            return None  # trailing garbage: not a clean record
        return v if isinstance(v, dict) else None
    except (EOFError, ValueError, struct.error, TypeError, KeyError,
            IndexError):
        # the documented contract is None-when-undecodable: a non-bytes
        # input or a malformed nested schema must drop the record, not
        # poison the split (offsets never advance past an exception)
        return None


class AvroParser(Parser):
    """Avro-encoded source messages: decode the record against its
    writer schema (an Avro JSON schema document), then coerce fields
    by name through the shared JSON lane rules. ``registry_framed``
    declares the Confluent wire envelope (a source property in the
    reference's WITH(...) options — never sniffed from the bytes)."""

    def __init__(self, schema: Schema, avro_schema, registry_framed=False):
        super().__init__(schema)
        if isinstance(avro_schema, str):
            avro_schema = json.loads(avro_schema)
        if avro_schema.get("type") != "record":
            raise ValueError("AvroParser needs a record schema")
        self.avro_schema = avro_schema
        self.registry_framed = bool(registry_framed)

    def parse(self, raw) -> Optional[Tuple]:
        raw = self.binary_raw(raw)
        if raw is None:
            return None
        rec = decode_record(
            raw, self.avro_schema, framed=self.registry_framed
        )
        if rec is None:
            return None
        return tuple(
            JsonParser._coerce(f, rec.get(f.name))
            for f in self.schema.fields
        )
