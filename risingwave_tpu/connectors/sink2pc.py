"""Coordinated two-phase-commit sinks — exactly-once external delivery.

Reference: src/meta/src/manager/sink_coordination/ (the coordinator
collects per-writer pre-commit metadata for an epoch and issues ONE
atomic commit) + the iceberg/file 2PC sinks it drives. Upgrades the
at-least-once LogSinker contract (connectors/log_store.py) to
exactly-once for sinks that can stage-then-publish atomically.

Protocol per epoch (each step idempotent, so every crash window
replays safely):

1. ``prepare(rows, epoch)`` — stage the batch durably but INVISIBLY
   (e.g. a staging file). Re-preparing an epoch overwrites the stage.
2. ``commit_prepared(epoch)`` — atomically publish (rename). A second
   commit of the same epoch is a no-op; committed epochs are immune
   to re-prepare.
3. The coordinator advances the log-store consumer offset only AFTER
   the external commit, so:
   - crash after prepare:   offset behind -> replay re-prepares
     (overwrite) and commits once;
   - crash after commit:    offset behind -> replay's commit is a
     no-op (already published);
   - rolled-back epochs:    never prepared past the durable frontier
     (``up_to``), and recovery aborts any staged leftovers.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from risingwave_tpu.connectors.log_store import KvLogStore
from risingwave_tpu.connectors.sink import Sink
from risingwave_tpu.resilience import RetryPolicy


class TwoPhaseSink(Sink):
    """A sink that can stage an epoch invisibly and publish atomically
    (the reference's coordinated sink trait)."""

    def prepare(self, rows, epoch: int) -> None:
        raise NotImplementedError

    def commit_prepared(self, epoch: int) -> None:
        raise NotImplementedError

    def abort_prepared(self, epoch: int) -> None:
        raise NotImplementedError

    def committed_epochs(self) -> List[int]:
        raise NotImplementedError

    # the plain Sink surface maps to prepare+commit in one step
    def write_batch(self, rows, epoch: int) -> None:
        self.prepare(rows, epoch)

    def commit(self, epoch: int) -> None:
        self.commit_prepared(epoch)


class FileTwoPhaseSink(TwoPhaseSink):
    """Stage to ``<dir>/staging/<epoch>``, publish by atomic rename to
    ``<dir>/committed/<epoch>`` (the file/iceberg 2PC shape)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(os.path.join(root, "staging"), exist_ok=True)
        os.makedirs(os.path.join(root, "committed"), exist_ok=True)

    def _staging(self, epoch: int) -> str:
        return os.path.join(self.root, "staging", f"{epoch:020d}.json")

    def _committed(self, epoch: int) -> str:
        return os.path.join(self.root, "committed", f"{epoch:020d}.json")

    def prepare(self, rows, epoch: int) -> None:
        if os.path.exists(self._committed(epoch)):
            return  # already published: replayed prepare is a no-op
        tmp = self._staging(epoch) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                [
                    {
                        "pk": list(pk),
                        "row": list(row) if row is not None else None,
                        "op": int(op),
                    }
                    for pk, row, op in rows
                ],
                f,
            )
        os.replace(tmp, self._staging(epoch))

    def commit_prepared(self, epoch: int) -> None:
        if os.path.exists(self._committed(epoch)):
            return  # idempotent publish
        if not os.path.exists(self._staging(epoch)):
            raise RuntimeError(f"epoch {epoch} was never prepared")
        os.replace(self._staging(epoch), self._committed(epoch))

    def abort_prepared(self, epoch: int) -> None:
        try:
            os.unlink(self._staging(epoch))
        except FileNotFoundError:
            pass

    def committed_epochs(self) -> List[int]:
        return sorted(
            int(f.split(".")[0])
            for f in os.listdir(os.path.join(self.root, "committed"))
            if f.endswith(".json")
        )

    def read_committed(self, epoch: int):
        with open(self._committed(epoch)) as f:
            return [
                (
                    tuple(r["pk"]),
                    tuple(r["row"]) if r["row"] is not None else None,
                    r["op"],
                )
                for r in json.load(f)
            ]


class SinkCoordinator:
    """The meta-side coordinator (sink_coordination/coordinator
    analogue, single-writer form): drains the durable log into a
    TwoPhaseSink with exactly-once publish semantics. The drain loop
    IS LogSinker's (TwoPhaseSink adapts write_batch/commit to
    prepare/commit_prepared) — one loop, no drift."""

    def __init__(
        self,
        log_store: KvLogStore,
        sink: TwoPhaseSink,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        from risingwave_tpu.connectors.log_store import LogSinker

        self.log_store = log_store
        self.sink = sink
        self._sinker = LogSinker(log_store, sink)
        # transient prepare/commit failures (a flaky external
        # coordinator) retry the drain: both phases are idempotent
        # (re-prepare overwrites the stage; re-commit of a published
        # epoch is a no-op) and the consume offset advances only after
        # the external commit, so a retried drain continues exactly
        # where the failed attempt stopped — exactly-once holds
        self._retry = retry_policy or RetryPolicy.from_env()

    def recover(self) -> None:
        """Abort staged-but-unpublished epochs: replay will re-prepare
        them (possibly with different batch boundaries)."""
        for epoch in self.log_store.pending_epochs():
            self.sink.abort_prepared(epoch)

    def run_once(self, up_to: int) -> int:
        """Deliver pending epochs <= ``up_to`` (the DURABLE frontier —
        REQUIRED: publishing a not-yet-durable epoch that later rolls
        back would permanently strand its pre-rollback rows externally,
        since committed epochs are immune to re-prepare). Safe to crash
        anywhere and rerun; the offset advances after the external
        commit, and both phases are idempotent — so transient failures
        mid-drain simply retry (bounded by the policy's deadline).
        Returns epochs published across all attempts."""
        if up_to is None:
            raise ValueError(
                "SinkCoordinator.run_once requires the durable frontier"
            )
        # count delivered epochs from the offset frontier, not from the
        # attempts' return values: an attempt that delivers some epochs
        # and then flakes advanced the offset for those epochs — the
        # retried attempt resumes at the pending frontier, and the
        # frontier delta is the exact total across all attempts
        pending0 = [
            e for e in self.log_store.pending_epochs() if e <= up_to
        ]
        self._retry.run(
            lambda: self._sinker.run_once(up_to=up_to), op="sink2pc.drain"
        )
        still = [
            e for e in self.log_store.pending_epochs() if e <= up_to
        ]
        return len(pending0) - len(still)
