"""KV log store — durable sink decoupling.

Reference: src/stream/src/common/log_store_impl/kv_log_store/ (the
sink-decoupling log: sink output persists in state, a LogSinker
consumes it at its own pace; sink/log_store.rs traits).

Closes the held-batch window documented in connectors/sink.py: with
deliver_on_durable, a crash after the manifest persisted but before
the held batch was written LOST the batch (at-most-once). Here the
batch itself is durable — appended to a per-sink log in the object
store at the barrier — and a decoupled ``LogSinker`` delivers pending
epochs to the real sink, committing its consume offset afterwards:

- no batch is ever lost (the log IS state; recovery rolls the
  consumer offset back past discarded epochs so regenerated output is
  redelivered);
- delivery is at-least-once across crashes (offset commits after the
  sink write; the reference needs coordinated 2PC sinks for
  exactly-once external delivery, manager/sink_coordination/);
- drive ``LogSinker.run_once(up_to=<durable frontier>)`` to also
  guarantee rolled-back epochs are never delivered; without ``up_to``
  the sinker may run ahead of durability and deliver output of an
  epoch that later rolls back (still at-least-once, never lost).
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence, Tuple

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.connectors.sink import Sink, compact_rows
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.storage.object_store import ObjectStore


class KvLogStore:
    """Per-sink epoch log in the object store: one blob per epoch plus
    a consumer-offset blob. Appends are idempotent per epoch (replay
    of a deterministic epoch overwrites with identical content)."""

    def __init__(self, store: ObjectStore, sink_id: str):
        self.store = store
        self.prefix = f"sinklog/{sink_id}"

    def _epoch_path(self, epoch: int) -> str:
        return f"{self.prefix}/log/{epoch:020d}.json"

    def _offset_path(self) -> str:
        return f"{self.prefix}/OFFSET"

    def append(self, epoch: int, batch) -> None:
        rows = [
            {"pk": list(pk), "row": list(row) if row is not None else None,
             "op": int(op)}
            for pk, row, op in batch
        ]
        self.store.put(
            self._epoch_path(epoch), json.dumps(rows).encode()
        )

    def committed_offset(self) -> int:
        p = self._offset_path()
        if not self.store.exists(p):
            return 0
        return int(json.loads(self.store.read(p))["epoch"])

    def pending_epochs(self) -> List[int]:
        off = self.committed_offset()
        out = []
        for p in self.store.list(self.prefix + "/log/"):
            epoch = int(p.rsplit("/", 1)[1].split(".")[0])
            if epoch > off:
                out.append(epoch)
        return sorted(out)

    def read(self, epoch: int):
        rows = json.loads(self.store.read(self._epoch_path(epoch)))
        return [
            (tuple(r["pk"]),
             tuple(r["row"]) if r["row"] is not None else None,
             r["op"])
            for r in rows
        ]

    def commit_through(self, epoch: int) -> None:
        self.store.put(
            self._offset_path(), json.dumps({"epoch": epoch}).encode()
        )

    def truncate(self) -> None:
        """GC delivered epochs (kv log store truncation)."""
        off = self.committed_offset()
        for p in list(self.store.list(self.prefix + "/log/")):
            epoch = int(p.rsplit("/", 1)[1].split(".")[0])
            if epoch <= off:
                self.store.delete(p)

    def discard_above(self, epoch: int) -> None:
        """Recovery: epochs past the committed manifest rolled back;
        their logged output is discarded AND the consumer offset rolls
        back with them — replay regenerates those epochs (possibly with
        different batch boundaries), and an offset ahead of the rolled-
        back frontier would make pending_epochs() skip the regenerated
        output forever (batch loss)."""
        for p in list(self.store.list(self.prefix + "/log/")):
            e = int(p.rsplit("/", 1)[1].split(".")[0])
            if e > epoch:
                self.store.delete(p)
        if self.committed_offset() > epoch:
            self.commit_through(epoch)


class LogStoreSinkExecutor(Executor):
    """Chain-tail sink writing through a KvLogStore (executor/sink.rs
    with sink decoupling ON): the barrier appends the epoch's compacted
    batch to the durable log; the decoupled LogSinker delivers."""

    def __init__(
        self, log_store: KvLogStore, pk: Sequence[str], columns: Sequence[str]
    ):
        self.log_store = log_store
        self.pk = tuple(pk)
        self.columns = tuple(columns)
        self._buffer: List[Tuple[Tuple, Tuple, int]] = []
        self._finish_queue: List[Tuple[int, list]] = []

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        from risingwave_tpu.connectors.sink import rows_from_chunk

        self._buffer.extend(rows_from_chunk(chunk, self.pk, self.columns))
        return [chunk]

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        if barrier is None:
            # the log is keyed by epoch; a direct drive has none, and
            # silently dropping the batch would be data loss — fail loud
            raise ValueError(
                "LogStoreSinkExecutor requires a real epoch: drive it "
                "through a runtime barrier, not on_barrier(None)"
            )
        # leftovers mean the previous finish walk ABORTED (an upstream
        # latch raised): those epochs rolled back — never log them
        self._finish_queue = []
        batch = compact_rows(self._buffer)
        self._buffer = []
        if batch or barrier.checkpoint:
            # persist in finish_barrier: an upstream latch (corrupt
            # epoch) raises from ITS finish before this blob is written
            self._finish_queue.append((barrier.epoch.curr, batch))
        return []

    def finish_barrier(self) -> None:
        due, self._finish_queue = self._finish_queue, []
        for epoch, batch in due:
            self.log_store.append(epoch, batch)

    def discard_pending(self) -> None:
        self._buffer = []
        self._finish_queue = []

    def on_recover(self, epoch: int) -> None:
        """Runtime recovery hook: drop logged output of rolled-back
        epochs (they will be regenerated by replay)."""
        self.log_store.discard_above(epoch)


class LogSinker:
    """The decoupled consumer (sink/log_store.rs LogSinker role):
    drains pending epochs into the real sink at its own pace — the
    stream never blocks on a slow external system."""

    def __init__(self, log_store: KvLogStore, sink: Sink):
        self.log_store = log_store
        self.sink = sink

    def run_once(self, up_to: Optional[int] = None) -> int:
        """Deliver pending epochs (optionally only those <= up_to,
        i.e. the durable frontier). Returns epochs delivered."""
        n = 0
        for epoch in self.log_store.pending_epochs():
            if up_to is not None and epoch > up_to:
                break
            self.sink.write_batch(self.log_store.read(epoch), epoch)
            self.sink.commit(epoch)
            self.log_store.commit_through(epoch)
            n += 1
        self.log_store.truncate()
        return n
