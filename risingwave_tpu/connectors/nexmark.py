"""Nexmark event generator source — the benchmark workhorse.

Reference: src/connector/src/source/nexmark/source/reader.rs:42 (the
SplitReader wrapping the `nexmark` crate's EventGenerator) and the
public Nexmark generator semantics that crate implements:

- events cycle deterministically 1 person : 3 auctions : 46 bids per
  50-event epoch;
- person/auction ids chain off the event number (last_base0_* formulas)
  so every bid references an auction/person that has already been
  generated — this is what makes q8-style stream joins meaningful;
- hot-key skew: most bids target the most recent "hot" auctions /
  bidders (1/hot_ratio of ids), matching real auction traffic;
- event timestamps advance at a configured inter-event gap, giving a
  controllable events/sec rate.

TPU re-design: generation is fully vectorized numpy (no per-event
objects); a batch of N event indices becomes three compacted column
sets (persons / auctions / bids) handed to the pipeline as fixed-
capacity StreamChunks. Splits partition the event-index space round-
robin exactly like the reference's split_index/split_num
(reader.rs:78-84), so multi-split generation is reproducible.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.array.dictionary import StringDictionary
from risingwave_tpu.types import DataType, Schema

# proportions fixed by the Nexmark spec
PERSON_PROPORTION = 1
AUCTION_PROPORTION = 3
BID_PROPORTION = 46
PROPORTION_DENOMINATOR = PERSON_PROPORTION + AUCTION_PROPORTION + BID_PROPORTION

FIRST_PERSON_ID = 1000
FIRST_AUCTION_ID = 1000
FIRST_CATEGORY_ID = 10

PERSON_SCHEMA = Schema(
    [
        ("id", DataType.INT64),
        ("name", DataType.VARCHAR),
        ("city", DataType.VARCHAR),
        ("state", DataType.VARCHAR),
        ("date_time", DataType.TIMESTAMP),
    ]
)

AUCTION_SCHEMA = Schema(
    [
        ("id", DataType.INT64),
        ("item_name", DataType.VARCHAR),
        ("initial_bid", DataType.INT64),
        ("reserve", DataType.INT64),
        ("date_time", DataType.TIMESTAMP),
        ("expires", DataType.TIMESTAMP),
        ("seller", DataType.INT64),
        ("category", DataType.INT64),
    ]
)

BID_SCHEMA = Schema(
    [
        ("auction", DataType.INT64),
        ("bidder", DataType.INT64),
        ("price", DataType.INT64),
        ("channel", DataType.VARCHAR),
        ("date_time", DataType.TIMESTAMP),
    ]
)

_CHANNELS = ["Google", "Facebook", "Baidu", "Apple"]
_CITIES = ["Phoenix", "Los Angeles", "San Francisco", "Boise", "Portland",
           "Bend", "Redmond", "Seattle", "Kent", "Cheyenne"]
_STATES = ["AZ", "CA", "ID", "OR", "WA", "WY"]
_FIRST = ["Peter", "Paul", "Luke", "John", "Saul", "Vicky", "Kate", "Julie",
          "Sarah", "Deiter", "Walter"]
_LAST = ["Shultz", "Abrams", "Spencer", "White", "Bartels", "Walton", "Smith",
         "Jones", "Noris"]


@dataclass
class NexmarkConfig:
    """Generator knobs (subset of the crate's NexmarkConfig that the
    benchmark queries exercise; defaults mirror the spec)."""

    first_event_rate: int = 10_000  # events/sec
    base_time_ms: int = 1_436_918_400_000  # spec BASE_TIME
    hot_auction_ratio: int = 2
    hot_bidder_ratio: int = 4
    hot_seller_ratio: int = 4
    num_active_people: int = 1000
    num_in_flight_auctions: int = 100
    auction_duration_ms: int = 10_000


_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — the counter-based RNG core: randomness is
    a PURE function of (seed, split, event ordinal, use-site), so the
    stream is identical no matter how generation is batched (offset
    resume replays exactly; code-review r2 finding #6)."""
    x = (x + np.uint64(0x9E3779B97F4A7C15)) & _M64
    x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) & _M64
    x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) & _M64
    return x ^ (x >> np.uint64(31))


def _last_base0_person_id(event_id: np.ndarray) -> np.ndarray:
    epoch = event_id // PROPORTION_DENOMINATOR
    offset = event_id % PROPORTION_DENOMINATOR
    offset = np.minimum(offset, PERSON_PROPORTION - 1)
    return epoch * PERSON_PROPORTION + offset


def _last_base0_auction_id(event_id: np.ndarray) -> np.ndarray:
    epoch = event_id // PROPORTION_DENOMINATOR
    offset = event_id % PROPORTION_DENOMINATOR
    before = offset < PERSON_PROPORTION
    epoch = np.where(before, epoch - 1, epoch)
    offset = np.where(
        before,
        AUCTION_PROPORTION - 1,
        np.where(
            offset >= PERSON_PROPORTION + AUCTION_PROPORTION,
            AUCTION_PROPORTION - 1,
            offset - PERSON_PROPORTION,
        ),
    )
    return epoch * AUCTION_PROPORTION + offset


class NexmarkGenerator:
    """Deterministic, seedable, vectorized event generator for one split."""

    def __init__(
        self,
        config: Optional[NexmarkConfig] = None,
        split_index: int = 0,
        split_num: int = 1,
        seed: int = 42,
        dictionaries: Optional[Dict[str, StringDictionary]] = None,
    ):
        self.config = config if config is not None else NexmarkConfig()
        self.split_index = split_index
        self.split_num = split_num
        self.seed = seed
        self._next_ordinal = 0  # ordinal within this split
        # VARCHAR codes are only equality-complete if every split shares
        # ONE dictionary set; private per-split dictionaries would assign
        # diverging codes to the same string and silently break
        # cross-split group-by/join. Build them via make_dictionaries()
        # and pass to every split.
        if dictionaries is None and split_num > 1:
            raise ValueError(
                "multi-split generation requires a shared `dictionaries` "
                "set (use NexmarkGenerator.make_dictionaries())"
            )
        self.dicts = (
            dictionaries if dictionaries is not None else self.make_dictionaries()
        )
        # pre-encode the small vocabularies so codes are dense & stable
        self._city_codes = self.dicts["city"].encode(_CITIES)
        self._state_codes = self.dicts["state"].encode(_STATES)
        self._chan_codes = self.dicts["channel"].encode(_CHANNELS)
        self._name_codes = self.dicts["name"].encode(
            [f"{f} {l}" for f in _FIRST for l in _LAST]
        )
        self._item_codes = self.dicts["item_name"].encode(
            [f"item-{c}" for c in range(997)]
        )

    def _h(self, eid: np.ndarray, site: int) -> np.ndarray:
        """64 random bits per EVENT for one use site."""
        seed_mix = (self.seed * 0xC2B2AE3D27D4EB4F) & 0xFFFFFFFFFFFFFFFF
        salt = (seed_mix ^ (site << 32)) & 0xFFFFFFFFFFFFFFFF
        x = eid.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
        return _mix64(x ^ np.uint64(salt))

    def _randbelow(self, eid: np.ndarray, site: int, n) -> np.ndarray:
        return (self._h(eid, site) % np.asarray(n).astype(np.uint64)).astype(
            np.int64
        )

    def _u01(self, eid: np.ndarray, site: int) -> np.ndarray:
        return (self._h(eid, site) >> np.uint64(11)) * (2.0 ** -53)

    @staticmethod
    def make_dictionaries() -> Dict[str, StringDictionary]:
        return {
            "name": StringDictionary(),
            "city": StringDictionary(),
            "state": StringDictionary(),
            "item_name": StringDictionary(),
            "channel": StringDictionary(),
        }

    # -- core ------------------------------------------------------------
    def next_events(self, count: int) -> Dict[str, Dict[str, np.ndarray]]:
        """Generate the next ``count`` events of this split, compacted
        into three column dicts: {"person": {...}, "auction": {...},
        "bid": {...}} (any may be empty)."""
        cfg = self.config
        ordinals = self._next_ordinal + np.arange(count, dtype=np.int64)
        self._next_ordinal += count
        # round-robin split partition of the global event-index space
        event_ids = ordinals * self.split_num + self.split_index
        rem = event_ids % PROPORTION_DENOMINATOR
        # ms timestamps advancing at the configured rate
        ts = cfg.base_time_ms + (event_ids * 1000) // cfg.first_event_rate

        is_person = rem < PERSON_PROPORTION
        is_auction = (~is_person) & (rem < PERSON_PROPORTION + AUCTION_PROPORTION)
        is_bid = ~is_person & ~is_auction

        out = {
            "person": self._persons(event_ids[is_person], ts[is_person]),
            "auction": self._auctions(event_ids[is_auction], ts[is_auction]),
            "bid": self._bids(event_ids[is_bid], ts[is_bid]),
        }
        return out

    def _persons(self, eid: np.ndarray, ts: np.ndarray):
        n = len(eid)
        pid = _last_base0_person_id(eid) + FIRST_PERSON_ID
        return {
            "id": pid,
            "name": self._name_codes[
                self._randbelow(eid, 1, len(self._name_codes))
            ].astype(np.int32),
            "city": self._city_codes[
                self._randbelow(eid, 2, len(self._city_codes))
            ].astype(np.int32),
            "state": self._state_codes[
                self._randbelow(eid, 3, len(self._state_codes))
            ].astype(np.int32),
            "date_time": ts,
        }

    def _auctions(self, eid: np.ndarray, ts: np.ndarray):
        n = len(eid)
        cfg = self.config
        aid = _last_base0_auction_id(eid) + FIRST_AUCTION_ID
        # seller: mostly the most recent "hot" person, else a recent one
        last_p = _last_base0_person_id(eid)
        hot = self._randbelow(eid, 4, cfg.hot_seller_ratio) > 0
        hot_seller = (last_p // cfg.hot_seller_ratio) * cfg.hot_seller_ratio
        active = np.minimum(last_p + 1, cfg.num_active_people)
        cold_seller = last_p - self._randbelow(eid, 5, np.maximum(active, 1))
        seller = np.where(hot, hot_seller, cold_seller) + FIRST_PERSON_ID
        initial = self._price(eid, 6)
        item = self._item_codes[aid % 997]
        return {
            "id": aid,
            "item_name": item.astype(np.int32),
            "initial_bid": initial,
            "reserve": initial + self._price(eid, 7) // 10,
            "date_time": ts,
            "expires": ts + cfg.auction_duration_ms,
            "seller": seller,
            "category": FIRST_CATEGORY_ID + self._randbelow(eid, 8, 5),
        }

    def _bids(self, eid: np.ndarray, ts: np.ndarray):
        n = len(eid)
        cfg = self.config
        last_a = _last_base0_auction_id(eid)
        hot_a = self._randbelow(eid, 9, cfg.hot_auction_ratio) > 0
        hot_auction = (last_a // cfg.hot_auction_ratio) * cfg.hot_auction_ratio
        in_flight = np.maximum(np.minimum(last_a + 1, cfg.num_in_flight_auctions), 1)
        cold_auction = last_a - self._randbelow(eid, 10, in_flight)
        auction = np.where(hot_a, hot_auction, cold_auction) + FIRST_AUCTION_ID

        last_p = _last_base0_person_id(eid)
        hot_b = self._randbelow(eid, 11, cfg.hot_bidder_ratio) > 0
        hot_bidder = (last_p // cfg.hot_bidder_ratio) * cfg.hot_bidder_ratio + 1
        active = np.maximum(np.minimum(last_p + 1, cfg.num_active_people), 1)
        cold_bidder = last_p - self._randbelow(eid, 12, active)
        bidder = np.where(hot_b, hot_bidder, cold_bidder) + FIRST_PERSON_ID

        return {
            "auction": auction,
            "bidder": bidder,
            "price": self._price(eid, 13),
            "channel": self._chan_codes[
                self._randbelow(eid, 14, len(self._chan_codes))
            ].astype(np.int32),
            "date_time": ts,
        }

    def _price(self, eid: np.ndarray, site: int) -> np.ndarray:
        """Spec price distribution: round(10^(U[0,1)*6) * 100) cents."""
        return np.round(
            np.power(10.0, self._u01(eid, site) * 6.0) * 100.0
        ).astype(np.int64)

    # -- chunk-producing source edge ------------------------------------
    # -- seekable-split offset API (reader.rs:42 offset semantics) ------
    @property
    def offset(self) -> int:
        return self._next_ordinal

    def seek(self, offset: int) -> None:
        self._next_ordinal = int(offset)

    def next_chunks(
        self, count: int, capacity: int
    ) -> Dict[str, Optional[StreamChunk]]:
        """Generate ``count`` events as per-stream fixed-capacity
        StreamChunks (None where the batch produced no such events).

        ``capacity`` must cover the worst-case per-type yield:
        ceil(count * 46/50) for bids.
        """
        events = self.next_events(count)
        out = {}
        for stream, schema in (
            ("person", PERSON_SCHEMA),
            ("auction", AUCTION_SCHEMA),
            ("bid", BID_SCHEMA),
        ):
            cols = events[stream]
            n = len(next(iter(cols.values()))) if cols else 0
            if n == 0:
                out[stream] = None
                continue
            out[stream] = StreamChunk.from_numpy(cols, capacity, schema=schema)
        return out
