"""Sink framework — the stream's exit edge.

Reference: src/connector/src/sink/ (``Sink``/``SinkWriter`` traits,
sink/mod.rs:337, writer.rs:35), ``trivial.rs`` blackhole, and
``common/compact_chunk.rs`` (collapse +/- churn per pk before
emitting downstream systems).

v0 scope: blackhole + local file (jsonl) sinks behind a SinkExecutor
with per-pk chunk compaction; epoch-batched delivery commits at
barrier (the decoupled log-store path arrives with the network edge).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.array.dictionary import StringDictionary
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.types import Op


def compact_rows(rows: List[Tuple[Tuple, Tuple, int]]) -> List[Tuple[Tuple, Tuple, int]]:
    """Collapse a barrier's buffered (pk, row, op) sequence to the net
    effect per pk (compact_chunk.rs): the last surviving state wins —
    insert+delete cancels, delete+insert becomes an update pair."""
    first_op: Dict[Tuple, int] = {}
    last: Dict[Tuple, Optional[Tuple]] = {}
    order: List[Tuple] = []
    for pk, row, op in rows:
        if pk not in last:
            order.append(pk)
            first_op[pk] = op
        if op in (Op.DELETE, Op.UPDATE_DELETE):
            last[pk] = None
        else:
            last[pk] = row
    out: List[Tuple[Tuple, Tuple, int]] = []
    for pk in order:
        row = last[pk]
        came_in_as_insert = first_op[pk] in (Op.INSERT, Op.UPDATE_INSERT)
        if row is None:
            if not came_in_as_insert:
                # existed before the barrier, gone now -> delete
                out.append((pk, None, Op.DELETE))
            # else: appeared and vanished within the epoch -> nothing
        else:
            out.append((pk, row, Op.INSERT))
    return out


class Sink:
    """Reference ``Sink`` trait narrowed to the epoch-batched path."""

    def write_batch(self, rows, epoch: int) -> None:
        raise NotImplementedError

    def commit(self, epoch: int) -> None:
        pass


class BlackholeSink(Sink):
    """sink/trivial.rs — counts and drops."""

    def __init__(self):
        self.rows_written = 0
        self.commits = 0

    def write_batch(self, rows, epoch: int) -> None:
        self.rows_written += len(rows)

    def commit(self, epoch: int) -> None:
        self.commits += 1


class FileSink(Sink):
    """Append-only jsonl file sink with epoch markers; VARCHAR columns
    decode through their dictionary when provided."""

    def __init__(
        self,
        path: str,
        columns: Sequence[str],
        dictionaries: Optional[Dict[str, StringDictionary]] = None,
    ):
        self.path = path
        self.columns = tuple(columns)
        self.dicts = dictionaries or {}
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1 << 16)

    def write_batch(self, rows, epoch: int) -> None:
        for pk, row, op in rows:
            if row is None:
                rec = {"op": "delete", "pk": list(pk)}
            else:
                vals = []
                for name, v in zip(self.columns, row):
                    d = self.dicts.get(name)
                    vals.append(d.decode_one(int(v)) if d is not None else v)
                rec = {"op": "insert", "pk": list(pk), "row": vals}
            self._f.write(json.dumps(rec, default=int) + "\n")

    def commit(self, epoch: int) -> None:
        self._f.write(json.dumps({"op": "commit", "epoch": epoch}) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self):
        self._f.close()


class SinkExecutor(Executor):
    """Chain-tail executor: buffers the epoch's deltas, compacts per
    pk at the barrier, delivers one batch, commits (reference:
    executor/sink.rs:40 + compact_chunk re-ordering)."""

    def __init__(self, sink: Sink, pk: Sequence[str], columns: Sequence[str]):
        self.sink = sink
        self.pk = tuple(pk)
        self.columns = tuple(columns)
        self._buffer: List[Tuple[Tuple, Tuple, int]] = []

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        d = chunk.to_numpy(with_ops=True)
        ops = d["__op__"]
        for i in range(len(ops)):
            pk = tuple(d[n][i].item() for n in self.pk)
            row = tuple(d[n][i].item() for n in self.columns)
            self._buffer.append((pk, row, int(ops[i])))
        return [chunk]

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        batch = compact_rows(self._buffer)
        self._buffer = []
        epoch = barrier.epoch.curr if barrier else 0
        self.sink.write_batch(batch, epoch)
        if barrier is None or barrier.checkpoint:
            self.sink.commit(epoch)
        return []
