"""Sink framework — the stream's exit edge.

Reference: src/connector/src/sink/ (``Sink``/``SinkWriter`` traits,
sink/mod.rs:337, writer.rs:35), ``trivial.rs`` blackhole, and
``common/compact_chunk.rs`` (collapse +/- churn per pk before
emitting downstream systems).

v0 scope: blackhole + local file (jsonl) sinks behind a SinkExecutor
with per-pk chunk compaction; epoch-batched delivery commits at
barrier (the decoupled log-store path arrives with the network edge).
"""

from __future__ import annotations

import json
import os
import threading as _threading
from typing import Dict, List, Optional, Sequence, Tuple


from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.array.dictionary import StringDictionary
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.types import Op


def compact_rows(rows: List[Tuple[Tuple, Tuple, int]]) -> List[Tuple[Tuple, Tuple, int]]:
    """Collapse a barrier's buffered (pk, row, op) sequence to the net
    effect per pk (compact_chunk.rs): the last surviving state wins —
    insert+delete cancels, delete+insert becomes an update pair."""
    first_op: Dict[Tuple, int] = {}
    last: Dict[Tuple, Optional[Tuple]] = {}
    order: List[Tuple] = []
    for pk, row, op in rows:
        if pk not in last:
            order.append(pk)
            first_op[pk] = op
        if op in (Op.DELETE, Op.UPDATE_DELETE):
            last[pk] = None
        else:
            last[pk] = row
    out: List[Tuple[Tuple, Tuple, int]] = []
    for pk in order:
        row = last[pk]
        came_in_as_insert = first_op[pk] in (Op.INSERT, Op.UPDATE_INSERT)
        if row is None:
            if not came_in_as_insert:
                # existed before the barrier, gone now -> delete
                out.append((pk, None, Op.DELETE))
            # else: appeared and vanished within the epoch -> nothing
        else:
            out.append((pk, row, Op.INSERT))
    return out


def rows_from_chunk(chunk: StreamChunk, pk, columns):
    """Chunk -> [(pk_tuple, row_tuple, op)] — the single host-side row
    extraction shared by every sink executor."""
    d = chunk.to_numpy(with_ops=True)
    ops = d["__op__"]
    out = []
    for i in range(len(ops)):
        out.append(
            (
                tuple(d[n][i].item() for n in pk),
                tuple(d[n][i].item() for n in columns),
                int(ops[i]),
            )
        )
    return out


class Sink:
    """Reference ``Sink`` trait narrowed to the epoch-batched path."""

    def write_batch(self, rows, epoch: int) -> None:
        raise NotImplementedError

    def commit(self, epoch: int) -> None:
        pass


class BlackholeSink(Sink):
    """sink/trivial.rs — counts and drops."""

    def __init__(self):
        self.rows_written = 0
        self.commits = 0

    def write_batch(self, rows, epoch: int) -> None:
        self.rows_written += len(rows)

    def commit(self, epoch: int) -> None:
        self.commits += 1


class FileSink(Sink):
    """Append-only jsonl file sink with epoch markers; VARCHAR columns
    decode through their dictionary when provided."""

    def __init__(
        self,
        path: str,
        columns: Sequence[str],
        dictionaries: Optional[Dict[str, StringDictionary]] = None,
    ):
        self.path = path
        self.columns = tuple(columns)
        self.dicts = dictionaries or {}
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1 << 16)

    def write_batch(self, rows, epoch: int) -> None:
        for pk, row, op in rows:
            if row is None:
                rec = {"op": "delete", "pk": list(pk)}
            else:
                vals = []
                for name, v in zip(self.columns, row):
                    d = self.dicts.get(name)
                    vals.append(d.decode_one(int(v)) if d is not None else v)
                rec = {"op": "insert", "pk": list(pk), "row": vals}
            self._f.write(json.dumps(rec, default=int) + "\n")

    def commit(self, epoch: int) -> None:
        self._f.write(json.dumps({"op": "commit", "epoch": epoch}) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self):
        self._f.close()


class SinkExecutor(Executor):
    """Chain-tail executor: buffers the epoch's deltas, compacts per
    pk at the barrier, delivers one batch, commits (reference:
    executor/sink.rs:40 + compact_chunk re-ordering).

    Delivery modes:
    - standalone (no runtime/store): write + commit at the barrier —
      there is no durability boundary to respect;
    - ``deliver_on_durable`` (set by StreamingRuntime.register when a
      checkpoint store exists): batches are held per epoch and only
      written + committed by ``on_epoch_durable`` once that epoch's
      manifest persisted — a crash in the window between barrier and
      manifest can no longer duplicate sink output on replay (ADVICE
      r2 medium).

    Contract (documented, not glossed): deferred delivery is
    exactly-once for in-process failures (failed epochs are discarded
    and regenerated by replay), but AT-MOST-ONCE across a process
    crash inside the narrow window after the manifest persists and
    before the held batch is written — state recovery resumes past
    that epoch, so the batch is not regenerated. Closing that window
    needs the reference's persisted sink log store
    (common/log_store_impl/kv_log_store, executor/sink.rs:40), which
    is the planned escalation path.
    """

    def __init__(self, sink: Sink, pk: Sequence[str], columns: Sequence[str]):
        self.sink = sink
        self.pk = tuple(pk)
        self.columns = tuple(columns)
        self._buffer: List[Tuple[Tuple, Tuple, int]] = []
        self.deliver_on_durable = False
        # held batches are appended by the barrier (main thread) and
        # drained by on_epoch_durable (async checkpoint worker)
        self._held_lock = _threading.Lock()
        self._held: List[Tuple[int, List[Tuple[Tuple, Tuple, int]]]] = []
        self._finish_queue: List[Tuple[int, list, bool]] = []

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        self._buffer.extend(rows_from_chunk(chunk, self.pk, self.columns))
        return [chunk]

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        batch = compact_rows(self._buffer)
        self._buffer = []
        epoch = barrier.epoch.curr if barrier else 0
        if self.deliver_on_durable:
            with self._held_lock:
                self._held.append((epoch, batch))
            return []
        # standalone delivery happens in finish_barrier, which the
        # pipeline runs in executor order AFTER the walk: an upstream
        # latch (overflow/inconsistency) raises from ITS finish before
        # this sink externally commits the corrupt epoch
        self._finish_queue.append(
            (epoch, batch, barrier is None or barrier.checkpoint)
        )
        return []

    def finish_barrier(self) -> None:
        due, self._finish_queue = self._finish_queue, []
        for epoch, batch, commit in due:
            self.sink.write_batch(batch, epoch)
            if commit:
                self.sink.commit(epoch)

    def discard_pending(self) -> None:
        """Recovery hook: drop batches held for epochs that rolled back
        (replay will regenerate them; keeping both would double-write)."""
        with self._held_lock:
            self._held = []
        self._buffer = []
        self._finish_queue = []

    def on_epoch_durable(self, epoch: int) -> None:
        """Runtime callback after the manifest persisted for ``epoch``:
        flush every held batch up to it, then commit."""
        if not self.deliver_on_durable:
            return
        with self._held_lock:
            due = [(ep, b) for ep, b in self._held if ep <= epoch]
            self._held = [(ep, b) for ep, b in self._held if ep > epoch]
        for ep, batch in due:
            self.sink.write_batch(batch, ep)
        if due:
            self.sink.commit(epoch)
