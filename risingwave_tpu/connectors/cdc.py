"""CDC backfill — consistent snapshot + change-stream switchover.

Reference: src/stream/src/executor/backfill/cdc/ — ingesting an
external database table needs BOTH its existing rows (a pk-ordered
snapshot scan) and its ongoing change stream, without losing or
double-applying rows that change DURING the scan. The reference's
algorithm, kept intact here:

- scan the external table in pk order, chunk by chunk, tracking the
  backfill position (highest pk emitted);
- concurrently drain the change log: an event whose pk is <= the
  position applies (that region is already downstream); an event
  BEYOND the position drops — the later snapshot read returns the
  post-change row, so applying both would double-count;
- when the scan is exhausted, backfill is done and every change event
  flows.

Progress (pk position + change-log offset + done flag) is
checkpointable, so recovery resumes the scan exactly (reference keeps
per-table cdc progress state the same way).

TPU re-design note: the scan emits columnar chunks sized for the
device path; the pk-position comparison is host-side (the change log
is a host stream anyway — device work starts downstream).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.array.composite import encode_column
from risingwave_tpu.storage.state_table import Checkpointable, StateDelta
from risingwave_tpu.types import Schema


class ExternalTable:
    """The upstream database table surface the backfill scans
    (reference: the external table reader over JDBC/debezium). A
    snapshot read returns CURRENT rows with pk > from_pk, pk-ordered.
    """

    def __init__(self, schema: Schema, pk_col: str):
        self.schema = schema
        self.pk_col = pk_col
        self.rows: Dict[int, tuple] = {}  # pk -> full row tuple

    def upsert(self, row: Sequence) -> None:
        self.rows[int(row[self.schema.names.index(self.pk_col)])] = tuple(
            row
        )

    def delete(self, pk: int) -> None:
        self.rows.pop(int(pk), None)

    def snapshot_read(self, from_pk: Optional[int], limit: int):
        """Rows with pk > from_pk in pk order (live state — the
        reference reads each snapshot chunk at current time too)."""
        pks = sorted(k for k in self.rows if from_pk is None or k > from_pk)
        take = pks[:limit]
        return [self.rows[k] for k in take], (take[-1] if take else None)


class CdcBackfillExecutor(Checkpointable):
    """(external table, change-log connector+parser) -> one combined
    chunk stream with the reference's backfill/stream merge rule."""

    def __init__(
        self,
        table: ExternalTable,
        log_connector,  # SplitEnumerator & SplitReader (change events)
        change_parser,  # ChangeParser (e.g. DebeziumJsonParser)
        table_id: str = "cdc.backfill",
        strings=None,
    ):
        self.table = table
        self.connector = log_connector
        self.parser = change_parser
        self.table_id = table_id
        self.strings = strings
        self.schema = table.schema
        self._pk_idx = self.schema.names.index(table.pk_col)
        self.pk_pos: Optional[int] = None  # highest backfilled pk
        self.done = False
        self.offsets: Dict[str, int] = {}
        self._committed = (None, False, {})

    # -- polling -----------------------------------------------------------
    def _encode(self, rows, ops=None, capacity=1 << 12) -> List[StreamChunk]:
        out = []
        for at in range(0, len(rows), capacity):
            part = rows[at : at + capacity]
            lanes: Dict[str, np.ndarray] = {}
            nulls: Dict[str, np.ndarray] = {}
            for j, f in enumerate(self.schema.fields):
                cl, cn = encode_column(
                    f, [r[j] for r in part], self.strings
                )
                lanes.update(cl)
                if cn:
                    nulls.update(cn)
            ops_arr = (
                np.asarray(ops[at : at + capacity], np.int32)
                if ops is not None
                else None
            )
            out.append(
                StreamChunk.from_numpy(
                    lanes, capacity, ops=ops_arr, nulls=nulls or None
                )
            )
        return out

    def poll(
        self, snapshot_rows: int = 1024, capacity: int = 1 << 12
    ) -> List[StreamChunk]:
        """One round: a snapshot batch (while backfilling) + the change
        log drained under the merge rule."""
        out: List[StreamChunk] = []
        if not self.done:
            rows, last = self.table.snapshot_read(
                self.pk_pos, snapshot_rows
            )
            if rows:
                out.extend(self._encode(rows, capacity=capacity))
                self.pk_pos = last
            else:
                self.done = True  # scan exhausted: pure streaming now
        # change log: apply events in the backfilled region only
        for split in self.connector.list_splits():
            sid = split.split_id
            raw, new_off = self.connector.read(
                split, self.offsets.get(sid, 0), 1 << 16
            )
            pairs = [
                p for r in raw for p in self.parser.parse_changes(r)
            ]
            keep_rows, keep_ops = [], []
            for op, row in pairs:
                pk = row[self._pk_idx]
                if not self.done and (
                    self.pk_pos is None
                    or pk is None
                    or int(pk) > self.pk_pos
                ):
                    # beyond the backfill frontier: the snapshot will
                    # (or did not yet) cover this pk — drop the event
                    continue
                keep_rows.append(row)
                keep_ops.append(op)
            if keep_rows:
                out.extend(
                    self._encode(keep_rows, keep_ops, capacity=capacity)
                )
            self.offsets[sid] = new_off
        return out

    # -- integrity ---------------------------------------------------------
    def state_digest(self) -> int:
        """Durable logical state: backfill cursor + upstream offsets."""
        from risingwave_tpu.integrity import host_obj_digest

        return host_obj_digest(
            {
                "pk_pos": self.pk_pos,
                "done": self.done,
                "offsets": dict(self.offsets),
            }
        )

    # -- checkpoint --------------------------------------------------------
    def checkpoint_delta(self) -> List[StateDelta]:
        cur = (self.pk_pos, self.done, dict(self.offsets))
        if cur == self._committed:
            return []
        self._committed = cur
        sids = sorted(self.offsets)
        n = 1 + len(sids)
        return [
            StateDelta(
                self.table_id,
                {"k": np.arange(n, dtype=np.int64)},
                {
                    "pos": np.asarray(
                        [-1 if self.pk_pos is None else self.pk_pos]
                        + [self.offsets[s] for s in sids],
                        np.int64,
                    ),
                    "done": np.asarray(
                        [int(self.done)] + [0] * len(sids), np.int64
                    ),
                    "sid": np.asarray(
                        [-1] + [int(s) for s in sids], np.int64
                    ),
                },
                np.zeros(n, bool),
                ("k",),
            )
        ]

    def staged_or_live_delta(self) -> List[StateDelta]:
        return self.checkpoint_delta()

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        if not key_cols:
            return
        order = np.argsort(np.asarray(key_cols["k"]))
        pos = np.asarray(value_cols["pos"])[order]
        done = np.asarray(value_cols["done"])[order]
        sid = np.asarray(value_cols["sid"])[order]
        self.pk_pos = None if int(pos[0]) < 0 else int(pos[0])
        self.done = bool(done[0])
        self.offsets = {
            str(int(s)): int(p) for s, p in zip(sid[1:], pos[1:])
        }
        self._committed = (self.pk_pos, self.done, dict(self.offsets))
