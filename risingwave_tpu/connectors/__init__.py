"""Connector sources/sinks (reference: src/connector/).

v0 scope: the Nexmark generator source (the benchmark workhorse,
reference src/connector/src/source/nexmark/) and a datagen-style random
source; external systems (Kafka etc.) are out of scope until the
network edge exists.
"""

from risingwave_tpu.connectors.nexmark import NexmarkConfig, NexmarkGenerator
from risingwave_tpu.connectors.source import NexmarkSourceExecutor

__all__ = ["NexmarkConfig", "NexmarkGenerator", "NexmarkSourceExecutor"]
