"""Connector sources/sinks (reference: src/connector/).

v0 scope: the Nexmark generator source (the benchmark workhorse,
reference src/connector/src/source/nexmark/) and a datagen-style random
source; external systems (Kafka etc.) are out of scope until the
network edge exists.
"""
