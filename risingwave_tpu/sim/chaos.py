"""Crash-injecting object store + kill/recover chaos runner.

Reference model: the madsim simulation tier kills arbitrary nodes at
arbitrary times and asserts the cluster converges to the same result
as an undisturbed run (src/tests/simulation/tests/integration_tests/
recovery/). Here the unit of failure is the process: a crash abandons
all live state mid-operation; durability is exactly what the object
store holds. Recovery = rebuild executors + ``CheckpointManager.
recover`` + source offsets resume (exactly-once's two halves).
"""

from __future__ import annotations

import random
from typing import Callable, Optional

from risingwave_tpu.storage.object_store import MemObjectStore, ObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager


class CrashPoint(BaseException):
    """The injected process death (BaseException: nothing may catch and
    'handle' a crash on the way out)."""


class CrashingStore(ObjectStore):
    """Wraps the durable store; ``arm(n)`` makes the n-th subsequent
    write raise CrashPoint and poisons every later write — the process
    is dead; only ``inner``'s already-committed bytes survive."""

    def __init__(self, inner: ObjectStore):
        self.inner = inner
        self._countdown: Optional[int] = None
        self.dead = False

    def arm(self, nth_write: int) -> None:
        self._countdown = nth_write

    def _write_gate(self):
        if self.dead:
            raise CrashPoint("process already dead")
        if self._countdown is not None:
            self._countdown -= 1
            if self._countdown <= 0:
                self.dead = True
                self._countdown = None
                raise CrashPoint("injected crash at write")

    def put(self, path: str, data: bytes) -> None:
        self._write_gate()
        self.inner.put(path, data)

    def delete(self, path: str) -> None:
        self._write_gate()
        self.inner.delete(path)

    def read(self, path: str) -> bytes:
        return self.inner.read(path)

    def exists(self, path: str) -> bool:
        return self.inner.exists(path)

    def list(self, prefix: str):
        return self.inner.list(prefix)


class ChaosRunner:
    """Run a build+feed workload for ``n_epochs`` COMMITTED epochs with
    seeded random crashes; compare against an undisturbed twin outside.

    ``make()`` returns a fresh workload object exposing ``executors``
    (incl. its source, so offsets checkpoint+restore) and is driven by
    ``feed(obj)`` for one epoch's data+barrier (NO commit — the runner
    owns commits so it can crash them). Epoch numbers encode the
    committed count, so recovery knows where to resume.
    """

    def __init__(
        self,
        make: Callable[[], object],
        feed: Callable[[object], None],
        seed: int = 0,
        crash_prob: float = 0.25,
        disk: Optional[ObjectStore] = None,
    ):
        self.make = make
        self.feed = feed
        self.rng = random.Random(seed)
        self.crash_prob = crash_prob
        self.disk = disk if disk is not None else MemObjectStore()
        self.crashes = 0

    def run(self, n_epochs: int, max_attempts: int = 200) -> object:
        obj = self.make()
        store = CrashingStore(self.disk)
        mgr = CheckpointManager(store)
        mgr.recover(obj.executors)  # no-op on a fresh disk
        done = mgr.max_committed_epoch >> 16
        attempts = 0
        while done < n_epochs:
            attempts += 1
            if attempts > max_attempts:
                raise RuntimeError("chaos run did not converge")
            if self.rng.random() < self.crash_prob:
                # land the crash anywhere in the commit's write window:
                # SST put(s) or the manifest put itself (torn upload)
                store.arm(self.rng.randint(1, 3))
            try:
                self.feed(obj)
                mgr.commit_epoch((done + 1) << 16, obj.executors)
                done += 1
            except CrashPoint:
                self.crashes += 1
                obj = self.make()
                store = CrashingStore(self.disk)
                mgr = CheckpointManager(store)
                mgr.recover(obj.executors)
                done = mgr.max_committed_epoch >> 16
        return obj
