"""Crash/flaky-injecting object stores + kill/recover chaos runner.

Reference model: the madsim simulation tier kills arbitrary nodes at
arbitrary times and asserts the cluster converges to the same result
as an undisturbed run (src/tests/simulation/tests/integration_tests/
recovery/). Here the unit of failure is the process: a crash abandons
all live state mid-operation; durability is exactly what the object
store holds. Recovery = rebuild executors + ``CheckpointManager.
recover`` + source offsets resume (exactly-once's two halves).

Three injectors compose:
- ``CrashingStore`` — FATAL faults: ``arm(n)`` kills the process at
  the n-th subsequent write, and a dead process serves NOTHING (reads
  included — a killed node cannot answer).
- ``FlakyStore`` — TRANSIENT faults: a seeded schedule of
  ``TransientStoreError`` + injected latency per op, the flaky-blob-
  store / slow-upload / DEAD-then-ALIVE-probe failure mode the
  resilience layer (risingwave_tpu/resilience.py) must absorb.
  Stack ``FlakyStore(CrashingStore(disk))`` and a crash can land in
  the MIDDLE of a retry loop (the retry re-enters the crash gate).
- ``CrashingExecutor`` — ACTOR deaths: a pass-through executor planted
  in a fragment chain that kills its actor thread mid-epoch (apply) or
  at the barrier fence; ``ActorChaosRunner`` drives it randomly so the
  runtime's fragment-scoped partial recovery (graph supervisor +
  replay buffer) is chaos-tested, not just the store boundary.

Replay: every runner failure message carries the fault-schedule seed;
``chaos_seed(default)`` lets tests accept ``RW_CHAOS_SEED`` to replay
a failing schedule deterministically.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Sequence

from risingwave_tpu.executors.base import Executor
from risingwave_tpu.resilience import (
    STORE_UNAVAILABLE,
    RetryingObjectStore,
    RetryPolicy,
    TransientStoreError,
)
from risingwave_tpu.storage.object_store import MemObjectStore, ObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager


def chaos_seed(default: int) -> int:
    """The fault-schedule seed: ``RW_CHAOS_SEED`` (replay a failure
    printed by a previous run) or the test's default. A malformed env
    value falls back to the default rather than killing collection."""
    from risingwave_tpu.resilience import _env_val

    return _env_val("RW_CHAOS_SEED", int, default)


class CrashPoint(BaseException):
    """The injected process death (BaseException: nothing may catch and
    'handle' a crash on the way out — retry loops included)."""


class CrashingStore(ObjectStore):
    """Wraps the durable store; ``arm(n)`` makes the n-th subsequent
    write raise CrashPoint and poisons EVERY later op — the process is
    dead; only ``inner``'s already-committed bytes survive. Reads are
    gated too: a dead process cannot serve reads (sim fidelity — a
    killed node answering GETs would mask torn-recovery bugs)."""

    def __init__(self, inner: ObjectStore):
        self.inner = inner
        self._countdown: Optional[int] = None
        self.dead = False

    def arm(self, nth_write: int) -> None:
        self._countdown = nth_write

    def _death_gate(self):
        if self.dead:
            raise CrashPoint("process already dead")

    def _write_gate(self):
        self._death_gate()
        if self._countdown is not None:
            self._countdown -= 1
            if self._countdown <= 0:
                self.dead = True
                self._countdown = None
                raise CrashPoint("injected crash at write")

    def put(self, path: str, data: bytes) -> None:
        self._write_gate()
        self.inner.put(path, data)

    def delete(self, path: str) -> None:
        self._write_gate()
        self.inner.delete(path)

    def read(self, path: str) -> bytes:
        self._death_gate()
        return self.inner.read(path)

    def read_range(self, path: str, off: int, length: int) -> bytes:
        self._death_gate()
        return self.inner.read_range(path, off, length)

    def exists(self, path: str) -> bool:
        self._death_gate()
        return self.inner.exists(path)

    def list(self, prefix: str):
        self._death_gate()
        return self.inner.list(prefix)


class FlakyStore(ObjectStore):
    """Seeded schedule of transient errors + injected latency per op.

    ``rate`` is the per-op probability of a ``TransientStoreError``;
    ``latency_s`` adds up to that much seeded delay per op (a slow
    upload, not just a failed one). Pass a shared ``rng`` so the
    schedule continues across process respawns (the ChaosRunner does),
    or a ``seed`` for a standalone deterministic schedule. ``ops``
    restricts injection to named ops (e.g. only ``put``)."""

    def __init__(
        self,
        inner: ObjectStore,
        rate: float = 0.2,
        seed: int = 0,
        rng: Optional[random.Random] = None,
        latency_s: float = 0.0,
        ops: Optional[Sequence[str]] = None,
    ):
        self.inner = inner
        self.rate = rate
        self.rng = rng if rng is not None else random.Random(seed)
        self.latency_s = latency_s
        self.ops = frozenset(ops) if ops is not None else None
        self.faults = 0

    def _maybe_fault(self, op: str, path: str) -> None:
        if self.ops is not None and op not in self.ops:
            return
        if self.latency_s:
            time.sleep(self.rng.random() * self.latency_s)
        if self.rng.random() < self.rate:
            self.faults += 1
            raise TransientStoreError(
                f"injected transient fault #{self.faults} at {op} {path}"
            )

    def put(self, path: str, data: bytes) -> None:
        self._maybe_fault("put", path)
        self.inner.put(path, data)

    def read(self, path: str) -> bytes:
        self._maybe_fault("read", path)
        return self.inner.read(path)

    def read_range(self, path: str, off: int, length: int) -> bytes:
        self._maybe_fault("read_range", path)
        return self.inner.read_range(path, off, length)

    def exists(self, path: str) -> bool:
        self._maybe_fault("exists", path)
        return self.inner.exists(path)

    def list(self, prefix: str):
        self._maybe_fault("list", prefix)
        return self.inner.list(prefix)

    def delete(self, path: str) -> None:
        self._maybe_fault("delete", path)
        self.inner.delete(path)


class CorruptingStore(ObjectStore):
    """Seeded SILENT-corruption injector: bit-flips or truncates blob
    bytes, on the read path (the store returns bytes that differ from
    what was written) or at rest (``corrupt_at_rest`` rewrites the
    stored bytes in place). Unlike CrashingStore/FlakyStore the fault
    is by construction UNDETECTABLE at the store boundary — no
    exception, no missing object — so only the integrity layer's
    checksums and digests can catch it. Every injection is recorded in
    ``injected`` as ``(path, mode)``; storm tests assert that each one
    was DETECTED (quarantined or scrub-flagged), i.e. zero corruptions
    survive silently.

    ``rate`` is the per-read probability; ``prefix`` restricts
    injection to matching paths (e.g. only SSTs); quarantine copies
    are never corrupted (they exist post-detection, and destroying
    forensics would let a detected fault masquerade as an undetected
    one)."""

    def __init__(
        self,
        inner: ObjectStore,
        rate: float = 0.0,
        seed: int = 0,
        rng: Optional[random.Random] = None,
        modes: Sequence[str] = ("bitflip", "truncate"),
        prefix: Optional[str] = None,
        ops: Sequence[str] = ("read",),
    ):
        self.inner = inner
        self.rate = rate
        self.rng = rng if rng is not None else random.Random(seed)
        self.modes = tuple(modes)
        self.prefix = prefix
        self.ops = frozenset(ops)
        self.injected: list = []  # (path, mode) — the detection ledger

    def _eligible(self, path: str) -> bool:
        from risingwave_tpu.integrity import QUARANTINE_PREFIX

        if path.startswith(QUARANTINE_PREFIX + "/"):
            return False
        return self.prefix is None or path.startswith(self.prefix)

    def _corrupt(self, data: bytes, mode: str) -> bytes:
        if not data:
            return data
        if mode == "truncate":
            # drop a seeded tail — at least one byte, never the whole
            # blob (an absent object is a DETECTABLE fault; silence is
            # the point)
            keep = self.rng.randrange(0, len(data))
            return data[:keep]
        b = bytearray(data)
        i = self.rng.randrange(len(b))
        b[i] ^= 1 << self.rng.randrange(8)
        return bytes(b)

    def _maybe(self, op: str, path: str, data: bytes) -> bytes:
        if op not in self.ops or not self._eligible(path):
            return data
        if not data or self.rng.random() >= self.rate:
            return data
        mode = self.modes[self.rng.randrange(len(self.modes))]
        self.injected.append((path, mode))
        return self._corrupt(data, mode)

    def corrupt_at_rest(
        self, path: Optional[str] = None, mode: Optional[str] = None
    ) -> Optional[str]:
        """Corrupt one committed blob IN PLACE (latent media fault: the
        damage persists across re-reads and process respawns). With no
        ``path``, picks a seeded eligible blob. Returns the path hit,
        or None if nothing is eligible."""
        if path is None:
            cands = [p for p in self.inner.list("") if self._eligible(p)]
            if not cands:
                return None
            path = cands[self.rng.randrange(len(cands))]
        if mode is None:
            mode = self.modes[self.rng.randrange(len(self.modes))]
        data = self.inner.read(path)
        if not data:
            return None
        self.injected.append((path, mode))
        self.inner.put(path, self._corrupt(data, mode))
        return path

    def put(self, path: str, data: bytes) -> None:
        self.inner.put(path, data)

    def read(self, path: str) -> bytes:
        return self._maybe("read", path, self.inner.read(path))

    def read_range(self, path: str, off: int, length: int) -> bytes:
        return self._maybe(
            "read_range", path, self.inner.read_range(path, off, length)
        )

    def exists(self, path: str) -> bool:
        return self.inner.exists(path)

    def list(self, prefix: str):
        return self.inner.list(prefix)

    def delete(self, path: str) -> None:
        self.inner.delete(path)


def corrupt_device_state(ex, attr: str = "table", seed: int = 0):
    """Flip one LIVE element of a device-resident state pytree (the
    in-HBM bit-flip the digest contract exists to catch). Picks a
    seeded live slot so the flip provably lands inside digest coverage
    — flipping a padding slot would (correctly) not move the digest.
    Returns ``(leaf_index, slot_index)`` for the failure message."""
    import numpy as np

    import jax

    obj = getattr(ex, attr)
    rng = random.Random(seed)
    leaves, treedef = jax.tree.flatten(obj)
    live = getattr(obj, "live", None)
    slot = None
    if live is not None:
        nz = np.flatnonzero(np.asarray(live))
        if nz.size:
            slot = int(nz[rng.randrange(nz.size)])
    # restrict to DIGEST-COVERED lanes when the contract names them
    # (lane builders pass state arrays by identity): flipping a
    # bookkeeping lane would — correctly — not move the digest, and
    # this hook exists to plant faults the digest MUST catch
    covered = None
    lanes_fn = getattr(ex, "digest_lanes", None)
    if callable(lanes_fn):
        try:
            covered = {id(a) for a in lanes_fn()[0].values()}
        except Exception:  # noqa: BLE001 — fall back to any leaf
            covered = None

    def pick(ids):
        return [
            i
            for i, a in enumerate(leaves)
            if hasattr(a, "dtype")
            and getattr(a, "size", 0)
            and a is not live
            and (ids is None or id(a) in ids)
            and (
                slot is None
                or (a.ndim >= 1 and live is not None
                    and a.shape[0] == live.shape[0])
            )
        ]

    cands = pick(covered) or pick(None)
    if not cands:
        raise ValueError(f"no corruptible leaf on {type(ex).__name__}")
    k = cands[rng.randrange(len(cands))]
    a = leaves[k]
    idx = (
        (slot,) + (0,) * (a.ndim - 1)
        if slot is not None
        else tuple(rng.randrange(d) for d in a.shape)
    )
    old = a[idx]
    if a.dtype == bool:
        new = ~old
    elif a.dtype.kind in "iu":
        new = old ^ 1
    else:
        new = old + 1.0
    leaves[k] = a.at[idx].set(new)
    setattr(ex, attr, jax.tree.unflatten(treedef, leaves))
    return (k, idx[0] if idx else 0)


class ActorCrash(RuntimeError):
    """Injected ACTOR death. Deliberately a RuntimeError (not a
    BaseException like CrashPoint): it must ride the normal executor-
    failure path — FragmentActor.run catches it, reports to the graph
    supervisor via ``_actor_failed``, and the runtime's partial
    recovery attributes/fences/restores exactly as for a real poisoned
    executor."""


class CrashingExecutor(Executor):
    """Pass-through executor that murders its actor thread on demand —
    the actor-kill injector ChaosRunner's store injectors cannot
    provide. ``arm("apply")`` kills mid-epoch while a chunk is being
    processed; ``arm("barrier")`` kills at the barrier fence;
    ``always=True`` kills at EVERY barrier (deterministic fault — the
    escalation-ladder fixture). One-shot arms disarm after firing, so
    the recovery replay passes."""

    def __init__(self, name: str = "crash"):
        self.name = name
        self._arm: Optional[Tuple[str, int]] = None
        self.always = False
        self.kills = 0

    def arm(self, on: str = "apply", after: int = 1) -> None:
        if on not in ("apply", "barrier"):
            raise ValueError(f"unknown kill site {on!r}")
        self._arm = (on, max(1, int(after)))

    def _maybe_die(self, site: str) -> None:
        if self.always and site == "barrier":
            self.kills += 1
            raise ActorCrash(f"{self.name}: deterministic kill at {site}")
        if self._arm is not None and self._arm[0] == site:
            on, left = self._arm
            left -= 1
            if left <= 0:
                self._arm = None
                self.kills += 1
                raise ActorCrash(f"{self.name}: injected kill at {site}")
            self._arm = (on, left)

    # Executor surface (base defaults for everything else, so the
    # epoch-batch fuser treats it as an opaque run-breaker)
    def apply(self, chunk):
        self._maybe_die("apply")
        return [chunk]

    def on_barrier(self, b):
        self._maybe_die("barrier")
        return []


class ActorChaosRunner:
    """ChaosRunner's actor-kill mode: murder a random actor mid-epoch
    (via the workload's ``CrashingExecutor``s) and let the runtime's
    supervisor recover — partially when the blast radius allows, fully
    otherwise — then assert convergence against a fault-free twin.

    ``make()`` returns a workload exposing:
      - ``runtime``  — a StreamingRuntime with ``auto_recover=True``;
      - ``crash_points`` — the CrashingExecutors planted in its chains;
      - ``feed(i)``  — push epoch ``i``'s data (DETERMINISTIC per index)
        and call ``runtime.barrier()``.

    Pump contract after a barrier that recovered instead of committing:
    ``runtime.last_recovery_mode`` says whether the failed window's
    data was replayed in place (``"partial"`` — just barrier again) or
    rolled back with everything else (``"full"`` — re-feed the same
    index; state rolled back to the last commit, so the re-feed is the
    replay). Every failure message carries the seed (RW_CHAOS_SEED
    replays the schedule)."""

    def __init__(
        self,
        make: Callable[[], object],
        seed: int = 0,
        kill_prob: float = 0.3,
        kill_site: str = "mixed",
    ):
        self.make = make
        self.seed = seed
        self.rng = random.Random(seed ^ 0xAC70)
        self.kill_prob = kill_prob
        self.kill_site = kill_site
        self.kills_armed = 0

    def _fail(self, why: str) -> RuntimeError:
        return RuntimeError(
            f"actor-kill chaos run {why} (seed={self.seed}; rerun with "
            f"RW_CHAOS_SEED={self.seed} to replay)"
        )

    def run(self, n_epochs: int, max_attempts: int = 200) -> object:
        obj = self.make()
        rt = obj.runtime
        done = 0
        attempts = 0
        fed = False  # has epoch `done`'s data been pushed (and survived)?
        while done < n_epochs:
            attempts += 1
            if attempts > max_attempts:
                raise self._fail("did not converge")
            if self.rng.random() < self.kill_prob and obj.crash_points:
                cp = self.rng.choice(list(obj.crash_points))
                site = (
                    self.rng.choice(("apply", "barrier"))
                    if self.kill_site == "mixed"
                    else self.kill_site
                )
                cp.arm(on=site, after=1)
                self.kills_armed += 1
            before = rt.mgr.max_committed_epoch
            if not fed:
                obj.feed(done)
                fed = True
            else:
                rt.barrier()
            if rt.mgr.max_committed_epoch > before:
                done += 1
                fed = False
            elif rt.last_recovery_mode == "full":
                # full recovery rolled this window back to the last
                # commit — the pump owns the replay: re-feed the index
                fed = False
        rt.wait_checkpoints()
        return obj


class ChaosRunner:
    """Run a build+feed workload for ``n_epochs`` COMMITTED epochs with
    seeded random crashes AND (optionally) a transient-fault storm;
    compare against an undisturbed twin outside.

    ``make()`` returns a fresh workload object exposing ``executors``
    (incl. its source, so offsets checkpoint+restore) and is driven by
    ``feed(obj)`` for one epoch's data+barrier (NO commit — the runner
    owns commits so it can crash them). Epoch numbers encode the
    committed count, so recovery knows where to resume.

    With ``flaky_rate`` > 0, the store stack per process incarnation is
    ``RetryingObjectStore(FlakyStore(CrashingStore(disk)))``: transient
    faults are absorbed by the retry layer, fatal crashes kill the
    incarnation — and a crash can land mid-retry. The flaky schedule's
    rng is SHARED across incarnations, so one seed replays the whole
    storm. A retry give-up (budget exceeded / breaker open) is treated
    like a crash: the process abandons live state and recovers.
    """

    def __init__(
        self,
        make: Callable[[], object],
        feed: Callable[[object], None],
        seed: int = 0,
        crash_prob: float = 0.25,
        disk: Optional[ObjectStore] = None,
        flaky_rate: float = 0.0,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self.make = make
        self.feed = feed
        self.seed = seed
        self.rng = random.Random(seed)
        self.crash_prob = crash_prob
        self.disk = disk if disk is not None else MemObjectStore()
        self.flaky_rate = flaky_rate
        # flaky schedule survives respawns: one rng for the whole storm
        self._flaky_rng = random.Random(seed ^ 0x5EED)
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=8,
            base_backoff_s=0.001,
            max_backoff_s=0.02,
            deadline_s=10.0,
            seed=seed,
        )
        self.crashes = 0
        self.giveups = 0
        self.faults_injected = 0

    def _spawn(self):
        """One process incarnation: fresh workload + store stack + mgr."""
        obj = self.make()
        crashing = CrashingStore(self.disk)
        store: ObjectStore = crashing
        flaky = None
        if self.flaky_rate > 0:
            flaky = FlakyStore(
                crashing, rate=self.flaky_rate, rng=self._flaky_rng
            )
            store = RetryingObjectStore(flaky, self.retry_policy)
        mgr = CheckpointManager(store, read_retry=self.retry_policy)
        mgr.recover(obj.executors)  # no-op on a fresh disk
        return obj, crashing, flaky, mgr

    def _not_converged(self) -> RuntimeError:
        return RuntimeError(
            f"chaos run did not converge (seed={self.seed}; "
            f"rerun with RW_CHAOS_SEED={self.seed} to replay)"
        )

    def _spawn_bounded(self, budget: list):
        """Respawn, absorbing retry give-ups DURING recovery reads too
        (the storm does not pause for the recovering process): each
        failed spawn burns one attempt and counts a giveup, so a
        hard-down store still surfaces with the seed breadcrumb."""
        while True:
            budget[0] += 1
            if budget[0] > budget[1]:
                raise self._not_converged()
            try:
                return self._spawn()
            except STORE_UNAVAILABLE:
                self.giveups += 1

    def run(self, n_epochs: int, max_attempts: int = 200) -> object:
        budget = [0, max_attempts]
        obj, crashing, flaky, mgr = self._spawn_bounded(budget)
        done = mgr.max_committed_epoch >> 16
        while done < n_epochs:
            budget[0] += 1
            if budget[0] > budget[1]:
                raise self._not_converged()
            if self.rng.random() < self.crash_prob:
                # land the crash anywhere in the commit's write window:
                # SST put(s) or the manifest put itself (torn upload) —
                # with retries on, a flaky fault may burn extra writes
                # first, so the crash lands MID retry loop
                crashing.arm(self.rng.randint(1, 3))
            try:
                self.feed(obj)
                mgr.commit_epoch((done + 1) << 16, obj.executors)
                done += 1
            except CrashPoint:
                self.crashes += 1
                if flaky is not None:
                    self.faults_injected += flaky.faults
                obj, crashing, flaky, mgr = self._spawn_bounded(budget)
                done = mgr.max_committed_epoch >> 16
            except STORE_UNAVAILABLE:
                # the store stayed down past the retry budget: the
                # process gives up the epoch exactly like a crash —
                # live state is abandoned, recovery replays
                self.giveups += 1
                if flaky is not None:
                    self.faults_injected += flaky.faults
                obj, crashing, flaky, mgr = self._spawn_bounded(budget)
                done = mgr.max_committed_epoch >> 16
        if flaky is not None:
            self.faults_injected += flaky.faults
        return obj


class OverloadChaosRunner:
    """Overload chaos: seeded ingest bursts + skewed key storms whose
    cardinality ramp rides the bucket lattice's pow2 boundaries, driven
    against a MEMORY-GOVERNED runtime (runtime/memory_governor.py). The
    acceptance contract this runner holds:

    - the degradation ladder walks the FULL arc — NORMAL -> THROTTLED
      -> SHEDDING -> DEGRADED — and walks BACK to NORMAL once relief
      lands (the commit lane catches up, cold-tier spill evicts the
      durable groups, allocators shrink, windows close);
    - the device-state ledger NEVER exceeds the HBM budget on any
      governed barrier (zero OOM by construction: growth past the
      budget is vetoed, sources lag at anchored offsets instead);
    - the run never wedges: every offered row is eventually ingested
      (lag, never loss) within a bounded barrier budget;
    - the governed run's final MV is BIT-IDENTICAL to an unthrottled,
      fault-free twin fed the same event prefix (exactly-once
      untouched by admission control).

    Two-pass, self-calibrating: pass 1 runs the TWIN (governor dormant)
    over the same seeded schedule, recording the per-barrier footprint
    trajectory (sum of ``state_nbytes()`` contracts — the same walk the
    governor's ledger does). The budget is then set just above the
    twin's peak (so deferral, never denial, and no emergency ``bump``)
    and the ladder thresholds are calibrated INSIDE the measured
    (warm floor, peak) band so the storm provably crosses every rung
    and post-relief footprint provably descends below them. Pass 2
    replays the schedule governed. Real bytes, real veto/spill/shrink
    mechanics — only the thresholds adapt to the workload's scale.

    ``make()`` returns a fresh workload object exposing:

    - ``runtime``   — a StreamingRuntime (governor dormant at build);
    - ``sources``   — a SourceManager owning every source (admission
      attaches here, so throttling rides the REAL poll path);
    - ``ingest(max_rows) -> int`` — poll the sources THROUGH
      ``sources.poll`` (offered window = max_rows; admission clamps
      it) and push into the runtime; returns rows actually ingested;
    - ``drain()``    — the workload's drain action (close windows via
      a watermark, flush the commit lane, ...) — a pure function of
      the data ingested so far, so both passes drain identically;
    - ``barrier()``  — one runtime barrier;
    - ``mv()``       — the MV snapshot for the bit-identity compare;
    - ``fragment_of`` (optional) — source name -> fragment map for
      per-fragment credit windows.

    Failure messages carry the seed (replay: ``RW_CHAOS_SEED=<seed>``).
    """

    def __init__(
        self,
        make: Callable[[], object],
        seed: int = 0,
        warm_epochs: int = 2,
        storm_rows: int = 12_000,
        burst_rows: int = 3_000,
        drain_epochs: int = 60,
        max_epochs: int = 400,
        cooldown: int = 2,
        budget_slack: float = 1.02,
        require_full_ladder: bool = True,
    ):
        self.make = make
        self.seed = seed
        self.warm_epochs = warm_epochs
        self.storm_rows = storm_rows
        self.burst_rows = burst_rows
        self.drain_epochs = drain_epochs
        self.max_epochs = max_epochs
        self.cooldown = cooldown
        self.budget_slack = budget_slack
        # how deep a rung the storm stacks before relief lands is
        # seed-dependent; replay-contract tests relax this
        self.require_full_ladder = require_full_ladder
        # filled by run()
        self.budget_bytes = 0
        self.thresholds = {}
        self.states_seen: list = []
        self.report: dict = {}

    def _fail(self, what: str) -> RuntimeError:
        return RuntimeError(
            f"overload chaos: {what} (seed={self.seed}; rerun with "
            f"RW_CHAOS_SEED={self.seed} to replay; report={self.report})"
        )

    def _bursts(self):
        """The seeded burst schedule: offered rows per storm epoch.
        Bursty by construction — the rng alternates heavy bursts with
        near-idle epochs, so the governed pass sees both the ramp and
        the boundary-riding flap pressure."""
        rng = random.Random(self.seed ^ 0xB00F)
        offered, total = [], 0
        while total < self.storm_rows:
            if rng.random() < 0.3:
                n = rng.randint(1, max(2, self.burst_rows // 20))
            else:
                n = rng.randint(self.burst_rows // 2, self.burst_rows)
            n = min(n, self.storm_rows - total)
            offered.append(n)
            total += n
        return offered

    @staticmethod
    def _footprint(runtime) -> int:
        total = 0
        for ex in runtime.executors():
            fn = getattr(ex, "state_nbytes", None)
            if fn is None:
                continue
            try:
                total += int(fn())
            except Exception:  # noqa: BLE001
                pass
        return total

    def _twin_pass(self, offered):
        """Unthrottled, fault-free twin: same schedule, governor
        dormant. Returns (mv_snapshot, warm footprint, peak)."""
        obj = self.make()
        traj = []
        for _ in range(self.warm_epochs):
            obj.ingest(0)
            obj.barrier()
            traj.append(self._footprint(obj.runtime))
        warm = max(traj) if traj else 0
        for n in offered:
            got = obj.ingest(n)
            if got != n:
                raise self._fail(
                    f"twin ingest lagged ({got}/{n} rows) — the twin "
                    "must be unthrottled"
                )
            obj.barrier()
            traj.append(self._footprint(obj.runtime))
        obj.drain()
        for _ in range(self.drain_epochs):
            obj.ingest(0)
            obj.barrier()
        return obj.mv(), warm, max(traj)

    def _calibrate(self, warm, peak):
        """Budget just above the twin's peak; ladder thresholds inside
        the measured (warm floor, peak) band. The governed pass's
        post-relief footprint returns to the warm level (spill evicts
        the durable groups, allocators shrink), so descent below every
        exit threshold (enter * exit_margin 0.85) is by construction."""
        budget = int(peak * self.budget_slack)
        floor = (warm / 0.85) / budget
        hi = (peak / budget) - 0.01
        span = hi - floor
        if span < 0.15:
            self.report.update(warm=warm, peak=peak, budget=budget)
            raise self._fail(
                f"calibration band too thin (floor={floor:.3f} "
                f"peak_frac={hi:.3f}) — the storm must grow state well "
                "past the warm steady footprint"
            )
        self.budget_bytes = budget
        self.thresholds = {
            "throttle_at": floor + 0.15 * span,
            "shed_at": floor + 0.50 * span,
            "degrade_at": floor + 0.85 * span,
        }

    def run(self):
        """Run both passes; returns (governed_mv, twin_mv) for the
        caller's bit-identity assert (the runner already asserted the
        ladder walk, the budget bound and the no-wedge bound)."""
        from risingwave_tpu.runtime.memory_governor import (
            LADDER,
            NORMAL,
            OverloadLadder,
        )

        offered = self._bursts()
        want, warm, peak = self._twin_pass(offered)
        self._calibrate(warm, peak)

        obj = self.make()
        gov = obj.runtime.memory_governor
        gov.budget_bytes = self.budget_bytes
        gov.enabled = True
        gov.ladder = OverloadLadder(
            cooldown=self.cooldown, **self.thresholds
        )
        # the spill watermark must sit BELOW the DEGRADED rung: a
        # parked source freezes the pressure it created, so relief has
        # to keep firing on the barrier clock while parked (each pass
        # frees whatever the commit lane has made durable since)
        gov.spill_at = min(
            gov.spill_at, self.thresholds["degrade_at"] * 0.95
        )
        obj.sources.attach_admission(
            gov.admission, getattr(obj, "fragment_of", None)
        )
        self.states_seen = [NORMAL]
        ledger_high = 0

        def _barrier(ingested=0):
            obj.barrier()
            st = gov.ladder.state
            if st != self.states_seen[-1]:
                self.states_seen.append(st)
            nonlocal ledger_high
            ledger_high = max(ledger_high, gov.ledger_high)
            if gov.ledger_high > gov.budget_bytes:
                self.report.update(
                    ledger=gov.ledger_high, budget=gov.budget_bytes
                )
                raise self._fail(
                    "ledger exceeded the HBM budget — the grow gate "
                    "leaked (emergency bump or ungated allocator)"
                )
            return ingested

        for _ in range(self.warm_epochs):
            obj.ingest(0)
            _barrier()
        # storm: offer each burst until ADMISSION lets it fully in —
        # lag, never loss; a parked source retries the same offer
        epochs = self.warm_epochs
        for n in offered:
            remaining = n
            while remaining > 0:
                remaining -= _barrier(obj.ingest(remaining))
                epochs += 1
                if epochs > self.max_epochs:
                    raise self._fail(
                        f"wedged: {remaining} rows of a {n}-row burst "
                        f"still unadmitted after {epochs} barriers"
                    )
        obj.drain()
        drained = 0
        while drained < self.max_epochs:
            obj.ingest(0)
            _barrier()
            drained += 1
            if (
                drained >= self.drain_epochs
                and gov.ladder.state == NORMAL
            ):
                break
        self.report = {
            "warm": warm,
            "peak": peak,
            "final": self._footprint(obj.runtime),
            "budget": self.budget_bytes,
            "ledger_high": ledger_high,
            "states_seen": list(self.states_seen),
            "vetoes": gov.vetoes,
            "spills": gov.spills,
            "parked_polls": gov.admission.parked_polls,
            "flaps": gov.ladder.flaps,
            "epochs": epochs,
            "drain_barriers": drained,
            "thresholds": dict(self.thresholds),
        }
        if self.require_full_ladder and set(self.states_seen) != set(LADDER):
            raise self._fail(
                f"ladder did not walk every rung: saw {self.states_seen}"
            )
        if len(set(self.states_seen)) < 2:
            raise self._fail("the storm never raised the ladder at all")
        if gov.ladder.state != NORMAL:
            raise self._fail(
                f"ladder never recovered: stuck at {gov.ladder.state} "
                f"after the drain"
            )
        return obj.mv(), want
