"""Wedgeable fake device — the sim tier for the q7 wedge class.

The real failure (BENCH_TPU_2/3) is a TPU whose dispatch queue stops
answering: every ``block_until_ready`` blocks forever, actors hang
mid-kernel, and the process sits until an outer alarm murders it. A
CPU test cannot wedge XLA on demand, so this module fakes the device
at the two seams the blackbox sentinel and the runtime actually
observe:

- :class:`WedgeableDevice` — a heartbeat target for
  ``DeviceSentinel(heartbeat_fn=dev.heartbeat)``: healthy beats return
  immediately (optionally with injected latency for SLOW coverage);
  ``wedge()`` makes every subsequent beat block until ``unwedge()``,
  exactly like a dispatch into a dead device queue.
- :class:`BlockingKernelExecutor` — a pass-through executor whose
  apply/flush blocks on the same device object when wedged: planted in
  a pipeline it wedges the barrier mid-walk (serial) or mid-actor
  (graph), reproducing "stuck actors" evidence in stall dumps while
  the sentinel independently classifies WEDGED.

``unwedge()`` releases every blocked thread (heartbeat workers, actor
threads) so tests can always tear down cleanly — a real wedge has no
such mercy, which is the point of testing against a fake one.
"""

from __future__ import annotations

import threading
from typing import Optional

from risingwave_tpu.executors.base import Executor

__all__ = ["WedgeableDevice", "BlockingKernelExecutor"]


class WedgeableDevice:
    """A fake device queue with an on/off wedge switch."""

    def __init__(self, latency_s: float = 0.0):
        self.latency_s = latency_s
        self._wedged = threading.Event()
        self._release = threading.Event()
        self._release.set()
        self.beats = 0
        self.blocked = 0
        self._lock = threading.Lock()

    @property
    def wedged(self) -> bool:
        return self._wedged.is_set()

    def wedge(self) -> None:
        """Every call into the device from now on blocks (the dead
        dispatch queue) until :meth:`unwedge`."""
        self._release.clear()
        self._wedged.set()

    def unwedge(self) -> None:
        """Revive the device: blocked callers return, new calls pass."""
        self._wedged.clear()
        self._release.set()

    def call(self, timeout: Optional[float] = None) -> None:
        """One device call: returns after ``latency_s`` when healthy,
        blocks while wedged. ``timeout`` bounds the block for callers
        that must not hang forever even in tests."""
        with self._lock:
            self.beats += 1
        if self.latency_s:
            # injected latency models a SLOW (congested-tunnel) device
            threading.Event().wait(self.latency_s)
        if self._wedged.is_set():
            with self._lock:
                self.blocked += 1
            self._release.wait(timeout=timeout)

    # the DeviceSentinel heartbeat_fn surface
    def heartbeat(self) -> None:
        self.call()


class BlockingKernelExecutor(Executor):
    """Pass-through executor whose hot path dispatches into a
    :class:`WedgeableDevice` — the "blocking fake kernel". Plant it in
    a chain and ``device.wedge()`` to freeze the pipeline exactly where
    a wedged XLA program would: mid-apply or at the barrier flush."""

    def __init__(
        self, device: WedgeableDevice, block_on: str = "barrier"
    ):
        if block_on not in ("apply", "barrier", "both"):
            raise ValueError(f"unknown block site {block_on!r}")
        self.device = device
        self.block_on = block_on

    def apply(self, chunk):
        if self.block_on in ("apply", "both"):
            self.device.call()
        return [chunk]

    def on_barrier(self, b):
        if self.block_on in ("barrier", "both"):
            self.device.call()
        return []
