"""Deterministic chaos/simulation harness.

Reference: src/tests/simulation/ (madsim cluster, random kill/restart
nexmark recovery suites, src/tests/simulation/src/cluster.rs:47).

TPU re-design: fragments are host-driven, so "a node crash" is
droppable in-process: abandon every live object mid-write, keep only
the object store's committed bytes, rebuild executors, recover. The
``CrashingStore`` injects the crash at an exact put — including BETWEEN
a checkpoint's SST uploads and its manifest commit, the torn-upload
window the manifest protocol must tolerate. ``FlakyStore`` layers a
seeded TRANSIENT fault storm (flaky blob store, injected latency) on
top, the failure mode risingwave_tpu/resilience.py must absorb; the
two compose so a crash can land mid-retry-loop.
"""

from risingwave_tpu.sim.chaos import (
    ActorChaosRunner,
    ActorCrash,
    ChaosRunner,
    CorruptingStore,
    CrashingExecutor,
    CrashingStore,
    CrashPoint,
    FlakyStore,
    OverloadChaosRunner,
    chaos_seed,
    corrupt_device_state,
)
from risingwave_tpu.sim.fake_device import (
    BlockingKernelExecutor,
    WedgeableDevice,
)

__all__ = [
    "ActorChaosRunner",
    "ActorCrash",
    "BlockingKernelExecutor",
    "ChaosRunner",
    "CorruptingStore",
    "CrashPoint",
    "CrashingExecutor",
    "CrashingStore",
    "FlakyStore",
    "OverloadChaosRunner",
    "WedgeableDevice",
    "chaos_seed",
    "corrupt_device_state",
]
