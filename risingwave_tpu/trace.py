"""Tracing — lightweight spans with chrome-trace export.

Reference: the reference threads `tracing` spans through every actor/
executor and exports via opentelemetry (src/utils/runtime/src/, await
tree dumps). Here spans are host-side (device work is opaque inside
XLA programs anyway): a context manager records (name, start, dur,
args) per thread into a bounded ring, renders chrome://tracing JSON,
and mirrors durations into the metrics registry.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import contextmanager

from risingwave_tpu.metrics import REGISTRY

_MAX_EVENTS = 65_536

# live span stacks per thread (the await-tree analogue: the reference
# dumps every actor's pending await tree on stall; here every thread's
# currently-open span stack is snapshotable via active_spans())
_ACTIVE_LOCK = threading.Lock()
_ACTIVE: dict = {}  # tid -> (thread_name, [ {span, t0, args}, ... ])


def active_spans() -> dict:
    """Snapshot every thread's currently-open span stack — what each
    actor/worker is doing RIGHT NOW (outermost first), with elapsed
    seconds. The stall-dump surface (reference: await-tree dumps)."""
    now = time.perf_counter()
    out = {}
    with _ACTIVE_LOCK:
        for tid, (tname, stack) in _ACTIVE.items():
            out[f"{tname}({tid})"] = [
                {
                    "span": fr["span"],
                    "elapsed_s": round(now - fr["t0"], 4),
                    **({"args": fr["args"]} if fr["args"] else {}),
                }
                for fr in stack
            ]
    return out


class Tracer:
    def __init__(self, max_events: int = _MAX_EVENTS):
        self._events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self.enabled = True

    @contextmanager
    def span(self, name: str, **args):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        tid = threading.get_ident()
        frame = {"span": name, "t0": t0, "args": args or None}
        with _ACTIVE_LOCK:
            if tid not in _ACTIVE:
                _ACTIVE[tid] = (threading.current_thread().name, [])
            _ACTIVE[tid][1].append(frame)
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            with _ACTIVE_LOCK:
                entry = _ACTIVE.get(tid)
                if entry is not None:
                    stack = entry[1]
                    if frame in stack:
                        stack.remove(frame)
                    if not stack:
                        del _ACTIVE[tid]
            with self._lock:
                self._events.append(
                    (
                        name,
                        tid,
                        t0,
                        dur,
                        args or None,
                    )
                )
            REGISTRY.histogram("span_ms").observe(dur * 1e3, span=name)

    def chrome_trace(self) -> str:
        """chrome://tracing / perfetto 'traceEvents' JSON."""
        with self._lock:
            events = list(self._events)
        out = []
        for name, tid, t0, dur, args in events:
            ev = {
                "name": name,
                "ph": "X",
                "pid": 1,
                "tid": tid % 1_000_000,
                "ts": t0 * 1e6,
                "dur": dur * 1e6,
            }
            if args:
                ev["args"] = args
            out.append(ev)
        return json.dumps({"traceEvents": out})

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.chrome_trace())

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


TRACER = Tracer()
span = TRACER.span
