"""Tracing — lightweight spans with chrome-trace / Perfetto export.

Reference: the reference threads `tracing` spans through every actor/
executor and exports via opentelemetry (src/utils/runtime/src/, await
tree dumps). Here spans are host-side (device work is opaque inside
XLA programs anyway): a context manager records (name, start, dur,
args) per thread into a bounded ring, renders chrome://tracing JSON,
and mirrors durations into the metrics registry.

Perfetto niceties (dispatch-wall profiler):
- stable per-thread tids (a small registry id, never ``tid % 1e6``
  which can collide across threads) plus ``ph:"M"`` thread_name
  metadata, so the flame view shows actor names;
- per-fragment pid lanes: spans carrying a ``fragment`` arg render in
  that fragment's own process track (named via process_name metadata);
- epoch flow events: spans carrying an ``epoch`` arg are linked with
  ``ph:"s"/"t"`` flow arrows, so one barrier is traceable across every
  actor thread it crossed.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import contextmanager

from risingwave_tpu.metrics import REGISTRY

_MAX_EVENTS = 65_536

# live span stacks per thread (the await-tree analogue: the reference
# dumps every actor's pending await tree on stall; here every thread's
# currently-open span stack is snapshotable via active_spans())
_ACTIVE_LOCK = threading.Lock()
_ACTIVE: dict = {}  # tid -> (thread_name, [ {span, t0, args}, ... ])

# stable small tids: python thread idents are reused after thread death
# and collide under ``% 1_000_000`` — assign each (ident, name) its own
# monotonic id. Names live in a SEPARATE {small_tid: name} map that is
# append-only: a recycled ident gets a fresh small tid, and the dead
# thread's tid keeps its name (post-recovery traces still label the
# pre-fault actor's lane correctly).
_TID_LOCK = threading.Lock()
_TIDS: dict = {}  # python ident -> (small_tid, thread_name)
_TID_NAMES: dict = {}  # small_tid -> thread_name (never overwritten)
_NEXT_TID = [1]


def _stable_tid() -> int:
    ident = threading.get_ident()
    with _TID_LOCK:
        entry = _TIDS.get(ident)
        name = threading.current_thread().name
        if entry is None or entry[1] != name:
            # new thread, or the ident was recycled by a new thread
            entry = (_NEXT_TID[0], name)
            _NEXT_TID[0] += 1
            _TIDS[ident] = entry
            _TID_NAMES[entry[0]] = name
        return entry[0]


def _thread_names() -> dict:
    with _TID_LOCK:
        return dict(_TID_NAMES)


def active_spans() -> dict:
    """Snapshot every thread's currently-open span stack — what each
    actor/worker is doing RIGHT NOW (outermost first), with elapsed
    seconds. The stall-dump surface (reference: await-tree dumps)."""
    now = time.perf_counter()
    out = {}
    with _ACTIVE_LOCK:
        for tid, (tname, stack) in _ACTIVE.items():
            out[f"{tname}({tid})"] = [
                {
                    "span": fr["span"],
                    "elapsed_s": round(now - fr["t0"], 4),
                    **({"args": fr["args"]} if fr["args"] else {}),
                }
                for fr in stack
            ]
    return out


class Tracer:
    def __init__(self, max_events: int = _MAX_EVENTS):
        self._events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self.enabled = True

    @contextmanager
    def span(self, name: str, **args):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        tid = threading.get_ident()
        frame = {"span": name, "t0": t0, "args": args or None}
        with _ACTIVE_LOCK:
            if tid not in _ACTIVE:
                _ACTIVE[tid] = (threading.current_thread().name, [])
            _ACTIVE[tid][1].append(frame)
        stid = _stable_tid()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            with _ACTIVE_LOCK:
                entry = _ACTIVE.get(tid)
                if entry is not None:
                    stack = entry[1]
                    if frame in stack:
                        stack.remove(frame)
                    if not stack:
                        del _ACTIVE[tid]
            with self._lock:
                self._events.append(
                    (
                        name,
                        stid,
                        t0,
                        dur,
                        args or None,
                    )
                )
            REGISTRY.histogram("span_ms").observe(dur * 1e3, span=name)

    def chrome_trace(self) -> str:
        """chrome://tracing / Perfetto 'traceEvents' JSON: named threads
        (ph:"M" thread_name), per-fragment pid lanes, and epoch flow
        events (ph:"s"/"t") linking one barrier across actor threads."""
        with self._lock:
            events = list(self._events)
        return render_chrome_trace(events, _thread_names())

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.chrome_trace())

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


def render_chrome_trace(events, thread_names=None) -> str:
    """Render ``(name, tid, t0, dur, args)`` event tuples as chrome://
    tracing / Perfetto JSON. Shared by the live Tracer ring and
    offline renderers (the black-box reader CLI reconstructs barrier
    timelines from a crash-surviving segment through this same path)."""
    names = dict(thread_names or {})
    # the ring appends at span COMPLETION; flow binding needs start
    # order so the "s" (first) event of an epoch precedes its "t"s
    events = sorted(events, key=lambda e: e[2])
    out = []
    # pid lanes: 1 = host/unattributed; each fragment its own pid
    frag_pids: dict = {}
    pids_seen = {1}
    tids_by_pid: dict = {}  # pid -> set(tid)
    epochs_seen: dict = {}  # epoch -> first-event flag
    for name, tid, t0, dur, args in events:
        pid = 1
        if args and "fragment" in args:
            frag = str(args["fragment"])
            pid = frag_pids.setdefault(frag, 2 + len(frag_pids))
            pids_seen.add(pid)
        tids_by_pid.setdefault(pid, set()).add(tid)
        ev = {
            "name": name,
            "ph": "X",
            "pid": pid,
            "tid": tid,
            "ts": t0 * 1e6,
            "dur": dur * 1e6,
        }
        if args:
            ev["args"] = args
        out.append(ev)
        epoch = (args or {}).get("epoch")
        if epoch is not None:
            # flow arrows: first span of the epoch starts the flow,
            # every later span binds to it (enclosing-slice binding)
            first = epoch not in epochs_seen
            epochs_seen[epoch] = True
            out.append(
                {
                    "name": f"epoch {epoch}",
                    "cat": "epoch",
                    # string id: epochs are ms<<16, so truncating
                    # to 32 bits would alias barriers ~65s apart
                    # into one bogus flow chain
                    "ph": "s" if first else "t",
                    "id": str(epoch),
                    "pid": pid,
                    "tid": tid,
                    "ts": t0 * 1e6,
                    "bp": "e",
                }
            )
    # metadata: process names (fragment lanes) + thread names
    meta = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 1,
            "args": {"name": "host"},
        }
    ]
    for frag, pid in sorted(frag_pids.items(), key=lambda kv: kv[1]):
        meta.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": f"fragment:{frag}"},
            }
        )
    for pid in sorted(pids_seen):
        for tid in sorted(tids_by_pid.get(pid, ())):
            meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": names.get(tid, f"thread-{tid}")},
                }
            )
    return json.dumps({"traceEvents": meta + out})


TRACER = Tracer()
span = TRACER.span
