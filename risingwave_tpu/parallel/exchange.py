"""Vnode hash exchange — the generalized shuffle between sharded
fragments.

Reference roles replaced (SURVEY.md §2.11, §3.3):
- ``HashDataDispatcher`` routing rows by key vnode to the actor that
  owns them (src/stream/src/executor/dispatch.rs:683, vnode mapping
  src/common/src/hash/consistent_hash/vnode.rs:34);
- the exchange channel / gRPC GetStream between fragments
  (src/stream/src/executor/exchange/permit.rs:35).

TPU re-design: the "channel" is one ``lax.all_to_all`` over the mesh's
ICI links, issued inside a ``shard_map``-ed program. Rows are packed
into per-destination buckets of STATIC capacity (cumulative-count
compaction, no sort), exchanged, and re-flattened — so the whole
dispatcher+channel+merge stack of the reference becomes a few fused
XLA collectives on device. Every sharded operator
(``sharded_agg.ShardedHashAgg``, ``sharded_join.ShardedHashJoin``,
``sharded_dedup.ShardedDedup``) builds on these primitives.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.ops.hashing import VNODE_COUNT, hash_columns

# The exchange's static contract (analysis/mesh_analyzer.py): every
# sharded executor that declares ``dispatch.fn == DISPATCH_FN`` routes
# rows through THIS module's consistent-hash path, so its destination
# shard is provably ``vnode(key) % n_shards`` — a pure function of the
# key lanes and the mesh size (RW-E902's proof obligation).  Rows then
# cross shards via ``EXCHANGE_COLLECTIVE`` inside the shard_map-ed
# program (never through host memory; RW-E901's obligation).
DISPATCH_FN = "dest_shard"
EXCHANGE_COLLECTIVE = "all_to_all"
EXCHANGE_MESH_CONTRACT = {
    "dispatch_fn": DISPATCH_FN,
    "collective": EXCHANGE_COLLECTIVE,
    "vnode_count": VNODE_COUNT,
}


def dest_shard(key_lanes, n_shards: int) -> jnp.ndarray:
    """Row -> owning shard via vnode (vnode.rs:34 + vnode mapping):
    256 vnodes round-robin over shards, so scaling the mesh only remaps
    vnodes, never rehashes rows."""
    vnode = (hash_columns(key_lanes, seed=0xC0FFEE) % VNODE_COUNT).astype(
        jnp.int32
    )
    return vnode % n_shards


def exchange_cols(chunk: StreamChunk) -> Dict[str, jnp.ndarray]:
    """The lane set ``exchange_chunk`` actually ships: every column
    plus the ops lane and null lanes as extra columns. Shared with the
    meshprof phase probes so they pack exactly what the real exchange
    packs."""
    cols = dict(chunk.columns)
    cols["__ops__"] = chunk.ops
    for name, lane in chunk.nulls.items():
        cols["__null__" + name] = lane
    return cols


def pack_buckets(
    chunk_cols: Dict[str, jnp.ndarray], valid, dest, n_shards, bucket_cap
):
    """Scatter rows into an (n_shards, bucket_cap) buffer per column.

    Position within a destination bucket = number of earlier valid rows
    with the same destination (a cumsum per destination — n_shards is
    static and small, so this is n_shards vectorized passes, no sort).
    Returns (buffers, valid_buffer, overflow, counts) where ``counts``
    is the (n_shards,) per-destination valid-row vector — the
    exchange-cost observability lane (meshprof); XLA drops it when a
    caller ignores it.
    """
    n = valid.shape[0]
    pos = jnp.zeros(n, jnp.int32)
    counts = []
    for d in range(n_shards):
        m = valid & (dest == d)
        pos = jnp.where(m, jnp.cumsum(m.astype(jnp.int32)) - 1, pos)
        counts.append(jnp.sum(m.astype(jnp.int32)))
    counts = jnp.stack(counts)
    overflow = jnp.any(counts > bucket_cap)

    in_cap = valid & (pos < bucket_cap)
    flat = dest * bucket_cap + pos  # index into (n_shards*bucket_cap,)
    idx = jnp.where(in_cap, flat, n_shards * bucket_cap)  # drop lane

    out = {}
    for name, col in chunk_cols.items():
        buf = jnp.zeros(n_shards * bucket_cap, col.dtype)
        out[name] = (
            buf.at[idx].set(col, mode="drop").reshape(n_shards, bucket_cap)
        )
    vbuf = (
        jnp.zeros(n_shards * bucket_cap, jnp.bool_)
        .at[idx]
        .set(in_cap, mode="drop")
        .reshape(n_shards, bucket_cap)
    )
    return out, vbuf, overflow, counts


def exchange_chunk(
    chunk: StreamChunk,
    key_lanes: Tuple[jnp.ndarray, ...],
    n_shards: int,
    bucket_cap: int,
    axis: str,
) -> Tuple[StreamChunk, jnp.ndarray, jnp.ndarray]:
    """Route a per-shard chunk's rows to their key-owning shards.

    Call INSIDE a shard_map-ed program (per-shard view, no leading
    shard axis). Ops and null lanes ride the same buckets as extra
    columns. Returns (received_chunk of capacity n_shards*bucket_cap,
    overflow_flag, counts) where ``counts`` is this shard's
    (n_shards,) routed-valid-row histogram — already live in the
    program for overflow detection, so threading it out costs one tiny
    output buffer and gives meshprof its exchange-cost matrix row
    without a second hash pass. Every row of the result lives on the
    shard that owns vnode(key), so downstream keyed state is
    shard-local.
    """
    dest = dest_shard(key_lanes, n_shards)
    bufs, vbuf, overflow, counts = pack_buckets(
        exchange_cols(chunk), chunk.valid, dest, n_shards, bucket_cap
    )
    ex = {
        n: jax.lax.all_to_all(b, axis, 0, 0, tiled=False)
        for n, b in bufs.items()
    }
    exv = jax.lax.all_to_all(vbuf, axis, 0, 0, tiled=False)

    flatten = lambda a: a.reshape(n_shards * bucket_cap)
    received = StreamChunk(
        columns={
            n: flatten(b)
            for n, b in ex.items()
            if n != "__ops__" and not n.startswith("__null__")
        },
        valid=flatten(exv),
        nulls={
            n[len("__null__"):]: flatten(b)
            for n, b in ex.items()
            if n.startswith("__null__")
        },
        ops=flatten(ex["__ops__"]),
    )
    return received, overflow, counts
