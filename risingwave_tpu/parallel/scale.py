"""ScaleController — online re-partitioning of running fragments.

Reference: src/meta/src/stream/scale.rs:453 (Reschedule plans: vnode
bitmap deltas + actor adds/removes, applied through a barrier) and the
auto-parallelism controller (auto_parallelism tests); recovery-driven
re-scaling in barrier/recovery.rs:415-425.

TPU re-design: a sharded fragment's state keys by vnode (vnode %
n_shards owns a key, parallel/exchange.py), and every sharded executor
restores ACROSS mesh sizes (sharded_agg._sharded_agg_restore_state
re-partitions recovered rows by vnode). So a reschedule is:

  1. barrier + wait_checkpoints  — quiesce; all state durable
  2. rebuild the fragment's executors on the new mesh
  3. restore their state from the last committed epoch (vnode remap
     happens inside restore_state)
  4. swap the fragment in place; the next epoch runs at the new
     parallelism

No state is shuffled between live shards: durability IS the handover
channel (the reference migrates actor state through Hummock the same
way on recovery-based rescale).
"""

from __future__ import annotations

from typing import Callable, Optional


class ScaleController:
    def __init__(self, runtime):
        self.runtime = runtime
        self.reschedules = 0

    def reschedule(self, fragment: str, rebuild: Callable[[object], object]):
        """Swap ``fragment`` for ``rebuild(old_pipeline)`` (typically
        the same operators on a different mesh), migrating all
        checkpointable state through the store."""
        rt = self.runtime
        if rt.mgr is None:
            raise RuntimeError("reschedule needs a durable store")
        with rt.lock:
            # 1. quiesce at a checkpoint barrier; join the async lane so
            # every executor's state is durable before the handover
            rt.barrier()
            rt.wait_checkpoints()
            old = rt.fragments[fragment]
            new = rebuild(old)
            # 2+3. restore the new executors from the committed epoch
            # (restore_state re-partitions by vnode for the new mesh).
            # Compaction must quiesce first: its GC deletes SSTs that
            # read_table may be about to read (same guard as
            # StreamingRuntime.recover)
            rt._compact_pause.set()
            try:
                rt._compact_idle.wait()
                rt.mgr.recover(new.executors)
            finally:
                rt._compact_pause.clear()
            # 4. swap in place; subscriptions and epochs carry over
            new._epoch = old._epoch
            rt.fragments[fragment] = new
            self.reschedules += 1
            from risingwave_tpu.event_log import EVENT_LOG

            EVENT_LOG.record(
                "scale", fragment=fragment, reschedules=self.reschedules
            )
            return new

    def autoscale(
        self,
        fragment: str,
        rebuild_at: Callable[[int], object],
        max_shard_load: float = 0.5,
    ) -> Optional[object]:
        """Double a sharded fragment's parallelism when any shard's
        table load crosses ``max_shard_load`` (the auto-parallelism
        policy; the reference reacts to worker join/leave instead).
        Since ISSUE 18 every sharded executor class exposes
        ``shard_occupancy`` (agg, dedup, join, mv, top_n) — not just
        the agg — so the load scan sees the whole chain; an armed mesh
        profiler's hot-shard verdict for one of this fragment's tables
        also triggers the reshard (router imbalance is a scale signal
        even while absolute occupancy is low).
        ``rebuild_at(n_shards)`` builds the fragment at that
        parallelism. Returns the new pipeline or None."""
        import numpy as np

        rt = self.runtime
        pipeline = rt.fragments[fragment]
        worst = 0.0
        n_shards = None
        table_ids = set()
        for ex in pipeline.executors:
            occ = getattr(ex, "shard_occupancy", None)
            cap = getattr(ex, "capacity", None)
            if occ is None or not cap:
                continue
            if getattr(ex, "table_id", None) is not None:
                table_ids.add(str(ex.table_id))
            load = float(np.asarray(occ()).max()) / cap
            if load > worst:
                # n_shards follows the executor that actually set the
                # worst load (a cooler sibling must not pick the size)
                worst = load
                n_shards = getattr(ex, "n_shards", None)
        skewed = False
        if n_shards is not None:
            try:
                from risingwave_tpu.parallel.meshprof import MESHPROF

                if MESHPROF.enabled and MESHPROF.barriers:
                    sk = MESHPROF.barriers[-1].get("skew")
                    skewed = bool(
                        sk and str(sk.get("table_id")) in table_ids
                    )
            except Exception:  # noqa: BLE001 — advisory signal only
                skewed = False
        if n_shards is None or (worst <= max_shard_load and not skewed):
            return None
        return self.reschedule(fragment, lambda _old: rebuild_at(2 * n_shards))
