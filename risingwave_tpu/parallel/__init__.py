"""Parallelism — vnode-sharded operators over a device mesh.

Reference model (SURVEY.md §2.11): RisingWave parallelizes a fragment
into N actors; rows route to actors by vnode of the distribution key
(256 vnodes, hash dispatcher src/stream/src/executor/dispatch.rs:683),
with gRPC exchange between actors.

TPU re-design: one fragment = ONE pjit/shard_map-compiled step over a
``jax.sharding.Mesh``; the hash exchange is an on-device
``lax.all_to_all`` riding ICI inside the step, and per-actor state is
the same slot-table arrays sharded along the mesh axis. Cross-host
(DCN) edges and the control plane stay host-driven, as the reference
keeps gRPC between compute nodes.
"""

from risingwave_tpu.parallel.sharded_agg import ShardedHashAgg, make_mesh
from risingwave_tpu.parallel.sharded_top_n import ShardedGroupTopN
from risingwave_tpu.parallel.sharded_join import (
    ShardedDedup,
    ShardedHashJoin,
    flatten_stacked,
    stack_for_mesh,
)

__all__ = [
    "ShardedDedup",
    "ShardedGroupTopN",
    "ShardedHashAgg",
    "ShardedHashJoin",
    "flatten_stacked",
    "make_mesh",
    "stack_for_mesh",
]
