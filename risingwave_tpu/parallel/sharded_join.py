"""Mesh-parallel HashJoin + append-only Dedup fragments.

Reference roles replaced (SURVEY.md §2.11; VERDICT r2 #2):
- N parallel HashJoin actors each owning the vnode slice of both join
  sides (src/stream/src/executor/hash_join.rs:129 distributed by
  HashDataDispatcher, dispatch.rs:683);
- N parallel AppendOnlyDedup actors (dedup/append_only_dedup.rs).

TPU re-design: state is STACKED — every per-slot array gains a leading
``(n_shards,)`` axis sharded over the mesh — and each ``apply`` is ONE
jitted ``shard_map`` program: vnode exchange (``parallel.exchange``)
followed by the *same single-chip kernel* (``join_step_fn`` /
``dedup_step_fn``) on the received rows. Because every join key lives
on exactly one shard, per-shard emissions are disjoint and exact; the
stacked output chunks flow on-device to the next sharded fragment (or
flatten to the host materializer).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.executors.dedup import dedup_step_fn
from risingwave_tpu.executors.hash_join import (
    JOIN_TYPES,
    _side_restore,
    join_step_fn,
)
from risingwave_tpu.ops.hash_table import HashTable, lookup_or_insert, set_live
from risingwave_tpu.ops.join import JoinSide
from risingwave_tpu.parallel.exchange import dest_shard, exchange_chunk
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    grow_pow2,
    pull_rows,
    stage_marks,
)

GROW_AT = 0.5


def stack_for_mesh(tree, mesh: Mesh, axis: str):
    """Replicate a single-chip state pytree into stacked (n_shards, ...)
    arrays laid out one-slice-per-device over ``mesh``."""
    n = mesh.devices.size

    def stack(a):
        return jnp.broadcast_to(a[None], (n,) + a.shape)

    return jax.device_put(
        jax.tree.map(stack, tree), NamedSharding(mesh, P(axis))
    )


def flatten_stacked(chunk: StreamChunk) -> StreamChunk:
    """(n_shards, cap) stacked chunk -> flat (n_shards*cap,) chunk (host
    boundary: feed the single materializer / sinks)."""
    return jax.tree.map(lambda a: a.reshape((-1,) + a.shape[2:]), chunk)


def track_bucket_cap(ex, bucket_cap: int) -> None:
    """Record the LARGEST exchange bucket any built step implied — the
    growth escape must never rebuild smaller than what overflowed."""
    ex._built_bucket_cap = max(
        getattr(ex, "_built_bucket_cap", None) or 0, bucket_cap
    )


def double_bucket_cap(ex) -> None:
    """The shared capacity-escape idiom: pin bucket_cap to 2x the
    largest bucket in effect (explicit setting wins over the implied
    per-chunk default)."""
    cur = (
        ex.bucket_cap
        if ex.bucket_cap is not None
        else getattr(ex, "_built_bucket_cap", None)
    )
    if cur is not None:
        ex.bucket_cap = 2 * cur


class ShardedDedup(Executor, Checkpointable):
    """Mesh-parallel DISTINCT: exchange by dedup key, local seen-set.

    ``apply`` takes a stacked (n_shards, cap) chunk and returns ONE
    stacked output chunk (capacity n_shards*bucket_cap per shard) of
    first-seen rows, still sharded by dedup-key vnode.
    """

    def __init__(
        self,
        mesh: Mesh,
        keys: Sequence[str],
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 16,
        bucket_cap: Optional[int] = None,
        table_id: str = "sharded_dedup",
    ):
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_shards = mesh.devices.size
        self.keys = tuple(keys)
        self.bucket_cap = bucket_cap
        self.table_id = table_id
        table1 = HashTable.create(
            capacity, tuple(jnp.dtype(schema_dtypes[k]) for k in self.keys)
        )
        self.table = stack_for_mesh(table1, mesh, self.axis)
        self.sdirty = stack_for_mesh(
            jnp.zeros(capacity, jnp.bool_), mesh, self.axis
        )
        self.stored = stack_for_mesh(
            jnp.zeros(capacity, jnp.bool_), mesh, self.axis
        )
        self.flags = stack_for_mesh(
            jnp.zeros(2, jnp.bool_), mesh, self.axis
        )  # [saw_delete, dropped|overflow]
        self._step = None
        self._built_bucket_cap: Optional[int] = None
        self.ex_counts_last = None  # (n, n) routed-row histogram, device

    def _build_step(self, chunk_cap: int):
        n, axis, keys = self.n_shards, self.axis, self.keys
        bucket_cap = self.bucket_cap or max(64, (2 * chunk_cap) // n)
        track_bucket_cap(self, bucket_cap)

        def local(table, sdirty, flags, chunk):
            table, sdirty, flags, chunk = jax.tree.map(
                lambda a: a[0], (table, sdirty, flags, chunk)
            )
            lanes = tuple(chunk.col(k) for k in keys)
            rchunk, ex_ovf, ex_counts = exchange_chunk(
                chunk, lanes, n, bucket_cap, axis
            )
            table, sdirty, out, saw_delete, dropped = dedup_step_fn(
                table, sdirty, rchunk, keys
            )
            flags = flags | jnp.stack([saw_delete, dropped | ex_ovf])
            ex = lambda t: jax.tree.map(lambda a: a[None], t)
            return ex(table), ex(sdirty), ex(flags), ex(out), ex_counts[None]

        spec = P(self.axis)
        return jax.jit(
            jax.shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec,) * 4,
                out_specs=(spec,) * 5,
                check_vma=False,
            ),
            donate_argnums=(0, 1, 2),
        )

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        if self._step is None:
            self._step = self._build_step(chunk.valid.shape[-1])
        self.table, self.sdirty, self.flags, out, self.ex_counts_last = (
            self._step(self.table, self.sdirty, self.flags, chunk)
        )
        return [out]

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        flags = jnp.any(self.flags, axis=0)
        if bool(flags[0]):
            raise RuntimeError("append-only sharded dedup received a DELETE")
        if bool(flags[1]):
            raise RuntimeError(
                "sharded dedup overflowed (probe chain or exchange bucket); "
                "grow capacity/bucket_cap"
            )
        return []

    # -- static contracts (analysis/) -------------------------------------
    def lint_info(self):
        return {
            "expects": {
                k: lane.dtype for k, lane in zip(self.keys, self.table.keys)
            },
            "keys": self.keys,
            "table_ids": (self.table_id,),
            "window_key": None,
        }

    def trace_contract(self):
        return {
            "kind": "host",
            "host_reason": "mesh-resident sharded step: per-fragment "
            "SPMD fusion is tracked by the mesh analyzer (RW-E9xx), "
            "not the single-chip fuser",
            "state": (self.table, self.sdirty, self.flags),
            "donate": True,
            "emission": "stacked",
            "fallback_syncs": ("on_barrier", "shard_occupancy"),
        }

    def mesh_contract(self):
        def trace_steps(abs_chunk):
            from risingwave_tpu.analysis.mesh_domain import abstract_tree

            step = self._build_step(int(abs_chunk.valid.shape[-1]))
            return [
                (
                    "apply",
                    step,
                    (
                        abstract_tree(self.table),
                        abstract_tree(self.sdirty),
                        abstract_tree(self.flags),
                        abs_chunk,
                    ),
                )
            ]

        return {
            "axis": self.axis,
            "n_shards": self.n_shards,
            "state": {
                "table": "sharded",
                "sdirty": "sharded",
                "flags": "sharded",
            },
            "updates": ("table", "sdirty", "flags"),
            "dispatch": {
                "fn": "dest_shard",
                "keys": self.keys,
                "vnode_axis": self.axis,
            },
            "exchange": "all_to_all",
            "donate": True,
            "order_insensitive": True,  # first-seen is per-slot, and
            # slot ownership is deterministic under the vnode route
            "trace_steps": trace_steps,
            "barrier_methods": ("on_barrier", "shard_occupancy"),
            "emission": "stacked",
        }

    # -- capacity escape (watchdog replay, scale.rs:453 analogue) ---------
    def capacity_overflow_latched(self) -> bool:
        return bool(jnp.any(self.flags, axis=0)[1])

    def grow_for_replay(self) -> None:
        """Double probe capacity + exchange bucket and reset device
        state at the new shapes; the watchdog's recover() restores
        durable rows into them before the poisoned epoch replays."""
        cap = 2 * self.table.keys[0].shape[-1]
        double_bucket_cap(self)
        key_dtypes = tuple(k.dtype for k in self.table.keys)
        self.table = stack_for_mesh(
            HashTable.create(cap, key_dtypes), self.mesh, self.axis
        )
        z = jnp.zeros(cap, jnp.bool_)
        self.sdirty = stack_for_mesh(z, self.mesh, self.axis)
        self.stored = stack_for_mesh(z, self.mesh, self.axis)
        self.flags = stack_for_mesh(
            jnp.zeros(2, jnp.bool_), self.mesh, self.axis
        )
        self._step = None

    # -- integrity --------------------------------------------------------
    def state_digest(self) -> int:
        """Shard-flattened dedup fold: slot-order invariance makes this
        digest equal to the single-chip twin's for the same key set."""
        from risingwave_tpu.integrity import host_digest

        def flat(a):
            a = np.asarray(a)
            return a.reshape((-1,) + a.shape[2:])

        lanes = {f"k{i}": flat(k) for i, k in enumerate(self.table.keys)}
        return host_digest(lanes, flat(self.table.live))

    # -- checkpoint/restore (one logical table across shards) ------------
    def checkpoint_delta(self) -> List[StateDelta]:
        """Same lane naming as the single-chip dedup (k{i}), keys
        globally unique across shards — either executor can restore the
        other's checkpoint."""
        sdirty = np.asarray(self.sdirty).reshape(-1)
        if not sdirty.any():
            return []
        shape = self.sdirty.shape
        upsert, tomb, sel = stage_marks(
            sdirty,
            np.asarray(self.table.live).reshape(-1),
            np.asarray(self.stored).reshape(-1),
        )
        flat = lambda a: a.reshape((-1,) + a.shape[2:])
        lanes = {f"k{i}": flat(l) for i, l in enumerate(self.table.keys)}
        key_names = tuple(lanes)
        keys = pull_rows(lanes, sel)
        self.stored = (
            self.stored | jnp.asarray(upsert.reshape(shape))
        ) & ~jnp.asarray(tomb.reshape(shape))
        self.sdirty = jnp.zeros_like(self.sdirty)
        return [StateDelta(self.table_id, keys, {}, tomb[sel], key_names)]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        """Re-partition recovered keys by vnode and rebuild every shard
        (works across mesh sizes: a key's shard is vnode % n_shards)."""
        n_rows = len(next(iter(key_cols.values()))) if key_cols else 0
        key_dtypes = tuple(k.dtype for k in self.table.keys)
        cap = self.table.keys[0].shape[-1]
        lanes = dest = None
        if n_rows:
            lanes = tuple(
                jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d))
                for i, d in enumerate(key_dtypes)
            )
            dest = np.asarray(dest_shard(lanes, self.n_shards))
            cap = grow_pow2(
                int(np.bincount(dest, minlength=self.n_shards).max()),
                cap,
                GROW_AT,
            )
        tables, stores = [], []
        for k in range(self.n_shards):
            t = HashTable.create(cap, key_dtypes)
            stored = jnp.zeros(cap, jnp.bool_)
            if n_rows:
                sel = np.flatnonzero(dest == k)
                if len(sel):
                    sub = tuple(l[jnp.asarray(sel)] for l in lanes)
                    t, slots, _, _ = lookup_or_insert(
                        t, sub, jnp.ones(len(sel), jnp.bool_)
                    )
                    t = set_live(t, slots, True)
                    stored = stored.at[slots].set(True)
            tables.append(t)
            stores.append(stored)
        sharding = NamedSharding(self.mesh, P(self.axis))
        stack = lambda *xs: jnp.stack(xs)
        self.table = jax.device_put(jax.tree.map(stack, *tables), sharding)
        self.stored = jax.device_put(jnp.stack(stores), sharding)
        self.sdirty = jax.device_put(
            jnp.zeros_like(self.stored), sharding
        )
        self.flags = stack_for_mesh(
            jnp.zeros(2, jnp.bool_), self.mesh, self.axis
        )
        self._step = None  # capacity may have changed: recompile


class ShardedHashJoin(Executor, Checkpointable):
    """Mesh-parallel streaming equi-join, all join types.

    Both sides' state is stacked over the mesh; each arrival runs one
    shard_map program: exchange the chunk by its own-side join key
    (both sides share the vnode hash on positionally-paired keys, so a
    key's left AND right rows land on the same shard), then the
    single-chip ``join_step_fn`` against the local slices. Emissions
    come back stacked (n_shards, out_cap).
    """

    def __init__(
        self,
        mesh: Mesh,
        left_keys: Sequence[str],
        right_keys: Sequence[str],
        left_dtypes: Dict[str, object],
        right_dtypes: Dict[str, object],
        capacity: int = 1 << 14,
        fanout: int = 8,
        out_cap: int = 1 << 12,
        bucket_cap: Optional[int] = None,
        left_nullable: Sequence[str] = (),
        right_nullable: Sequence[str] = (),
        join_type: str = "inner",
        table_id: str = "sharded_join",
    ):
        if join_type not in JOIN_TYPES:
            raise ValueError(f"unknown join type {join_type!r}")
        self.table_id = table_id
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_shards = mesh.devices.size
        self.join_type = join_type
        self.left_keys = tuple(left_keys)
        self.right_keys = tuple(right_keys)
        self.left_names = tuple(sorted(left_dtypes))
        self.right_names = tuple(sorted(right_dtypes))
        if join_type.endswith("semi") or join_type.endswith("anti"):
            self.out_names = (
                self.left_names
                if join_type.startswith("left")
                else self.right_names
            )
        else:
            self.out_names = self.left_names + self.right_names
        self.out_cap = out_cap
        self.bucket_cap = bucket_cap

        lk = tuple(jnp.dtype(left_dtypes[k]) for k in self.left_keys)
        rk = tuple(jnp.dtype(right_dtypes[k]) for k in self.right_keys)
        if lk != rk:
            raise ValueError(f"join key dtype mismatch: {lk} vs {rk}")
        left1 = JoinSide.create(
            capacity,
            fanout,
            lk,
            {n: jnp.dtype(left_dtypes[n]) for n in self.left_names},
            nullable=left_nullable,
        )
        right1 = JoinSide.create(
            capacity,
            fanout,
            rk,
            {n: jnp.dtype(right_dtypes[n]) for n in self.right_names},
            nullable=right_nullable,
        )
        self._lint_left_nulls = tuple(left_nullable)
        self._lint_right_nulls = tuple(right_nullable)
        self._lint_left_dtypes = {
            n: jnp.dtype(left_dtypes[n]) for n in self.left_names
        }
        self._lint_right_dtypes = {
            n: jnp.dtype(right_dtypes[n]) for n in self.right_names
        }
        self.left = stack_for_mesh(left1, mesh, self.axis)
        self.right = stack_for_mesh(right1, mesh, self.axis)
        self._em_overflow = stack_for_mesh(
            jnp.zeros((), jnp.bool_), mesh, self.axis
        )
        self._steps: Dict[Tuple[str, int], object] = {}
        self._built_bucket_cap: Optional[int] = None
        self.ex_counts_last = None  # (n, n) routed-row histogram, device

    def _build_step(self, arrival: str, chunk_cap: int):
        n, axis = self.n_shards, self.axis
        bucket_cap = self.bucket_cap or max(64, (2 * chunk_cap) // n)
        track_bucket_cap(self, bucket_cap)
        own_keys = self.left_keys if arrival == "l" else self.right_keys
        other_keys = self.right_keys if arrival == "l" else self.left_keys
        own_names = self.left_names if arrival == "l" else self.right_names
        other_names = self.right_names if arrival == "l" else self.left_names
        join_type, out_cap, out_names = (
            self.join_type,
            self.out_cap,
            self.out_names,
        )

        def local(own, other, em_ovf, chunk):
            own, other, em_ovf, chunk = jax.tree.map(
                lambda a: a[0], (own, other, em_ovf, chunk)
            )
            lanes = tuple(chunk.col(k) for k in own_keys)
            rchunk, ex_ovf, ex_counts = exchange_chunk(
                chunk, lanes, n, bucket_cap, axis
            )
            own, other, cols, nulls, ops, valid, ovf = join_step_fn(
                own,
                other,
                rchunk,
                own_keys,
                other_keys,
                own_names,
                other_names,
                out_cap,
                join_type,
                arrival,
                out_names,
            )
            out = StreamChunk(columns=cols, valid=valid, nulls=nulls, ops=ops)
            em_ovf = em_ovf | ovf | ex_ovf
            ex = lambda t: jax.tree.map(lambda a: a[None], t)
            return ex(own), ex(other), ex(em_ovf), ex(out), ex_counts[None]

        spec = P(self.axis)
        return jax.jit(
            jax.shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec,) * 4,
                out_specs=(spec,) * 5,
                check_vma=False,
            ),
            donate_argnums=(0, 1, 2),
        )

    def _apply(self, arrival: str, chunk: StreamChunk) -> List[StreamChunk]:
        key = (arrival, chunk.valid.shape[-1])
        step = self._steps.get(key)
        if step is None:
            step = self._steps[key] = self._build_step(*key)
        own, other = (
            (self.left, self.right)
            if arrival == "l"
            else (self.right, self.left)
        )
        own, other, self._em_overflow, out, self.ex_counts_last = step(
            own, other, self._em_overflow, chunk
        )
        if arrival == "l":
            self.left, self.right = own, other
        else:
            self.right, self.left = own, other
        return [out]

    def apply_left(self, chunk: StreamChunk) -> List[StreamChunk]:
        return self._apply("l", chunk)

    def apply_right(self, chunk: StreamChunk) -> List[StreamChunk]:
        return self._apply("r", chunk)

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        raise TypeError("ShardedHashJoin is two-input: use apply_left/right")

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        if bool(jnp.any(self._em_overflow)):
            raise RuntimeError(
                "sharded join emission/exchange overflowed; raise out_cap "
                "or bucket_cap"
            )
        for name, side in (("left", self.left), ("right", self.right)):
            if bool(jnp.any(side.overflow)):
                raise RuntimeError(
                    f"{name} sharded join side overflowed (fanout/probe); "
                    "grow fanout/capacity"
                )
            if bool(jnp.any(side.inconsistent)):
                raise RuntimeError(
                    f"{name} sharded join side saw a DELETE matching no "
                    "stored row"
                )
        return []

    # -- static contracts (analysis/) -------------------------------------
    def lint_info(self):
        dtypes = dict(self._lint_left_dtypes)
        dtypes.update(self._lint_right_dtypes)
        return {
            "left_keys": self.left_keys,
            "right_keys": self.right_keys,
            "expects_left": dict(self._lint_left_dtypes),
            "expects_right": dict(self._lint_right_dtypes),
            "emits": {n: dtypes.get(n) for n in self.out_names},
            "table_ids": (self.table_id,),
        }

    def trace_contract(self):
        return {
            "kind": "host",
            "host_reason": "mesh-resident sharded step: per-fragment "
            "SPMD fusion is tracked by the mesh analyzer (RW-E9xx), "
            "not the single-chip fuser",
            "state": (self.left, self.right),
            "donate": True,
            "emission": "fixed",
            "emission_caps": (self.out_cap,),
            "two_input": True,
            "two_input_fusible": False,
            "fallback_syncs": ("on_barrier", "shard_occupancy"),
        }

    def mesh_contract(self):
        def trace_steps(abs_chunk):
            # self-seeded: each arrival's chunk carries that SIDE's
            # lanes (the threaded source spec can't describe both), so
            # build the abstract chunks from the declared schemas and
            # take only capacity/shard count from the caller's chunk
            from risingwave_tpu.analysis.mesh_domain import (
                abstract_tree,
                stacked_schema_chunk,
            )

            cap = int(abs_chunk.valid.shape[-1])
            n = (
                int(abs_chunk.valid.shape[0])
                if getattr(abs_chunk.valid, "ndim", 1) > 1
                else self.n_shards
            )
            left = abstract_tree(self.left)
            right = abstract_tree(self.right)
            em = abstract_tree(self._em_overflow)
            lchunk = stacked_schema_chunk(
                self._lint_left_dtypes, self._lint_left_nulls, cap, n
            )
            rchunk = stacked_schema_chunk(
                self._lint_right_dtypes, self._lint_right_nulls, cap, n
            )
            return [
                (
                    "apply_left",
                    self._build_step("l", cap),
                    (left, right, em, lchunk),
                ),
                (
                    "apply_right",
                    self._build_step("r", cap),
                    (right, left, em, rchunk),
                ),
            ]

        return {
            "axis": self.axis,
            "n_shards": self.n_shards,
            "state": {
                "left": "sharded",
                "right": "sharded",
                "_em_overflow": "sharded",
            },
            "updates": ("left", "right", "_em_overflow"),
            "dispatch": {
                "fn": "dest_shard",
                "keys": {"l": self.left_keys, "r": self.right_keys},
                "vnode_axis": self.axis,
            },
            "exchange": "all_to_all",
            "donate": True,
            "order_insensitive": True,  # emission slots are ordered by
            # (bucket lane, stored slot), both deterministic
            "trace_steps": trace_steps,
            "barrier_methods": ("on_barrier", "shard_occupancy"),
            "emission": "stacked",
        }

    # -- capacity escape (watchdog replay, scale.rs:453 analogue) ---------
    def capacity_overflow_latched(self) -> bool:
        if bool(jnp.any(self._em_overflow)):
            return True
        return any(
            bool(jnp.any(getattr(self, s).overflow))
            for s in ("left", "right")
        )

    def grow_for_replay(self) -> None:
        """Double the overflowed dimension (emission/bucket caps on the
        exchange latch; capacity+fanout on a side latch) and reset both
        sides empty at the new shapes — the mid-epoch state is poisoned
        either way, and recover() restores the durable rows before the
        epoch replays."""
        if bool(jnp.any(self._em_overflow)):
            self.out_cap *= 2
            double_bucket_cap(self)
        side_ovf = any(
            bool(jnp.any(getattr(self, s).overflow))
            for s in ("left", "right")
        )
        f = 2 if side_ovf else 1  # key lanes pair: grow both sides
        for name in ("left", "right"):
            proto = jax.tree.map(lambda a: a[0], getattr(self, name))
            side1 = JoinSide.create(
                proto.capacity * f,
                proto.fanout * f,
                tuple(k.dtype for k in proto.table.keys),
                {nm: a.dtype for nm, a in proto.rows.items()},
                nullable=tuple(proto.row_nulls),
            )
            setattr(
                self, name, stack_for_mesh(side1, self.mesh, self.axis)
            )
        self._em_overflow = stack_for_mesh(
            jnp.zeros((), jnp.bool_), self.mesh, self.axis
        )
        self._steps = {}

    # -- integrity --------------------------------------------------------
    def state_digest(self) -> int:
        """Shard-flattened twin of the single-chip join digest (the
        per-side folds XOR, like HashJoinExecutor.state_digest)."""
        from types import SimpleNamespace

        from risingwave_tpu.integrity import host_digest, join_side_lanes

        def flat(a):
            a = np.asarray(a)
            return a.reshape((-1,) + a.shape[2:])

        def flat_side(side):
            table = SimpleNamespace(
                keys=tuple(flat(k) for k in side.table.keys),
                live=flat(side.table.live),
            )
            return SimpleNamespace(
                table=table,
                rows={n: flat(a) for n, a in side.rows.items()},
                row_nulls={
                    n: flat(a) for n, a in side.row_nulls.items()
                },
                row_valid=flat(side.row_valid),
                degree=flat(side.degree),
            )

        ld = host_digest(*join_side_lanes(flat_side(self.left), np.where))
        rd = host_digest(
            *join_side_lanes(flat_side(self.right), np.where)
        )
        return ld ^ rd

    # -- checkpoint/restore (two logical tables across shards) -----------
    def checkpoint_table_ids(self) -> List[str]:
        return [f"{self.table_id}.left", f"{self.table_id}.right"]

    def checkpoint_delta(self) -> List[StateDelta]:
        """Same lane naming as the single-chip join (_side_delta):
        k{i} key lanes + rv/deg/r_*/n_* 2D bucket lanes, each side ONE
        logical table; keys are globally unique across shards."""
        out = []
        for name in ("left", "right"):
            d = self._sharded_side_delta(name)
            if d is not None:
                out.append(d)
        return out

    def _sharded_side_delta(self, name: str) -> Optional[StateDelta]:
        side = getattr(self, name)
        sdirty = np.asarray(side.sdirty).reshape(-1)
        if not sdirty.any():
            return None
        shape = side.sdirty.shape
        upsert, tomb, sel = stage_marks(
            sdirty,
            np.asarray(side.table.live).reshape(-1),
            np.asarray(side.stored).reshape(-1),
        )
        flat = lambda a: a.reshape((-1,) + a.shape[2:])
        lanes = {f"k{i}": flat(l) for i, l in enumerate(side.table.keys)}
        key_names = tuple(lanes)
        lanes["rv"] = flat(side.row_valid)
        lanes["deg"] = flat(side.degree)
        for nm, a in side.rows.items():
            lanes[f"r_{nm}"] = flat(a)
        for nm, a in side.row_nulls.items():
            lanes[f"n_{nm}"] = flat(a)
        pulled = pull_rows(lanes, sel)
        keys = {k: pulled[k] for k in key_names}
        vals = {k: v for k, v in pulled.items() if k not in key_names}
        setattr(
            self,
            name,
            dataclasses.replace(
                side,
                sdirty=jnp.zeros_like(side.sdirty),
                stored=(
                    side.stored | jnp.asarray(upsert.reshape(shape))
                ) & ~jnp.asarray(tomb.reshape(shape)),
            ),
        )
        return StateDelta(
            f"{self.table_id}.{name}", keys, vals, tomb[sel], key_names
        )

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        """Re-partition one side's recovered rows by vnode (the same
        positional-key hash the exchange uses) and rebuild every shard
        with the single-chip _side_restore — works across mesh sizes."""
        name = "left" if table_id.endswith(".left") else "right"
        side = getattr(self, name)
        proto = jax.tree.map(lambda a: a[0], side)
        n_rows = len(next(iter(key_cols.values()))) if key_cols else 0
        dest = None
        if n_rows:
            lanes = tuple(
                jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=k.dtype))
                for i, k in enumerate(proto.table.keys)
            )
            dest = np.asarray(dest_shard(lanes, self.n_shards))
            # uniform per-shard capacity: _side_restore grows from the
            # template's capacity, so pre-grow the template to the
            # hottest shard's need and every shard lands on one shape
            cap = grow_pow2(
                int(np.bincount(dest, minlength=self.n_shards).max()),
                proto.capacity,
                GROW_AT,
            )
        else:
            cap = proto.capacity
        template = JoinSide.create(
            cap,
            proto.fanout,
            tuple(k.dtype for k in proto.table.keys),
            {nm: a.dtype for nm, a in proto.rows.items()},
            nullable=tuple(proto.row_nulls),
        )
        sides = []
        for k in range(self.n_shards):
            if n_rows:
                m = dest == k
                sub_k = {kk: v[m] for kk, v in key_cols.items()}
                sub_v = {kk: v[m] for kk, v in value_cols.items()}
            else:
                sub_k, sub_v = {}, {}
            sides.append(_side_restore(template, sub_k, sub_v))
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *sides)
        setattr(
            self,
            name,
            jax.device_put(stacked, NamedSharding(self.mesh, P(self.axis))),
        )
        self._em_overflow = stack_for_mesh(
            jnp.zeros((), jnp.bool_), self.mesh, self.axis
        )
        self._steps = {}  # capacities may have changed: recompile


# -- mesh observability surface (meshprof / scale / memory governor) ------
def stacked_state_nbytes_per_shard(self) -> List[int]:
    """Uniform split of the stacked device state: every per-slot array
    carries the same ``(n_shards, ...)`` shape, so per-shard bytes are
    exactly total/n with NO device read — the rw_memory per-shard rows
    and meshprof's state_bytes lane."""
    n = self.n_shards
    return [self.state_nbytes() // n] * n


def _sharded_dedup_state_nbytes(self) -> int:
    return int(
        sum(
            leaf.nbytes
            for leaf in jax.tree.leaves(
                (self.table, self.sdirty, self.flags)
            )
        )
    )


def _sharded_dedup_shard_occupancy(self):
    """Per-shard claimed-slot counts (autoscale + skew input). One
    packed device read."""
    return np.asarray(
        jnp.sum((self.table.fp1 != jnp.uint32(0)).astype(jnp.int32), axis=1)
    )


def _sharded_join_state_nbytes(self) -> int:
    return int(
        sum(
            leaf.nbytes
            for leaf in jax.tree.leaves((self.left, self.right))
        )
    )


def _sharded_join_shard_occupancy(self):
    occ = jnp.sum(
        (self.left.table.fp1 != jnp.uint32(0)).astype(jnp.int32), axis=1
    ) + jnp.sum(
        (self.right.table.fp1 != jnp.uint32(0)).astype(jnp.int32), axis=1
    )
    return np.asarray(occ)


ShardedDedup.state_nbytes = _sharded_dedup_state_nbytes
ShardedDedup.state_nbytes_per_shard = stacked_state_nbytes_per_shard
ShardedDedup.shard_occupancy = _sharded_dedup_shard_occupancy
ShardedHashJoin.state_nbytes = _sharded_join_state_nbytes
ShardedHashJoin.state_nbytes_per_shard = stacked_state_nbytes_per_shard
ShardedHashJoin.shard_occupancy = _sharded_join_shard_occupancy
