"""Mesh observability — per-shard barrier attribution, exchange-cost
matrix, and hot-shard skew verdicts for the multi-chip path (ISSUE 18).

ROADMAP item 3 (mesh scale-out, exchange as on-device collectives) needs
a measured baseline before the exchange moves into the fused program —
the same play PR 6 ran on the serial path, where the profiler pinned the
319 ms dispatch wall before PR 10 killed it. This module is that
profiler for the sharded graph:

1. **Per-shard barrier attribution.** ``watch(pipeline)`` wraps the
   *instances* of the sharded executors (``ShardedHashAgg``,
   ``ShardedDedup``, ``ShardedHashJoin``, ``ShardedMaterialize``,
   ``ShardedGroupTopN``) plus the host boundary lanes
   (``StackSplitExecutor`` -> ``host_split``, ``FlattenExecutor`` ->
   ``host_flatten``, everything else in the chain -> ``host_other``).
   Each wrapped call is fenced on the executor's small status leaf
   (``dropped``/``flags``/``_em_overflow``) so its wall is a real
   device-inclusive measurement, and a barrier window's attributed
   time is the sum of those walls. Instance wrapping (not class
   wrapping) keeps a serial twin pipeline in the same process
   completely unperturbed — the bit-identity contract.

2. **Exchange-cost matrix.** ``pack_buckets`` already computes every
   shard's per-destination routed-row histogram (it feeds the overflow
   flag), so the sharded executors thread it out of their existing
   jitted step as one tiny extra output (``ex_counts_last``, a stacked
   ``(n_shards, n_shards)`` int32 — row = source shard). The wrapped
   apply just keeps a reference; the window close reads the tiny
   arrays (the barrier already drained the queue), sums them into the
   per-barrier (src, dst) delta, and feeds
   ``exchange_rows_total{src,dst}`` / ``exchange_bytes_total{src,dst}``
   plus the per-barrier traffic matrix on the trace. No second hash
   pass, no extra program on the apply path — armed and unarmed runs
   execute the byte-identical step. Barrier-flush re-exchange traffic
   (agg flush rounds) is NOT counted — the matrix measures
   input-driven exchange, the part the future collective fusion
   ratchets against.

3. **pack/route/unpack phase split.** Per (executor, chunk-cap) the
   close calibrates three one-shot probe programs built from the real
   ``exchange.py`` internals (pack only / pack+route / full exchange,
   outputs kept live through cheap reductions so XLA cannot DCE them),
   takes the min of ``PROBE_REPS`` post-compile runs, and scales by the
   window's apply count; shard-local time is the clamped residual.
   Probes are a one-time cost (``calibration_ms``), never on the steady
   path, and can be disabled (``enable(probes=False)``).

4. **Hot-shard skew verdict.** Per close, each executor's rows-in
   vector (delta-matrix column sums) is tested: max/mean >=
   ``RW_SKEW_RATIO`` with at least ``RW_SKEW_MIN_ROWS`` routed rows
   folds — like PR 16's ``backpressure_fragment`` — into ONE
   ``skew_shard`` verdict per barrier (worst executor wins), a
   ``shard_skew_frac`` gauge, and at most one structured ``skew``
   event per close.

The pipeline hooks (``GraphPipeline.wait_barrier``,
``Pipeline.barrier``, ``TwoInputPipeline.barrier``) call
``pipeline_barrier(pipeline)`` to close the window;
``StreamingRuntime._end_trace`` drains pending windows onto
``EpochTrace.mesh`` (and into ``barrier_stage_ms`` as ``mesh_*`` /
per-shard stages, so the existing dashboards, blackbox ring and
Perfetto lanes pick the sections up). ``rw_shards`` / ``rw_exchange``
system tables read ``table_snapshot()`` — lock-copied host dicts,
never a device sync. Unarmed (the default), nothing is wrapped and the
hot path is untouched.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from risingwave_tpu.event_log import EVENT_LOG
from risingwave_tpu.executors.hash_agg import _build_key_lanes
from risingwave_tpu.metrics import REGISTRY
from risingwave_tpu.parallel.exchange import (
    dest_shard,
    exchange_chunk,
    exchange_cols,
    pack_buckets,
)

SKEW_RATIO = float(os.environ.get("RW_SKEW_RATIO", "2.0"))
SKEW_MIN_ROWS = int(os.environ.get("RW_SKEW_MIN_ROWS", "64"))
PROBE_REPS = 2  # post-compile timing runs per probe; min is kept

# executor class name -> lane kind. Exchange kinds carry routed-row
# counts + probes; host kinds only contribute wall to the phase split.
_EXCHANGE_KINDS = {
    "ShardedHashAgg": "agg",
    "ShardedDedup": "dedup",
    "ShardedHashJoin": "join",
    "ShardedMaterialize": "mv",
    "ShardedGroupTopN": "top_n",
}
_HOST_KINDS = {
    "StackSplitExecutor": "host_split",
    "FlattenExecutor": "host_flatten",
}

# the small always-present status leaf each sharded class updates every
# apply — blocking on it fences the whole step without touching state
_FENCES = {
    "agg": lambda ex: ex.dropped,
    "dedup": lambda ex: ex.flags,
    "join": lambda ex: ex._em_overflow,
    "mv": lambda ex: ex.state.dropped,
    "top_n": lambda ex: ex.dropped,
}

_PHASES = (
    "pack",
    "route",
    "unpack",
    "shard_local",
    "host_split",
    "host_flatten",
    "host_other",
)


def _key_fn_for(ex, kind: str, arrival: Optional[str]):
    """The exchange-key builder matching what the executor's own
    ``_build_step`` routes on. Captures only immutable tuples — never
    the executor itself (the profiler must not keep dead executors
    alive after kill+recover)."""
    if kind == "agg":
        gk, nb = ex.group_keys, ex.nullable
        return lambda c: _build_key_lanes(c, gk, nb)
    if kind == "dedup":
        ks = ex.keys
    elif kind == "join":
        ks = ex.left_keys if arrival == "l" else ex.right_keys
    elif kind == "mv":
        ks = ex.pk
    else:  # top_n
        ks = ex.group_by
    return lambda c: tuple(c.col(k) for k in ks)


def _build_probe(mesh, axis: str, n_shards: int, bucket_cap: int, key_fn,
                 stage: str):
    """One phase-probe program: the real exchange pipeline cut after
    ``stage`` ("pack" | "route" | "full"), with every produced buffer
    reduced into a scalar so XLA keeps the full work live."""

    def local(chunk):
        c = jax.tree.map(lambda a: a[0], chunk)
        lanes = key_fn(c)
        if stage == "full":
            rc, ovf, _cts = exchange_chunk(c, lanes, n_shards, bucket_cap, axis)
            acc = jnp.sum(rc.valid.astype(jnp.int32)) + ovf.astype(jnp.int32)
            for col in rc.columns.values():
                acc = acc + jnp.sum((col != 0).astype(jnp.int32))
            return acc[None]
        dest = dest_shard(lanes, n_shards)
        bufs, vbuf, ovf, _ = pack_buckets(
            exchange_cols(c), c.valid, dest, n_shards, bucket_cap
        )
        if stage == "route":
            bufs = {
                nm: jax.lax.all_to_all(b, axis, 0, 0, tiled=False)
                for nm, b in bufs.items()
            }
            vbuf = jax.lax.all_to_all(vbuf, axis, 0, 0, tiled=False)
        acc = jnp.sum(vbuf.astype(jnp.int32)) + ovf.astype(jnp.int32)
        for b in bufs.values():
            acc = acc + jnp.sum((b != 0).astype(jnp.int32))
        return acc[None]

    spec = P(axis)
    return jax.jit(
        jax.shard_map(
            local, mesh=mesh, in_specs=(spec,), out_specs=spec,
            check_vma=False,
        )
    )


def _chunk_row_bytes(chunk) -> int:
    """Bytes one exchanged row carries: every column + the ops lane +
    null lanes + the valid bit — the lanes ``exchange_chunk`` actually
    ships through all_to_all."""
    total = chunk.ops.dtype.itemsize + chunk.valid.dtype.itemsize
    for col in chunk.columns.values():
        total += col.dtype.itemsize
    for lane in chunk.nulls.values():
        total += lane.dtype.itemsize
    return int(total)


class _ExecInfo:
    """Per watched executor: weakly referenced (kill+recover must not
    leave orphaned lanes), with the probe caches living here so they
    die with the watch, not with the class."""

    __slots__ = (
        "ref", "kind", "lane", "table_id", "owner", "pipe_name",
        "n_shards", "wrapped", "probe_ms", "templates", "bytes_per_row",
        "occ_cache", "occ_age",
    )

    def __init__(self, ex, kind: str, lane: str, owner: int,
                 pipe_name: str):
        self.ref = weakref.ref(ex)
        self.kind = kind
        self.lane = lane
        self.table_id = getattr(ex, "table_id", type(ex).__name__)
        self.owner = owner
        self.pipe_name = pipe_name
        self.n_shards = int(getattr(ex, "n_shards", 0) or 0)
        self.wrapped: List[str] = []
        self.probe_ms: Dict[Any, tuple] = {}
        self.templates: Dict[Any, Any] = {}
        self.bytes_per_row: Dict[Any, int] = {}
        self.occ_cache = None  # last shard_occupancy read (host int64)
        self.occ_age = 0  # closes since that read


class MeshProfiler:
    """Process singleton (``MESHPROF``). Thread-safe: the sharded graph
    runs executors on FragmentActor threads while the driver closes
    windows from ``wait_barrier``."""

    def __init__(self):
        self._lock = threading.RLock()
        self.enabled = False
        self.probes_enabled = True
        self.host_ms = 0.0  # steady-path self-measured bookkeeping
        self.calibration_ms = 0.0  # one-time probe compiles/timing
        self.errors = 0
        self.barrier_count = 0
        self.barriers: deque = deque(maxlen=64)  # mesh docs, newest last
        self._pending: deque = deque(maxlen=16)  # awaiting runtime drain
        self._execs: Dict[int, _ExecInfo] = {}  # id(ex) -> info
        self._window: Dict[int, dict] = {}  # id(info) -> open entry
        self._tables: Dict[str, dict] = {}  # table_id -> host snapshot
        self._ex_n = 0
        self._ex_rows = None  # cumulative np (n, n) rows
        self._ex_bytes = None
        self._ex_rows_last = None  # last barrier's delta
        self._ex_bytes_last = None

    # -- arming -----------------------------------------------------------
    def enable(self, probes: bool = True) -> None:
        with self._lock:
            self.enabled = True
            self.probes_enabled = bool(probes)

    def disable(self) -> None:
        with self._lock:
            self.enabled = False
            for info in self._execs.values():
                ex = info.ref()
                if ex is None:
                    continue
                for m in info.wrapped:
                    ex.__dict__.pop(m, None)
            self._execs.clear()
            self._window.clear()
            self._pending.clear()

    def reset_stats(self) -> None:
        """Zero the meters (gates measure deltas across a run)."""
        with self._lock:
            self.host_ms = 0.0
            self.calibration_ms = 0.0
            self.errors = 0
            self.barrier_count = 0
            self.barriers.clear()
            self._pending.clear()
            self._tables.clear()
            self._ex_n = 0
            self._ex_rows = self._ex_bytes = None
            self._ex_rows_last = self._ex_bytes_last = None

    def watch(self, pipeline, name: str = "pipeline") -> int:
        """Instance-wrap a pipeline's sharded chain. No-op unless armed
        and the chain actually contains a sharded executor (a serial
        pipeline in the same process stays untouched). Returns the
        number of executors newly wrapped."""
        if not self.enabled:
            return 0
        exs = getattr(pipeline, "executors", None)
        if callable(exs):
            exs = exs()
        exs = list(exs or ())
        if not any(type(e).__name__ in _EXCHANGE_KINDS for e in exs):
            return 0
        n = 0
        with self._lock:
            for ex in exs:
                if id(ex) in self._execs:
                    continue
                cls = type(ex).__name__
                if cls in _EXCHANGE_KINDS:
                    kind, lane = _EXCHANGE_KINDS[cls], "exec"
                elif cls in _HOST_KINDS:
                    kind = lane = _HOST_KINDS[cls]
                else:
                    kind = lane = "host_other"
                try:
                    info = _ExecInfo(ex, kind, lane, id(pipeline), name)
                except TypeError:
                    continue  # not weakref-able: skip, never fault
                if kind in _EXCHANGE_KINDS.values():
                    if kind == "join":
                        self._wrap(info, ex, "apply_left", True, "l")
                        self._wrap(info, ex, "apply_right", True, "r")
                    else:
                        self._wrap(info, ex, "apply", True, None)
                    if hasattr(ex, "on_barrier"):
                        self._wrap(info, ex, "on_barrier", False, None)
                else:
                    self._wrap(info, ex, "apply", False, None)
                self._execs[id(ex)] = info
                n += 1
        return n

    def _wrap(self, info: _ExecInfo, ex, method: str, count: bool,
              arrival: Optional[str]) -> None:
        orig = getattr(ex, method)
        fence = _FENCES.get(info.kind)
        exref = info.ref
        prof = self

        def wrapped(*a, **kw):
            if not prof.enabled:
                return orig(*a, **kw)
            t0 = time.perf_counter()
            ret = orig(*a, **kw)
            try:
                tgt = fence(exref()) if fence is not None else ret
                if tgt is not None:
                    jax.block_until_ready(tgt)
            except Exception:
                pass  # fencing is best-effort; never fault the step
            t1 = time.perf_counter()
            try:
                chunk = a[0] if (count and a) else None
                prof._record(info, t0, t1, chunk, arrival)
            except Exception:
                prof.errors += 1
            prof.host_ms += (time.perf_counter() - t1) * 1e3
            return ret

        setattr(ex, method, wrapped)
        info.wrapped.append(method)

    # -- the hot path -----------------------------------------------------
    def _record(self, info: _ExecInfo, t0: float, t1: float, chunk,
                arrival: Optional[str]) -> None:
        with self._lock:
            entry = self._window.get(id(info))
            if entry is None:
                entry = self._window[id(info)] = {
                    "info": info,
                    "t_first": t0,
                    "wall_ms": 0.0,
                    "applies": {},
                    "counts": [],
                }
            entry["t_first"] = min(entry["t_first"], t0)
            entry["wall_ms"] += (t1 - t0) * 1e3
            if chunk is None or getattr(chunk.valid, "ndim", 1) != 2:
                return
            ex = info.ref()
            if ex is None:
                return
            cap = int(chunk.valid.shape[-1])
            capkey = (cap, arrival)
            entry["applies"][capkey] = entry["applies"].get(capkey, 0) + 1
            # the executor's own jitted step already computed this
            # apply's (src, dst) routed-row histogram (pack_buckets
            # feeds it into overflow detection) and threads it out as
            # ``ex_counts_last`` — keep the tiny device ref; the close
            # reads it after the barrier drained the queue. Zero extra
            # programs on the apply path.
            cts = getattr(ex, "ex_counts_last", None)
            if cts is not None:
                entry["counts"].append(cts)
            if capkey not in info.bytes_per_row:
                info.bytes_per_row[capkey] = _chunk_row_bytes(chunk)
            if (
                self.probes_enabled
                and capkey not in info.probe_ms
                and capkey not in info.templates
            ):
                info.templates[capkey] = chunk  # probe calibration input

    # -- window close -----------------------------------------------------
    def pipeline_barrier(self, pipeline) -> Optional[dict]:
        """Close this pipeline's window: read the tiny per-apply count
        outputs, phase split, skew verdict, counters, trace doc.
        Called from the pipeline's barrier (driver thread, actors
        idle). Never faults the barrier."""
        if not self.enabled:
            return None
        t0 = time.perf_counter()
        with self._lock:
            picked = [
                self._window.pop(k)
                for k in [
                    k
                    for k, e in self._window.items()
                    if e["info"].owner == id(pipeline)
                ]
            ]
        if not picked:
            return None
        doc = None
        cal_ms = 0.0
        try:
            for e in picked:
                cal_ms += self._calibrate(e)
            doc = self._close(picked)
        except Exception:
            self.errors += 1
        if doc is not None:
            with self._lock:
                self.barrier_count += 1
                self.barriers.append(doc)
                self._pending.append(doc)
        self.calibration_ms += cal_ms
        self.host_ms += (time.perf_counter() - t0) * 1e3 - cal_ms
        return doc

    def _calibrate(self, entry: dict) -> float:
        """One-time pack/route/unpack probe timing for any (cap,
        arrival) this window exercised and has a template for. Returns
        the wall spent calibrating (booked to ``calibration_ms``)."""
        info = entry["info"]
        if not self.probes_enabled or not info.templates:
            return 0.0
        ex = info.ref()
        if ex is None:
            info.templates.clear()
            return 0.0
        c0 = time.perf_counter()
        for capkey in list(entry["applies"]):
            if capkey in info.probe_ms:
                info.templates.pop(capkey, None)
                continue
            tmpl = info.templates.pop(capkey, None)
            if tmpl is None:
                continue
            cap, arrival = capkey
            bucket_cap = getattr(ex, "bucket_cap", None) or max(
                64, (2 * cap) // info.n_shards
            )
            key_fn = _key_fn_for(ex, info.kind, arrival)
            stages = {}
            try:
                for stage in ("pack", "route", "full"):
                    fn = _build_probe(
                        ex.mesh, ex.axis, info.n_shards, bucket_cap,
                        key_fn, stage,
                    )
                    jax.block_until_ready(fn(tmpl))  # compile + warm
                    best = float("inf")
                    for _ in range(PROBE_REPS):
                        p0 = time.perf_counter()
                        jax.block_until_ready(fn(tmpl))
                        best = min(best, time.perf_counter() - p0)
                    stages[stage] = best * 1e3
            except Exception:
                self.errors += 1
                continue
            pack = stages["pack"]
            route = max(0.0, stages["route"] - stages["pack"])
            unpack = max(0.0, stages["full"] - stages["route"])
            info.probe_ms[capkey] = (pack, route, unpack)
        return (time.perf_counter() - c0) * 1e3

    def _close(self, picked: List[dict]) -> dict:
        t_close = time.perf_counter()
        infos = [e["info"] for e in picked]
        n = max([i.n_shards for i in infos if i.n_shards] or [0])
        wall_ms = (t_close - min(e["t_first"] for e in picked)) * 1e3
        attributed = sum(e["wall_ms"] for e in picked)
        wall_ms = max(wall_ms, attributed)
        phases = {p: 0.0 for p in _PHASES}
        shard_local = np.zeros(max(n, 1))
        rows_in = np.zeros(max(n, 1), np.int64)
        occupancy = np.zeros(max(n, 1), np.int64)
        state_bytes = np.zeros(max(n, 1), np.int64)
        ex_rows = np.zeros((max(n, 1), max(n, 1)), np.int64)
        ex_bytes = np.zeros((max(n, 1), max(n, 1)), np.int64)
        best_skew = None
        c_rows = REGISTRY.counter("exchange_rows_total")
        c_bytes = REGISTRY.counter("exchange_bytes_total")

        for e in picked:
            info = e["info"]
            if info.lane != "exec":
                phases[info.lane] += e["wall_ms"]
                continue
            ex = info.ref()
            # phase split from calibrated probes, scaled by applies
            pack = route = unpack = 0.0
            for capkey, n_app in e["applies"].items():
                p = info.probe_ms.get(capkey)
                if p:
                    pack += p[0] * n_app
                    route += p[1] * n_app
                    unpack += p[2] * n_app
            probe_total = pack + route + unpack
            if probe_total > 0.9 * e["wall_ms"] and probe_total > 0:
                s = 0.9 * e["wall_ms"] / probe_total
                pack, route, unpack = pack * s, route * s, unpack * s
            local = max(0.0, e["wall_ms"] - (pack + route + unpack))
            phases["pack"] += pack
            phases["route"] += route
            phases["unpack"] += unpack
            phases["shard_local"] += local

            # sum this window's per-apply count outputs (tiny (n, n)
            # device arrays the executor's own step produced; the
            # barrier already drained the queue so each read is a
            # 256-byte transfer, not a wait)
            delta = np.zeros((max(n, 1), max(n, 1)), np.int64)
            for cts in e.get("counts", ()):
                try:
                    c = np.asarray(cts, np.int64)
                    if c.shape == delta.shape:
                        delta += c
                except Exception:
                    self.errors += 1
            e["counts"] = ()
            bpr = (
                int(np.mean(list(info.bytes_per_row.values())))
                if info.bytes_per_row
                else 0
            )
            dbytes = delta * bpr
            ex_rows += delta
            ex_bytes += dbytes
            for i, j in zip(*np.nonzero(delta)):
                c_rows.inc(int(delta[i, j]), src=str(int(i)),
                           dst=str(int(j)))
                c_bytes.inc(int(dbytes[i, j]), src=str(int(i)),
                            dst=str(int(j)))

            rin = delta.sum(axis=0)  # rows each dst shard received
            rows_in += rin
            tot = int(rin.sum())
            if tot > 0:
                shard_local += local * (rin / tot)
            elif n:
                shard_local += local / n

            occ = None
            if ex is not None and hasattr(ex, "shard_occupancy"):
                # occupancy drifts slowly but each read is an eager
                # device reduction + sync (~2.5ms on the 8-way CPU
                # sim): refresh every 4th close, reuse in between
                info.occ_age += 1
                if info.occ_cache is None or info.occ_age >= 4:
                    try:
                        fresh = np.asarray(ex.shard_occupancy(), np.int64)
                        info.occ_cache, info.occ_age = fresh, 0
                    except Exception:
                        pass
                occ = info.occ_cache
                if occ is not None and occ.shape[0] == n:
                    occupancy = np.maximum(occupancy, occ)
                else:
                    occ = None
            sb = None
            if ex is not None and hasattr(ex, "state_nbytes_per_shard"):
                try:
                    sb = np.asarray(ex.state_nbytes_per_shard(), np.int64)
                    if sb.shape[0] == n:
                        state_bytes += sb
                except Exception:
                    sb = None

            ratio = 0.0
            if tot >= SKEW_MIN_ROWS and n > 1:
                ratio = float(rin.max() / (tot / n))
                if ratio >= SKEW_RATIO and (
                    best_skew is None or ratio > best_skew["ratio"]
                ):
                    best_skew = {
                        "shard": int(rin.argmax()),
                        "ratio": round(ratio, 3),
                        "frac": round(float(rin.max() / tot), 4),
                        "table_id": info.table_id,
                        "rows": tot,
                    }

            with self._lock:
                t = self._tables.setdefault(
                    info.table_id, {"rows_in_total": [0] * max(n, 1),
                                    "barriers": 0}
                )
                prev_tot = np.asarray(t["rows_in_total"], np.int64)
                if prev_tot.shape[0] != max(n, 1):
                    prev_tot = np.zeros(max(n, 1), np.int64)
                t.update(
                    executor=(
                        type(ex).__name__ if ex is not None else "dead"
                    ),
                    pipeline=info.pipe_name,
                    n_shards=n,
                    rows_in_last=[int(v) for v in rin],
                    rows_in_total=[int(v) for v in prev_tot + rin],
                    occupancy=(
                        [int(v) for v in occ] if occ is not None else None
                    ),
                    state_bytes_per_shard=(
                        [int(v) for v in sb] if sb is not None else None
                    ),
                    local_ms_last=round(local, 3),
                    skew_ratio_last=round(ratio, 3),
                    barriers=t["barriers"] + 1,
                )

        coverage = attributed / wall_ms if wall_ms > 0 else 1.0
        with self._lock:
            if self._ex_rows is None or self._ex_n != n:
                self._ex_n = n
                self._ex_rows = np.zeros((max(n, 1), max(n, 1)), np.int64)
                self._ex_bytes = np.zeros((max(n, 1), max(n, 1)), np.int64)
            self._ex_rows += ex_rows
            self._ex_bytes += ex_bytes
            self._ex_rows_last = ex_rows
            self._ex_bytes_last = ex_bytes

        g = REGISTRY.gauge("shard_skew_frac")
        g.set(best_skew["frac"] if best_skew else 0.0)
        REGISTRY.gauge("mesh_coverage_frac").set(round(coverage, 4))
        REGISTRY.counter("mesh_barriers_total").inc()
        if best_skew:
            REGISTRY.counter("skew_verdicts_total").inc(
                shard=str(best_skew["shard"])
            )
            EVENT_LOG.record(
                "skew",
                table_id=best_skew["table_id"],
                shard=best_skew["shard"],
                ratio=best_skew["ratio"],
                frac=best_skew["frac"],
                rows=best_skew["rows"],
            )
        return {
            "n_shards": n,
            "wall_ms": round(wall_ms, 3),
            "attributed_ms": round(attributed, 3),
            "coverage_frac": round(coverage, 4),
            "phases_ms": {p: round(v, 3) for p, v in phases.items()},
            "shard_local_ms": [round(float(v), 3) for v in shard_local],
            "rows_in": [int(v) for v in rows_in],
            "occupancy": [int(v) for v in occupancy],
            "state_bytes": [int(v) for v in state_bytes],
            "exchange": {
                "rows": ex_rows.tolist(),
                "bytes": ex_bytes.tolist(),
            },
            "skew": best_skew,
        }

    # -- trace feed -------------------------------------------------------
    def observe_barrier(self, runtime, tr) -> None:
        """Runtime barrier hook: drain pending window docs (one per
        sharded pipeline that closed since the last trace) into ONE
        ``tr.mesh`` block + ``barrier_stage_ms`` mesh/per-shard stages.
        Mirrors MemoryGovernor.observe_barrier: enabled-gated,
        exception-proof, self-timed."""
        if not self.enabled:
            return
        t0 = time.perf_counter()
        try:
            with self._lock:
                pend = list(self._pending)
                self._pending.clear()
            if not pend:
                return
            tr.mesh = self.fold(pend)
            for ph, ms in tr.mesh["phases_ms"].items():
                if ms > 0:
                    tr.add_stage(f"mesh_{ph}", ms)
            for i, ms in enumerate(tr.mesh["shard_local_ms"]):
                if ms > 0:
                    tr.add_stage("shard_local", ms, fragment=f"shard{i}")
        except Exception:
            self.errors += 1
        finally:
            self.host_ms += (time.perf_counter() - t0) * 1e3

    @staticmethod
    def fold(docs: List[dict]) -> dict:
        """Fold several per-pipeline window docs into one barrier doc:
        walls/phases/matrices sum, occupancy takes the max level, the
        worst skew verdict wins."""
        if len(docs) == 1:
            return docs[0]
        n = max(d["n_shards"] for d in docs)

        def vec(key):
            out = np.zeros(max(n, 1))
            for d in docs:
                v = np.asarray(d[key], float)
                out[: v.shape[0]] += v
            return out

        ex_rows = np.zeros((max(n, 1), max(n, 1)), np.int64)
        ex_bytes = np.zeros((max(n, 1), max(n, 1)), np.int64)
        occ = np.zeros(max(n, 1), np.int64)
        for d in docs:
            m = np.asarray(d["exchange"]["rows"], np.int64)
            ex_rows[: m.shape[0], : m.shape[1]] += m
            m = np.asarray(d["exchange"]["bytes"], np.int64)
            ex_bytes[: m.shape[0], : m.shape[1]] += m
            o = np.asarray(d["occupancy"], np.int64)
            occ[: o.shape[0]] = np.maximum(occ[: o.shape[0]], o)
        wall = sum(d["wall_ms"] for d in docs)
        att = sum(d["attributed_ms"] for d in docs)
        skews = [d["skew"] for d in docs if d["skew"]]
        return {
            "n_shards": n,
            "wall_ms": round(wall, 3),
            "attributed_ms": round(att, 3),
            "coverage_frac": round(att / wall, 4) if wall > 0 else 1.0,
            "phases_ms": {
                p: round(sum(d["phases_ms"].get(p, 0.0) for d in docs), 3)
                for p in _PHASES
            },
            "shard_local_ms": [
                round(float(v), 3) for v in vec("shard_local_ms")
            ],
            "rows_in": [int(v) for v in vec("rows_in")],
            "occupancy": [int(v) for v in occ],
            "state_bytes": [int(v) for v in vec("state_bytes")],
            "exchange": {
                "rows": ex_rows.tolist(),
                "bytes": ex_bytes.tolist(),
            },
            "skew": (
                max(skews, key=lambda s: s["ratio"]) if skews else None
            ),
        }

    # -- read surfaces ----------------------------------------------------
    def orphans(self) -> List[str]:
        """Window/watch entries whose executor died without a close
        (the PR 5/6/8 orphan-audit surface) — returned, then pruned.
        A clean kill+recover leaves this empty."""
        with self._lock:
            stale = sorted(
                {
                    e["info"].table_id
                    for e in self._window.values()
                    if e["info"].ref() is None
                }
            )
            self._window = {
                k: e
                for k, e in self._window.items()
                if e["info"].ref() is not None
            }
            self._execs = {
                k: i for k, i in self._execs.items() if i.ref() is not None
            }
        return stale

    def table_snapshot(self) -> dict:
        """Lock-copied host dicts for rw_shards / rw_exchange and the
        stall dump — never a device sync, safe from any thread."""
        with self._lock:
            tables = {k: dict(v) for k, v in sorted(self._tables.items())}
            ex = {
                "n_shards": self._ex_n,
                "rows": (
                    self._ex_rows.tolist()
                    if self._ex_rows is not None
                    else []
                ),
                "bytes": (
                    self._ex_bytes.tolist()
                    if self._ex_bytes is not None
                    else []
                ),
                "rows_last": (
                    self._ex_rows_last.tolist()
                    if self._ex_rows_last is not None
                    else []
                ),
                "bytes_last": (
                    self._ex_bytes_last.tolist()
                    if self._ex_bytes_last is not None
                    else []
                ),
            }
            last = self.barriers[-1] if self.barriers else None
            return {
                "enabled": self.enabled,
                "tables": tables,
                "exchange": ex,
                "last_barrier": last,
                "barriers": self.barrier_count,
                "host_ms": round(self.host_ms, 3),
                "calibration_ms": round(self.calibration_ms, 3),
                "errors": self.errors,
            }


MESHPROF = MeshProfiler()
