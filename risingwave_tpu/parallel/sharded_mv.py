"""Mesh-sharded materialized view: pk-partitioned device MV state.

Reference roles replaced (SURVEY.md §2.11; VERDICT r4 #6):
- N parallel MaterializeExecutor actors each owning the vnode slice of
  the MV's pk space (src/stream/src/executor/mview/materialize.rs:44,
  distributed by the fragment's hash exchange, dispatch.rs:683);
- the batch-read storage table serving point/snapshot reads over those
  slices (src/storage/src/table/batch_table/).

TPU re-design: the MV's pk hash table + value lanes gain a leading
``(n_shards,)`` axis sharded over the mesh; each ``apply`` is ONE
jitted ``shard_map`` program — vnode exchange by pk
(``parallel.exchange``) then the single-chip ``mv_step_fn`` kernel on
the received rows. Every pk lives on exactly one shard, so snapshots
concatenate and checkpoints are one logical table (same ``k{j}``/
``v{j}``/``n_{c}`` lane naming as DeviceMaterializeExecutor — either
executor can restore the other's checkpoint, and restore re-partitions
rows by vnode so recovery works across mesh sizes, vnode.rs:34).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.executors.materialize import (
    MvDeviceReadMixin,
    MvDeviceState,
    mv_step_fn,
)
from risingwave_tpu.ops.hash_table import HashTable, lookup, lookup_or_insert
from risingwave_tpu.parallel.exchange import dest_shard, exchange_chunk
from risingwave_tpu.parallel.sharded_join import (
    double_bucket_cap,
    stack_for_mesh,
    stacked_state_nbytes_per_shard,
    track_bucket_cap,
)
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    grow_pow2,
    pull_rows,
    stage_marks,
)

GROW_AT = 0.5


class ShardedMaterialize(MvDeviceReadMixin, Executor, Checkpointable):
    """Vnode-partitioned device MV over a jax Mesh.

    ``apply`` expects STACKED (n_shards, cap) chunks (a sharded join's
    emissions or a sharded agg's stacked flush); rows route to the
    shard owning their pk vnode on ICI, then upsert locally with the
    single-chip kernel. Passes its input through unchanged (the
    Materialize contract — downstream sinks/subscribers see the same
    change stream).

    Schema constraint: fixed-width non-nullable pk lanes (the same
    constraint as DeviceMaterializeExecutor; NULLs in VALUE columns
    ride per-column null lanes).
    """

    def __init__(
        self,
        mesh: Mesh,
        pk: Sequence[str],
        columns: Sequence[str],
        schema_dtypes: Dict[str, object],
        table_id: str = "mview",
        capacity: int = 1 << 16,
        nullable: Sequence[str] = (),
        bucket_cap: Optional[int] = None,
    ):
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_shards = mesh.devices.size
        self.pk = tuple(pk)
        self.columns = tuple(columns)
        self.table_id = table_id
        self.capacity = capacity
        self.bucket_cap = bucket_cap
        self.dtypes = {
            n: jnp.dtype(schema_dtypes[n]) for n in self.pk + self.columns
        }
        table1 = HashTable.create(
            capacity, tuple(self.dtypes[k] for k in self.pk)
        )
        state1 = MvDeviceState(
            values={
                c: jnp.zeros(capacity, self.dtypes[c]) for c in self.columns
            },
            vnulls={
                c: jnp.zeros(capacity, jnp.bool_)
                for c in nullable
                if c in self.columns
            },
            sdirty=jnp.zeros(capacity, jnp.bool_),
            stored=jnp.zeros(capacity, jnp.bool_),
            dropped=jnp.zeros((), jnp.bool_),
        )
        self.table = stack_for_mesh(table1, mesh, self.axis)
        self.state = stack_for_mesh(state1, mesh, self.axis)
        self._steps: Dict[int, object] = {}
        self.checkpoint_enabled = False
        self.ex_counts_last = None  # (n, n) routed-row histogram, device

    # -- the sharded step -------------------------------------------------
    def _build_step(self, chunk_cap: int):
        n, axis, pk, cols = self.n_shards, self.axis, self.pk, self.columns
        bucket_cap = self.bucket_cap or max(64, (2 * chunk_cap) // n)
        track_bucket_cap(self, bucket_cap)

        def local(table, state, chunk):
            table, state, chunk = jax.tree.map(
                lambda a: a[0], (table, state, chunk)
            )
            lanes = tuple(chunk.col(k) for k in pk)
            rchunk, ex_ovf, ex_counts = exchange_chunk(
                chunk, lanes, n, bucket_cap, axis
            )
            table, state = mv_step_fn(table, state, rchunk, pk, cols)
            state = MvDeviceState(
                state.values,
                state.vnulls,
                state.sdirty,
                state.stored,
                state.dropped | ex_ovf,
            )
            ex = lambda t: jax.tree.map(lambda a: a[None], t)
            return ex(table), ex(state), ex_counts[None]

        spec = P(self.axis)
        return jax.jit(
            jax.shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec,) * 3,
                out_specs=(spec,) * 3,
                check_vma=False,
            ),
            donate_argnums=(0, 1),
        )

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        cap = chunk.valid.shape[-1]
        step = self._steps.get(cap)
        if step is None:
            step = self._steps[cap] = self._build_step(cap)
        self.table, self.state, self.ex_counts_last = step(
            self.table, self.state, chunk
        )
        return [chunk]

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        if bool(jnp.any(self.state.dropped)):
            raise RuntimeError(
                "sharded MV overflowed (probe chain or exchange bucket); "
                "grow capacity/bucket_cap"
            )
        return []

    # -- static contracts (analysis/) -------------------------------------
    def lint_info(self):
        return {
            "expects": dict(self.dtypes),
            "state_pk": tuple(self.pk),
            "keys": self.pk,
            "table_ids": (self.table_id,),
        }

    def trace_contract(self):
        return {
            "kind": "host",
            "host_reason": "mesh-resident sharded step: per-fragment "
            "SPMD fusion is tracked by the mesh analyzer (RW-E9xx), "
            "not the single-chip fuser",
            "state": (self.table, self.state),
            "donate": True,
            "emission": "passthrough",
            "fallback_syncs": (
                "on_barrier",
                "_host_rows",
                "get_rows",
                "shard_occupancy",
            ),
        }

    def mesh_contract(self):
        def trace_steps(abs_chunk):
            from risingwave_tpu.analysis.mesh_domain import abstract_tree

            step = self._build_step(int(abs_chunk.valid.shape[-1]))
            return [
                (
                    "apply",
                    step,
                    (
                        abstract_tree(self.table),
                        abstract_tree(self.state),
                        abs_chunk,
                    ),
                )
            ]

        return {
            "axis": self.axis,
            "n_shards": self.n_shards,
            "state": {"table": "sharded", "state": "sharded"},
            "updates": ("table", "state"),
            "dispatch": {
                "fn": "dest_shard",
                "keys": self.pk,
                "vnode_axis": self.axis,
            },
            "exchange": "all_to_all",
            "donate": True,
            "order_insensitive": True,  # pk upserts: last writer per
            # slot, and arrival order within a chunk is preserved by
            # the bucket layout
            "trace_steps": trace_steps,
            "barrier_methods": ("on_barrier", "shard_occupancy"),
            # the serving reads fan out one device probe per
            # destination shard — the E907 scan targets
            "fanout_methods": ("get_rows", "_host_rows"),
            "emission": "passthrough",
        }

    # -- capacity escape (watchdog replay, scale.rs:453 analogue) ---------
    def capacity_overflow_latched(self) -> bool:
        return bool(jnp.any(self.state.dropped))

    def grow_for_replay(self) -> None:
        """Double pk-table capacity + exchange bucket and reset device
        state at the new shapes; recover() restores the durable rows
        before the poisoned epoch replays."""
        self.capacity *= 2
        double_bucket_cap(self)
        nullable = tuple(self.state.vnulls)
        table1 = HashTable.create(
            self.capacity, tuple(self.dtypes[k] for k in self.pk)
        )
        state1 = MvDeviceState(
            values={
                c: jnp.zeros(self.capacity, self.dtypes[c])
                for c in self.columns
            },
            vnulls={
                c: jnp.zeros(self.capacity, jnp.bool_) for c in nullable
            },
            sdirty=jnp.zeros(self.capacity, jnp.bool_),
            stored=jnp.zeros(self.capacity, jnp.bool_),
            dropped=jnp.zeros((), jnp.bool_),
        )
        self.table = stack_for_mesh(table1, self.mesh, self.axis)
        self.state = stack_for_mesh(state1, self.mesh, self.axis)
        self._steps = {}

    def state_nbytes(self) -> int:
        return sum(
            leaf.nbytes for leaf in jax.tree.leaves((self.table, self.state))
        )

    # -- reads ------------------------------------------------------------
    def _host_rows(self):
        """Flatten the shard axis (pks are globally unique) and pull
        every live row — the same one-bulk-transfer contract as
        DeviceMaterializeExecutor._host_rows."""
        flat = lambda a: a.reshape((-1,) + a.shape[2:])
        live = np.asarray(self.table.live).reshape(-1)
        sel = np.flatnonzero(live)
        lanes = {f"k{j}": flat(k) for j, k in enumerate(self.table.keys)}
        lanes.update(
            {
                f"v{j}": flat(self.state.values[c])
                for j, c in enumerate(self.columns)
            }
        )
        lanes.update(
            {f"n_{c}": flat(lane) for c, lane in self.state.vnulls.items()}
        )
        return sel, pull_rows(lanes, sel)

    # snapshot()/to_numpy() come from MvDeviceReadMixin

    def get_rows(self, key_tuples):
        """Point reads by pk (batch-table get_row analogue): route each
        key to its owning shard, probe that shard's slice read-only,
        and pull ONLY the matching slots — O(keys), not O(table)."""
        if not key_tuples:
            return []
        lanes = tuple(
            jnp.asarray(
                np.asarray([k[j] for k in key_tuples]),
                self.dtypes[self.pk[j]],
            )
            for j in range(len(self.pk))
        )
        dest = np.asarray(dest_shard(lanes, self.n_shards))
        out: List[Optional[tuple]] = [None] * len(key_tuples)
        flat = lambda a: a.reshape((-1,) + a.shape[2:])
        cap = self.table.live.shape[-1]
        for s in set(dest.tolist()):
            m = np.flatnonzero(dest == s)
            dsel = jnp.asarray(m)
            sub = tuple(l[dsel] for l in lanes)
            shard_table = jax.tree.map(lambda a: a[s], self.table)
            slots, found = lookup(
                shard_table, sub, jnp.ones(len(m), jnp.bool_)
            )
            hit = np.asarray(found & (slots >= 0))
            gsel = s * cap + np.asarray(slots)[hit]
            if not len(gsel):
                continue
            pulled = pull_rows(
                {
                    **{
                        f"v{j}": flat(self.state.values[c])
                        for j, c in enumerate(self.columns)
                    },
                    **{
                        f"n_{c}": flat(lane)
                        for c, lane in self.state.vnulls.items()
                    },
                },
                gsel,
            )
            for r, i in enumerate(m[hit]):
                out[i] = tuple(
                    None
                    if (f"n_{c}" in pulled and pulled[f"n_{c}"][r])
                    else pulled[f"v{j}"][r].item()
                    for j, c in enumerate(self.columns)
                )
        return out

    # -- integrity --------------------------------------------------------
    def state_digest(self) -> int:
        """Shard-flattened MV fold (integrity.mv_lanes): equal to the
        single-chip twin's digest for the same row set."""
        from risingwave_tpu.integrity import host_digest, mv_lanes

        lanes, live = mv_lanes(self.table, self.state)

        def flat(a):
            a = np.asarray(a)
            return a.reshape((-1,) + a.shape[2:])

        return host_digest(
            {k: flat(v) for k, v in lanes.items()}, flat(live)
        )

    # -- checkpoint/restore (one logical table across shards) ------------
    def checkpoint_delta(self) -> List[StateDelta]:
        shape = self.state.sdirty.shape
        sdirty = np.asarray(self.state.sdirty).reshape(-1)
        if not sdirty.any():
            return []
        alive = np.asarray(self.table.live).reshape(-1)
        stored = np.asarray(self.state.stored).reshape(-1)
        upsert, tomb, sel = stage_marks(sdirty, alive, stored)
        if not len(sel):
            self.state.sdirty = jnp.zeros_like(self.state.sdirty)
            return []
        flat = lambda a: a.reshape((-1,) + a.shape[2:])
        lanes = {f"k{j}": flat(k) for j, k in enumerate(self.table.keys)}
        lanes.update(
            {
                f"v{j}": flat(self.state.values[c])
                for j, c in enumerate(self.columns)
            }
        )
        lanes.update(
            {f"n_{c}": flat(lane) for c, lane in self.state.vnulls.items()}
        )
        rows = pull_rows(lanes, sel)
        key_cols = {f"k{j}": rows[f"k{j}"] for j in range(len(self.pk))}
        value_cols = {
            f"v{j}": rows[f"v{j}"] for j in range(len(self.columns))
        }
        for c in self.state.vnulls:
            value_cols[f"n_{c}"] = rows[f"n_{c}"].astype(np.uint8)
        self.state.stored = jnp.asarray(
            ((stored | upsert) & ~tomb).reshape(shape)
        )
        self.state.sdirty = jnp.zeros_like(self.state.sdirty)
        return [
            StateDelta(
                self.table_id,
                key_cols,
                value_cols,
                tomb[sel],
                tuple(f"k{j}" for j in range(len(self.pk))),
            )
        ]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        n = len(next(iter(key_cols.values()))) if key_cols else 0
        key_dtypes = tuple(self.dtypes[k] for k in self.pk)
        cap = self.capacity
        lanes = dest = None
        if n:
            lanes = tuple(
                jnp.asarray(np.asarray(key_cols[f"k{j}"], dtype=d))
                for j, d in enumerate(key_dtypes)
            )
            dest = np.asarray(dest_shard(lanes, self.n_shards))
            cap = grow_pow2(
                int(np.bincount(dest, minlength=self.n_shards).max()),
                cap,
                GROW_AT,
            )
        vn_names = tuple(self.state.vnulls)
        tables, states = [], []
        for s in range(self.n_shards):
            t = HashTable.create(cap, key_dtypes)
            values = {c: jnp.zeros(cap, self.dtypes[c]) for c in self.columns}
            vnulls = {c: jnp.zeros(cap, jnp.bool_) for c in vn_names}
            stored = jnp.zeros(cap, jnp.bool_)
            if n:
                sel = np.flatnonzero(dest == s)
                if len(sel):
                    dsel = jnp.asarray(sel)
                    sub = tuple(l[dsel] for l in lanes)
                    t, slots, _, _ = lookup_or_insert(
                        t, sub, jnp.ones(len(sel), jnp.bool_)
                    )
                    live = t.live.at[slots].set(True)
                    t = HashTable(t.fp1, t.fp2, t.keys, live)
                    for j, c in enumerate(self.columns):
                        values[c] = values[c].at[slots].set(
                            jnp.asarray(
                                np.asarray(value_cols[f"v{j}"])[sel].astype(
                                    self.dtypes[c]
                                )
                            )
                        )
                    for c in vn_names:
                        lane = value_cols.get(f"n_{c}")
                        if lane is not None:
                            vnulls[c] = vnulls[c].at[slots].set(
                                jnp.asarray(
                                    np.asarray(lane)[sel].astype(bool)
                                )
                            )
                    stored = stored.at[slots].set(True)
            tables.append(t)
            states.append(
                MvDeviceState(
                    values,
                    vnulls,
                    jnp.zeros(cap, jnp.bool_),
                    stored,
                    jnp.zeros((), jnp.bool_),
                )
            )
        sharding = NamedSharding(self.mesh, P(self.axis))
        stack = lambda *xs: jnp.stack(xs)
        self.table = jax.device_put(jax.tree.map(stack, *tables), sharding)
        self.state = jax.device_put(jax.tree.map(stack, *states), sharding)
        self.capacity = cap
        self._steps = {}  # capacity may have changed: recompile


# -- mesh observability surface (meshprof / scale / memory governor) ------
def _sharded_mv_shard_occupancy(self):
    """Per-shard claimed pk-slot counts (autoscale + skew input). One
    packed device read."""
    return np.asarray(
        jnp.sum((self.table.fp1 != jnp.uint32(0)).astype(jnp.int32), axis=1)
    )


ShardedMaterialize.shard_occupancy = _sharded_mv_shard_occupancy
ShardedMaterialize.state_nbytes_per_shard = stacked_state_nbytes_per_shard
