"""Vnode-sharded HashAgg: hash exchange + grouped state on a mesh.

Reference roles replaced (SURVEY.md §2.11, §3.3):
- ``HashDataDispatcher`` — rows route to the downstream actor owning
  their key's vnode (src/stream/src/executor/dispatch.rs:683,
  vnode mapping src/common/src/hash/consistent_hash/vnode.rs:34);
- the exchange channel / gRPC GetStream between actors
  (src/stream/src/executor/exchange/permit.rs:35) — here a single
  ``lax.all_to_all`` over the mesh's ICI links inside the jit step;
- N parallel HashAgg actors, each owning its vnode slice of group
  state (src/stream/src/executor/hash_agg.rs:62).

Design: state lives STACKED — every per-slot array gains a leading
``(n_shards,)`` axis sharded over the mesh. The step runs under
``shard_map``; inside, each shard:

1. computes each local row's destination shard ``vnode(key) % n``;
2. packs rows into per-destination buckets of static capacity
   (compaction by cumulative count — no sort on the hot path);
3. exchanges buckets with ``lax.all_to_all`` (the ICI shuffle);
4. runs the SAME single-chip kernels (lookup_or_insert + agg apply)
   on the received rows against its local slot table.

Each group key lives on exactly one shard, so per-barrier flush is
shard-local and the concatenated deltas are globally exact. Bucket
overflow (static capacity exceeded by a skewed chunk) latches the
``dropped`` flag — the same correctness backstop as MAX_PROBE
overflow, surfaced at the next barrier.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.executors.hash_agg import (
    _build_key_lanes,
    _mark_checkpointed,
    _rehash,
    build_restored_agg,
)
from risingwave_tpu.ops import agg as agg_ops
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.ops.hash_table import HashTable, lookup_or_insert, set_live
from risingwave_tpu.parallel.sharded_join import (
    double_bucket_cap,
    stacked_state_nbytes_per_shard,
    track_bucket_cap,
)
from risingwave_tpu.parallel.exchange import (
    dest_shard as _dest_shard,
    exchange_chunk,
    pack_buckets as _pack_buckets,
)
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    pull_rows,
    stage_marks,
)

GROW_AT = 0.5


def make_mesh(n_devices: Optional[int] = None, axis: str = "shard") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


class ShardedHashAgg(Executor, Checkpointable):
    """Mesh-parallel HashAgg with on-device hash exchange.

    The executor owns stacked (n_shards, capacity) state sharded over
    ``mesh``; ``apply`` expects stacked (n_shards, chunk_cap) input
    chunks (each shard's source slice — e.g. one Nexmark split per
    shard); flush returns host-side StreamChunks.

    Capacity is per-shard and GROWS 2x when the per-shard insert bound
    trips 50% load (per-shard rehash under one shard_map program).
    Checkpoints stage ONE table of all shards' changed rows (keys are
    globally unique — each lives on exactly one shard); restore
    re-partitions rows by vnode, so recovery works across DIFFERENT
    mesh sizes (vnode.rs:34 remap semantics).
    """

    def __init__(
        self,
        mesh: Mesh,
        group_keys: Sequence[str],
        calls: Sequence[AggCall],
        schema_dtypes: Dict[str, object],
        capacity: int = 1 << 16,
        out_cap: int = 1 << 14,
        bucket_cap: Optional[int] = None,
        chunk_cap: Optional[int] = None,
        nullable_keys: Sequence[str] = (),
        table_id: str = "sharded_agg",
        stacked_out: bool = False,
    ):
        self.table_id = table_id
        # stacked_out keeps barrier-flush deltas as STACKED device
        # chunks — required when the flush feeds another sharded op
        # (e.g. a join side: q7's per-window MAX change stream) instead
        # of crossing the host boundary
        self.stacked_out = stacked_out
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_shards = mesh.devices.size
        self.group_keys = tuple(group_keys)
        self.calls = tuple(calls)
        if any(c.materialized for c in self.calls):
            raise NotImplementedError(
                "materialized MIN/MAX is single-chip only for now"
            )
        self.nullable = tuple(k in set(nullable_keys) for k in self.group_keys)
        self.capacity = capacity
        self.out_cap = out_cap
        self._dtypes = dict(schema_dtypes)
        self._float_extremes = agg_ops.float_extreme_meta(
            self.calls, {k: jnp.dtype(v) for k, v in self._dtypes.items()}
        )
        self.bucket_cap = bucket_cap

        key_dtypes = []
        for k, nb in zip(self.group_keys, self.nullable):
            key_dtypes.append(jnp.dtype(self._dtypes[k]))
            if nb:
                key_dtypes.append(jnp.dtype(jnp.bool_))
        table1 = HashTable.create(capacity, key_dtypes)
        state1 = agg_ops.create_state(capacity, self.calls, self._dtypes)

        def stack(a):
            return jnp.broadcast_to(a[None], (self.n_shards,) + a.shape)

        shard0 = NamedSharding(mesh, P(self.axis))
        self._shard0 = shard0
        self._key_dtypes = tuple(key_dtypes)
        self.table = jax.device_put(jax.tree.map(stack, table1), shard0)
        self.state = jax.device_put(jax.tree.map(stack, state1), shard0)
        self.dropped = jax.device_put(
            jnp.zeros(self.n_shards, jnp.bool_), shard0
        )
        self._step = None  # built lazily (needs bucket_cap from chunk)
        self._insert_bound = 0  # per-shard upper bound of claimed slots
        self._built_bucket_cap: Optional[int] = None
        self.ex_counts_last = None  # (n, n) routed-row histogram, device

    # -- the sharded step -------------------------------------------------
    def _build_step(self, chunk_cap: int):
        n_shards = self.n_shards
        bucket_cap = self.bucket_cap or max(64, (2 * chunk_cap) // n_shards)
        track_bucket_cap(self, bucket_cap)
        calls, group_keys, nullable = self.calls, self.group_keys, self.nullable
        axis = self.axis

        def local_step(table, state, dropped, chunk: StreamChunk):
            # shard_map gives each shard its (1, ...) slice; drop the axis
            table = jax.tree.map(lambda a: a[0], table)
            state = jax.tree.map(lambda a: a[0], state)
            dropped = dropped[0]
            chunk = jax.tree.map(lambda a: a[0], chunk)

            # 1-3) vnode route + bucket pack + all_to_all ICI shuffle
            keys = _build_key_lanes(chunk, group_keys, nullable)
            rchunk, overflow, ex_counts = exchange_chunk(
                chunk, keys, n_shards, bucket_cap, axis
            )

            # 4) local agg over the received rows
            rkeys = _build_key_lanes(rchunk, group_keys, nullable)
            table, slots, _, _ = lookup_or_insert(table, rkeys, rchunk.valid)
            signs = rchunk.effective_signs()
            dropped = (
                dropped
                | overflow
                | jnp.any(rchunk.valid & (slots < 0))
            )
            values = {
                c.input: rchunk.col(c.input) for c in calls if c.input is not None
            }
            in_nulls = {
                c.input: rchunk.nulls[c.input]
                for c in calls
                if c.input is not None and c.input in rchunk.nulls
            }
            state = agg_ops.apply(state, calls, slots, signs, values, in_nulls)
            table = set_live(table, slots, state.row_count[slots] > 0)

            expand = lambda a: a[None]
            return (
                jax.tree.map(expand, table),
                jax.tree.map(expand, state),
                dropped[None],
                ex_counts[None],  # (1, n): this shard's routing row
            )

        spec = P(self.axis)
        shmapped = jax.shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=(spec, spec, spec, spec),
            check_vma=False,
        )
        return jax.jit(shmapped, donate_argnums=(0, 1))

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        """``chunk`` must be stacked: every array (n_shards, chunk_cap),
        sharded or shardable over the mesh axis."""
        for k, nb in zip(self.group_keys, self.nullable):
            if not nb and k in chunk.nulls:
                raise ValueError(
                    f"group key {k!r} carries a null lane but was not "
                    "declared in nullable_keys"
                )
        chunk_cap = chunk.valid.shape[-1]
        if self._step is None:
            self._step = self._build_step(chunk_cap)
        # worst case a shard receives every row of the exchange
        bucket_cap = self.bucket_cap or max(64, (2 * chunk_cap) // self.n_shards)
        self._maybe_grow(self.n_shards * bucket_cap)
        self._insert_bound += self.n_shards * bucket_cap
        self.table, self.state, self.dropped, self.ex_counts_last = (
            self._step(self.table, self.state, self.dropped, chunk)
        )
        return []

    def _maybe_grow(self, incoming: int) -> None:
        """Per-shard 2x rehash when the insert bound trips GROW_AT load
        (the single-chip growth contract, applied per shard under one
        shard_map program)."""
        cap = self.capacity
        if self._insert_bound + incoming <= cap * GROW_AT:
            return
        claimed = int(jnp.max(jnp.sum(
            (self.table.fp1 != jnp.uint32(0)).astype(jnp.int32), axis=1
        )))
        keep = (
            self.table.live
            | self.state.emitted_valid
            | self.state.dirty
            | self.state.sdirty
        ) & (self.table.fp1 != jnp.uint32(0))
        survivors = int(jnp.max(jnp.sum(keep.astype(jnp.int32), axis=1)))
        from risingwave_tpu.ops.hash_table import plan_rehash

        new_cap = plan_rehash(cap, incoming, claimed, survivors, GROW_AT)
        if new_cap is not None:
            calls = self.calls
            spec = P(self.axis)

            def local(table, state):
                table = jax.tree.map(lambda a: a[0], table)
                state = jax.tree.map(lambda a: a[0], state)
                t2, s2, _ = _rehash(table, state, {}, calls, new_cap)
                ex = lambda t: jax.tree.map(lambda a: a[None], t)
                return ex(t2), ex(s2)

            grow = jax.jit(
                jax.shard_map(
                    local,
                    mesh=self.mesh,
                    in_specs=(spec, spec),
                    out_specs=(spec, spec),
                    check_vma=False,
                ),
                donate_argnums=(0, 1),
            )
            self.table, self.state = grow(self.table, self.state)
            self.capacity = new_cap
            claimed = int(jnp.max(jnp.sum(
                (self.table.fp1 != jnp.uint32(0)).astype(jnp.int32), axis=1
            )))
        self._insert_bound = claimed

    # -- capacity escape (watchdog replay, scale.rs:453 analogue) ---------
    def capacity_overflow_latched(self) -> bool:
        return bool(jnp.any(self.dropped))

    def grow_for_replay(self) -> None:
        """Double the skew-sensitive capacities (exchange bucket,
        emission cap, probe table) and reset device state; recover()
        restores the durable rows before the poisoned epoch replays."""
        double_bucket_cap(self)
        self.out_cap *= 2
        self.capacity *= 2
        table1 = HashTable.create(self.capacity, self._key_dtypes)
        state1 = agg_ops.create_state(
            self.capacity, self.calls, self._dtypes
        )
        stack = lambda a: jnp.broadcast_to(
            a[None], (self.n_shards,) + a.shape
        )
        self.table = jax.device_put(
            jax.tree.map(stack, table1), self._shard0
        )
        self.state = jax.device_put(
            jax.tree.map(stack, state1), self._shard0
        )
        self.dropped = jax.device_put(
            jnp.zeros(self.n_shards, jnp.bool_), self._shard0
        )
        self._insert_bound = 0
        self._step = None
        if hasattr(self, "_flush"):
            del self._flush

    # -- barrier flush ----------------------------------------------------
    def _build_flush(self):
        out_cap, fx = self.out_cap, self._float_extremes

        def local_flush(state, table_keys):
            state = jax.tree.map(lambda a: a[0], state)
            table_keys = jax.tree.map(lambda a: a[0], table_keys)
            state, delta = agg_ops.flush(state, table_keys, out_cap, fx)
            expand = lambda a: a[None]
            return jax.tree.map(expand, state), jax.tree.map(expand, delta)

        spec = P(self.axis)
        return jax.jit(
            jax.shard_map(
                local_flush,
                mesh=self.mesh,
                in_specs=(spec, spec),
                out_specs=(spec, spec),
                check_vma=False,
            )
        )

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        if bool(jnp.any(self.dropped)):
            raise RuntimeError(
                "sharded agg overflowed (bucket or probe); grow capacities"
            )
        if not hasattr(self, "_flush"):
            self._flush = self._build_flush()
        outs: List[StreamChunk] = []
        # each round drains up to out_cap dirty groups per shard, so
        # capacity/out_cap rounds always suffice; a persistently-set
        # overflow flag (kernel bug) must raise, not hang (ADVICE r2)
        max_rounds = max(2, self.capacity // max(1, self.out_cap)) + 2
        for _ in range(max_rounds):
            self.state, delta = self._flush(self.state, self.table.keys)
            outs.append(self._delta_to_chunk(delta))
            if not bool(jnp.any(delta["overflow"])):
                return outs
        raise RuntimeError(
            f"sharded agg flush did not drain in {max_rounds} rounds — "
            "overflow flag appears stuck"
        )

    def _delta_to_chunk(self, delta) -> StreamChunk:
        """Stacked (n_shards, 2*out_cap) delta -> one flat StreamChunk
        (or, with ``stacked_out``, a stacked device chunk that flows
        straight into the next sharded op with no host round-trip)."""
        if self.stacked_out:
            flat = lambda a: a  # keep the shard axis + device residency
        else:
            flat = lambda a: np.asarray(a).reshape(-1)
        cols, nulls = {}, {}
        i = 0
        for name, nb in zip(self.group_keys, self.nullable):
            cols[name] = flat(delta[f"key{i}"])
            i += 1
            if nb:
                nulls[name] = flat(delta[f"key{i}"])
                i += 1
        for c in self.calls:
            cols[c.output] = flat(delta[c.output])
            lane = delta.get(c.output + "__isnull")
            if lane is not None:
                nulls[c.output] = flat(lane)
        return StreamChunk(
            columns={k: jnp.asarray(v) for k, v in cols.items()},
            valid=jnp.asarray(flat(delta["valid"])),
            nulls={k: jnp.asarray(v) for k, v in nulls.items()},
            ops=jnp.asarray(flat(delta["ops"])),
        )

    # -- static contracts (analysis/) -------------------------------------
    def lint_info(self):
        emits = {k: self._dtypes.get(k) for k in self.group_keys}
        renames = {k: k for k in self.group_keys}
        requires = set(self.group_keys)
        for c in self.calls:
            if c.input is not None:
                requires.add(c.input)
            if c.kind in ("count", "count_star"):
                out_dt = jnp.int64
            elif c.kind in ("min", "max") and c.input in self._dtypes:
                out_dt = self._dtypes[c.input]
            else:
                out_dt = None  # sum/avg widen by kind-specific rules
            emits[c.output] = out_dt
            renames[c.output] = None
        return {
            "requires": tuple(sorted(requires)),
            "expects": {
                k: self._dtypes[k]
                for k in sorted(requires)
                if k in self._dtypes
            },
            "emits": emits,
            "renames": renames,
            "keys": self.group_keys,
            "table_ids": (self.table_id,),
            "window_key": None,
        }

    def trace_contract(self):
        # mesh-resident: the per-chunk step IS one jitted shard_map
        # dispatch, but single-chip fusion cannot absorb it — whether
        # the whole sharded fragment collapses into one SPMD dispatch
        # is the mesh analyzer's E9xx question (mesh_contract below).
        # The host reads (flush drain, growth planning, occupancy) are
        # declared as fallback_syncs so the fusion corpus accounts for
        # the parallel path instead of skipping it as opaque.
        full = self.out_cap
        return {
            "kind": "host",
            "host_reason": "mesh-resident sharded step: per-fragment "
            "SPMD fusion is tracked by the mesh analyzer (RW-E9xx), "
            "not the single-chip fuser",
            "state": (self.table, self.state),
            "donate": True,
            "emission": "bucketed",
            "emission_caps": (
                (full,) if self.stacked_out else (self.n_shards * full,)
            ),
            "fallback_syncs": (
                "on_barrier",
                "_delta_to_chunk",
                "_maybe_grow",
                "shard_occupancy",
            ),
        }

    def mesh_contract(self):
        def trace_steps(abs_chunk):
            from risingwave_tpu.analysis.mesh_domain import abstract_tree

            step = self._build_step(int(abs_chunk.valid.shape[-1]))
            return [
                (
                    "apply",
                    step,
                    (
                        abstract_tree(self.table),
                        abstract_tree(self.state),
                        abstract_tree(self.dropped),
                        abs_chunk,
                    ),
                )
            ]

        return {
            "axis": self.axis,
            "n_shards": self.n_shards,
            "state": {
                "table": "sharded",
                "state": "sharded",
                "dropped": "sharded",
            },
            "updates": ("table", "state", "dropped"),
            "dispatch": {
                "fn": "dest_shard",
                "keys": self.group_keys,
                "vnode_axis": self.axis,
            },
            "exchange": "all_to_all",
            "donate": True,
            # per-slot merges apply in received-bucket order, which the
            # deterministic all_to_all layout fixes per (src, lane)
            "order_insensitive": True,
            "trace_steps": trace_steps,
            "barrier_methods": (
                "on_barrier",
                "_delta_to_chunk",
                "_maybe_grow",
                "shard_occupancy",
            ),
            "emission": "stacked" if self.stacked_out else "host",
        }


def _sharded_agg_shard_occupancy(self):
    """Per-shard claimed-slot counts (autoscale policy input,
    parallel/scale.py). One packed device read."""
    return np.asarray(
        jnp.sum((self.table.fp1 != jnp.uint32(0)).astype(jnp.int32), axis=1)
    )


def _sharded_agg_checkpoint_delta(self) -> List[StateDelta]:
    """Stage ALL shards' changed rows as ONE table (keys are globally
    unique across shards); same lane naming as the single-chip agg so
    either executor can restore the other's checkpoint."""
    shape = (self.n_shards, self.capacity)
    sdirty = np.asarray(self.state.sdirty).reshape(-1)
    if not sdirty.any():
        return []
    alive = (
        np.asarray(self.table.live)
        | np.asarray(self.state.emitted_valid)
        | np.asarray(self.state.dirty)
    ).reshape(-1)
    upsert, tomb, sel = stage_marks(
        sdirty, alive, np.asarray(self.state.stored).reshape(-1)
    )
    flat = lambda a: a.reshape((-1,) + a.shape[2:])
    lanes = {f"k{i}": flat(lane) for i, lane in enumerate(self.table.keys)}
    key_names = tuple(lanes)
    lanes["row_count"] = flat(self.state.row_count)
    for n, a in self.state.accums.items():
        lanes[f"acc_{n}"] = flat(a)
        lanes[f"em_{n}"] = flat(self.state.emitted[n])
    for n, a in self.state.nonnull.items():
        lanes[f"nn_{n}"] = flat(a)
        lanes[f"ei_{n}"] = flat(self.state.emitted_isnull[n])
    lanes["ev"] = flat(self.state.emitted_valid)
    pulled = pull_rows(lanes, sel)
    keys = {k: pulled[k] for k in key_names}
    vals = {k: v for k, v in pulled.items() if k not in key_names}
    self.state = _mark_checkpointed(
        self.state,
        jnp.asarray(upsert.reshape(shape)),
        jnp.asarray(tomb.reshape(shape)),
    )
    return [StateDelta(self.table_id, keys, vals, tomb[sel], key_names)]


def _sharded_agg_restore_state(self, table_id, key_cols, value_cols) -> None:
    """Re-partition recovered rows by vnode and rebuild every shard —
    works across mesh sizes (a key's shard is vnode % n_shards, so a
    different mesh just remaps vnodes; vnode.rs:34)."""
    n = len(next(iter(key_cols.values()))) if key_cols else 0
    if n:
        lanes = tuple(
            jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d))
            for i, d in enumerate(self._key_dtypes)
        )
        dest = np.asarray(_dest_shard(lanes, self.n_shards))
    cap = self.capacity
    while n and max(
        np.bincount(dest, minlength=self.n_shards).max(), 1
    ) > cap * GROW_AT:
        cap *= 2
    tables, states = [], []
    for k in range(self.n_shards):
        sel = np.flatnonzero(dest == k) if n else np.zeros(0, np.int64)
        t, s, _ = build_restored_agg(
            cap, self.calls, self._dtypes, self._key_dtypes,
            key_cols, value_cols, sel=sel,
        )
        tables.append(t)
        states.append(s)
    stack = lambda *xs: jnp.stack(xs)
    self.table = jax.device_put(
        jax.tree.map(stack, *tables), self._shard0
    )
    self.state = jax.device_put(
        jax.tree.map(stack, *states), self._shard0
    )
    self.capacity = cap
    self.dropped = jax.device_put(
        jnp.zeros(self.n_shards, jnp.bool_), self._shard0
    )
    self._insert_bound = int(
        np.bincount(dest, minlength=self.n_shards).max()
    ) if n else 0


def _sharded_agg_state_nbytes(self) -> int:
    """Stacked device bytes across all shards (memory-governor ledger
    + meshprof state_bytes lane)."""
    return int(
        sum(
            leaf.nbytes
            for leaf in jax.tree.leaves((self.table, self.state))
        )
    )


def _sharded_agg_state_digest(self) -> int:
    """Shard-flattened agg fold (integrity.agg_lanes over the stacked
    pytree): equal to the single-chip twin's digest for the same
    logical groups — slot order and shard placement cancel out."""
    from risingwave_tpu.integrity import agg_lanes, host_digest

    lanes, live = agg_lanes(self.table, self.state)

    def flat(a):
        a = np.asarray(a)
        return a.reshape((-1,) + a.shape[2:])

    return host_digest({k: flat(v) for k, v in lanes.items()}, flat(live))


ShardedHashAgg.checkpoint_delta = _sharded_agg_checkpoint_delta
ShardedHashAgg.shard_occupancy = _sharded_agg_shard_occupancy
ShardedHashAgg.restore_state = _sharded_agg_restore_state
ShardedHashAgg.state_nbytes = _sharded_agg_state_nbytes
ShardedHashAgg.state_digest = _sharded_agg_state_digest
ShardedHashAgg.state_nbytes_per_shard = stacked_state_nbytes_per_shard


def stack_chunks(chunks: Sequence[StreamChunk]) -> StreamChunk:
    """Stack per-shard chunks (same capacity/columns) into one stacked
    chunk with a leading shard axis — the input format ShardedHashAgg
    expects (each shard = one source split)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *chunks)
