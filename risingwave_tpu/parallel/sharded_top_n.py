"""Mesh-parallel retractable GroupTopN.

Reference role: N parallel GroupTopN actors each owning the groups
whose vnode lands on them (src/stream/src/executor/top_n/group_top_n.rs
distributed by HashDataDispatcher). Groups are DISJOINT across shards
(the exchange routes by the group columns), so each shard's per-group
top-k is globally exact and the barrier emissions concatenate.

Structure mirrors ShardedDedup: stacked per-shard state, ``apply`` is
one shard_map program (vnode exchange + the single-chip
``_upsert_step_ed`` kernel); the barrier runs the pure ranking kernel
per shard and the SHARED host diff (``_diff_touched_groups``) against
per-shard emitted mirrors — host traffic stays O(changed groups x k)
per shard. Checkpoints use the single-chip lane naming (k{i} + r_*),
keys globally unique across shards, so either executor restores the
other's checkpoint (cross-layout recovery)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor
from risingwave_tpu.executors.top_n_plain import (
    _diff_touched_groups,
    _emit_diffs,
    _group_topk_mask,
    _upsert_step_ed,
)
from risingwave_tpu.ops.hash_table import (
    HashTable,
    lookup_or_insert,
    set_live,
)
from risingwave_tpu.parallel.exchange import dest_shard, exchange_chunk
from risingwave_tpu.parallel.sharded_join import (
    stack_for_mesh,
    stacked_state_nbytes_per_shard,
    track_bucket_cap,
)
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    StateDelta,
    grow_pow2,
    pull_rows,
    stage_marks,
)

GROW_AT = 0.5


class ShardedGroupTopN(Executor, Checkpointable):
    """GROUP BY g ORDER BY o LIMIT k over a device mesh."""

    def __init__(
        self,
        mesh,
        group_by: Sequence[str],
        order_col: str,
        limit: int,
        pk: Sequence[str],
        schema_dtypes: Dict[str, object],
        desc: bool = False,
        capacity: int = 1 << 12,
        bucket_cap: Optional[int] = None,
        table_id: str = "sharded_group_top_n",
    ):
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_shards = mesh.devices.size
        self.group_by = tuple(group_by)
        self.order_col = order_col
        self.limit = int(limit)
        self.desc = desc
        self.pk = tuple(pk)
        self.store_keys = self.group_by + tuple(
            c for c in self.pk if c not in self.group_by
        )
        self.names = tuple(sorted(schema_dtypes))
        self._dtypes = {n: jnp.dtype(schema_dtypes[n]) for n in self.names}
        self.bucket_cap = bucket_cap
        self.table_id = table_id
        table1 = HashTable.create(
            capacity, tuple(self._dtypes[c] for c in self.store_keys)
        )
        self.table = stack_for_mesh(table1, mesh, self.axis)
        z = jnp.zeros(capacity, jnp.bool_)
        self.rows = stack_for_mesh(
            {n: jnp.zeros(capacity, self._dtypes[n]) for n in self.names},
            mesh,
            self.axis,
        )
        self.sdirty = stack_for_mesh(z, mesh, self.axis)
        self.stored = stack_for_mesh(z, mesh, self.axis)
        self.epoch_dirty = stack_for_mesh(z, mesh, self.axis)
        self.dropped = stack_for_mesh(jnp.zeros((), jnp.bool_), mesh, self.axis)
        self._step = None
        self._built_bucket_cap: Optional[int] = None
        self.ex_counts_last = None  # (n, n) routed-row histogram, device
        # per-shard host mirrors of what was emitted
        self._emitted: List[Dict[Tuple, Dict[Tuple, Tuple]]] = [
            {} for _ in range(self.n_shards)
        ]

    # -- the sharded step -------------------------------------------------
    def _build_step(self, chunk_cap: int):
        n, axis = self.n_shards, self.axis
        bucket_cap = self.bucket_cap or max(64, (2 * chunk_cap) // n)
        track_bucket_cap(self, bucket_cap)
        group_by, store_keys, names = (
            self.group_by,
            self.store_keys,
            self.names,
        )

        def local(table, rows, sdirty, edirty, dropped, chunk):
            table, rows, sdirty, edirty, dropped, chunk = jax.tree.map(
                lambda a: a[0],
                (table, rows, sdirty, edirty, dropped, chunk),
            )
            lanes = tuple(chunk.col(g) for g in group_by)
            rchunk, ex_ovf, ex_counts = exchange_chunk(
                chunk, lanes, n, bucket_cap, axis
            )
            table, rows, sdirty, edirty, dr = _upsert_step_ed(
                table, rows, sdirty, edirty, rchunk, store_keys, names
            )
            dropped = dropped | dr | ex_ovf
            ex = lambda t: jax.tree.map(lambda a: a[None], t)
            return (
                ex(table), ex(rows), ex(sdirty), ex(edirty), ex(dropped),
                ex_counts[None],
            )

        spec = P(self.axis)
        return jax.jit(
            jax.shard_map(
                local,
                mesh=self.mesh,
                in_specs=(spec,) * 6,
                out_specs=(spec,) * 6,
                check_vma=False,
            ),
            donate_argnums=(0, 1, 2, 3, 4),
        )

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        if self._step is None:
            self._step = self._build_step(chunk.valid.shape[-1])
        (
            self.table,
            self.rows,
            self.sdirty,
            self.epoch_dirty,
            self.dropped,
            self.ex_counts_last,
        ) = self._step(
            self.table,
            self.rows,
            self.sdirty,
            self.epoch_dirty,
            self.dropped,
            chunk,
        )
        return []

    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        # ONE packed device->host read per barrier (tunneled-TPU
        # round-trips dominate; the single-chip executor packs the
        # same way): latch + per-shard dirty vector together
        packed = np.asarray(
            jnp.concatenate(
                [
                    jnp.any(self.dropped)[None],
                    jnp.any(self.epoch_dirty, axis=-1),
                ]
            )
        )
        if bool(packed[0]):
            raise RuntimeError(
                "sharded GroupTopN overflowed (probe or exchange bucket)"
            )
        shard_dirty = packed[1:]
        if not shard_dirty.any():
            return []
        dels: list = []
        ins: list = []
        for s in range(self.n_shards):
            if not shard_dirty[s]:
                continue
            table_s = jax.tree.map(lambda a: a[s], self.table)
            rows_s = {n: a[s] for n, a in self.rows.items()}
            edirty_s = self.epoch_dirty[s]
            in_topk, gdirty = _group_topk_mask(
                table_s,
                rows_s,
                edirty_s,
                self.limit,
                self.desc,
                self.group_by,
                self.order_col,
            )
            d, i = _diff_touched_groups(
                table_s, rows_s, in_topk, edirty_s, self.group_by,
                self.pk, self.names, gdirty, self._emitted[s],
            )
            dels.extend(d)
            ins.extend(i)
        self.epoch_dirty = stack_for_mesh(
            jnp.zeros(self.epoch_dirty.shape[-1], jnp.bool_),
            self.mesh,
            self.axis,
        )
        return _emit_diffs(dels, ins, self.names, self._dtypes)

    # -- static contracts (analysis/) -------------------------------------
    def lint_info(self):
        cols = self.names
        return {
            "expects": {c: self._dtypes[c] for c in cols},
            "emits": {c: self._dtypes.get(c) for c in cols},
            "renames": {c: c for c in cols},
            "keys": self.group_by,
            "table_ids": (self.table_id,),
            "window_key": None,
        }

    def trace_contract(self):
        return {
            "kind": "host",
            "host_reason": "mesh-resident sharded step: per-fragment "
            "SPMD fusion is tracked by the mesh analyzer (RW-E9xx), "
            "not the single-chip fuser",
            "state": (self.table, self.rows),
            "donate": True,
            # the barrier diff emits exactly the touched top-k rows —
            # a host-built, count-dependent chunk
            "emission": "data_dependent",
            "fallback_syncs": ("on_barrier", "shard_occupancy"),
        }

    def mesh_contract(self):
        def trace_steps(abs_chunk):
            from risingwave_tpu.analysis.mesh_domain import abstract_tree

            step = self._build_step(int(abs_chunk.valid.shape[-1]))
            return [
                (
                    "apply",
                    step,
                    (
                        abstract_tree(self.table),
                        abstract_tree(self.rows),
                        abstract_tree(self.sdirty),
                        abstract_tree(self.epoch_dirty),
                        abstract_tree(self.dropped),
                        abs_chunk,
                    ),
                )
            ]

        return {
            "axis": self.axis,
            "n_shards": self.n_shards,
            "state": {
                "table": "sharded",
                "rows": "sharded",
                "sdirty": "sharded",
                "epoch_dirty": "sharded",
                "dropped": "sharded",
            },
            "updates": ("table", "rows", "sdirty", "epoch_dirty", "dropped"),
            "dispatch": {
                "fn": "dest_shard",
                "keys": self.group_by,
                "vnode_axis": self.axis,
            },
            "exchange": "all_to_all",
            "donate": True,
            "order_insensitive": True,  # top-k membership is an
            # order-statistic of the stored set, not of arrival order
            "trace_steps": trace_steps,
            # the barrier walk pulls each dirty shard's slice to host
            # and diffs against the _emitted mirrors — the E901/E907
            # scan targets
            "barrier_methods": ("on_barrier", "shard_occupancy"),
            "emission": "host",
        }

    # -- capacity escape ---------------------------------------------------
    def capacity_overflow_latched(self) -> bool:
        return bool(jnp.any(self.dropped))

    def grow_for_replay(self) -> None:
        from risingwave_tpu.parallel.sharded_join import double_bucket_cap

        cap = 2 * self.table.keys[0].shape[-1]
        double_bucket_cap(self)
        table1 = HashTable.create(
            cap, tuple(self._dtypes[c] for c in self.store_keys)
        )
        self.table = stack_for_mesh(table1, self.mesh, self.axis)
        z = jnp.zeros(cap, jnp.bool_)
        self.rows = stack_for_mesh(
            {n: jnp.zeros(cap, self._dtypes[n]) for n in self.names},
            self.mesh,
            self.axis,
        )
        self.sdirty = stack_for_mesh(z, self.mesh, self.axis)
        self.stored = stack_for_mesh(z, self.mesh, self.axis)
        self.epoch_dirty = stack_for_mesh(z, self.mesh, self.axis)
        self.dropped = stack_for_mesh(
            jnp.zeros((), jnp.bool_), self.mesh, self.axis
        )
        self._emitted = [{} for _ in range(self.n_shards)]
        self._step = None

    # -- integrity --------------------------------------------------------
    def state_digest(self) -> int:
        """Shard-flattened row-store fold (single-chip lane naming)."""
        from risingwave_tpu.integrity import host_digest

        def flat(a):
            a = np.asarray(a)
            return a.reshape((-1,) + a.shape[2:])

        lanes = {f"k{i}": flat(k) for i, k in enumerate(self.table.keys)}
        for n in self.names:
            lanes[f"r_{n}"] = flat(self.rows[n])
        return host_digest(lanes, flat(self.table.live))

    # -- checkpoint/restore (single-chip lane naming) ---------------------
    def checkpoint_delta(self) -> List[StateDelta]:
        sdirty = np.asarray(self.sdirty).reshape(-1)
        if not sdirty.any():
            return []
        shape = self.sdirty.shape
        upsert, tomb, sel = stage_marks(
            sdirty,
            np.asarray(self.table.live).reshape(-1),
            np.asarray(self.stored).reshape(-1),
        )
        flat = lambda a: a.reshape((-1,) + a.shape[2:])
        lanes = {f"k{i}": flat(l) for i, l in enumerate(self.table.keys)}
        key_names = tuple(lanes)
        for n in self.names:
            lanes[f"r_{n}"] = flat(self.rows[n])
        pulled = pull_rows(lanes, sel)
        keys = {k: pulled[k] for k in key_names}
        vals = {k: v for k, v in pulled.items() if k not in key_names}
        self.stored = (
            self.stored | jnp.asarray(upsert.reshape(shape))
        ) & ~jnp.asarray(tomb.reshape(shape))
        self.sdirty = jnp.zeros_like(self.sdirty)
        return [StateDelta(self.table_id, keys, vals, tomb[sel], key_names)]

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        """Re-partition recovered rows by GROUP-column vnode and
        rebuild every shard; emitted mirrors rebuild from the restored
        top-k at the next barrier touch (rows restore epoch-clean)."""
        from jax.sharding import NamedSharding

        n_rows = len(next(iter(key_cols.values()))) if key_cols else 0
        key_dtypes = tuple(self._dtypes[c] for c in self.store_keys)
        cap = self.table.keys[0].shape[-1]
        glanes = dest = None
        if n_rows:
            lanes = tuple(
                jnp.asarray(np.asarray(key_cols[f"k{i}"], dtype=d))
                for i, d in enumerate(key_dtypes)
            )
            glanes = lanes[: len(self.group_by)]
            dest = np.asarray(dest_shard(glanes, self.n_shards))
            cap = grow_pow2(
                int(np.bincount(dest, minlength=self.n_shards).max()),
                cap,
                GROW_AT,
            )
        tables, rowstacks, stores = [], [], []
        for s in range(self.n_shards):
            t = HashTable.create(cap, key_dtypes)
            rws = {n: jnp.zeros(cap, self._dtypes[n]) for n in self.names}
            stored = jnp.zeros(cap, jnp.bool_)
            if n_rows:
                sel = np.flatnonzero(dest == s)
                if len(sel):
                    dsel = jnp.asarray(sel)
                    sub = tuple(
                        jnp.asarray(np.asarray(key_cols[f"k{i}"]))[dsel]
                        .astype(d)
                        for i, d in enumerate(key_dtypes)
                    )
                    t, slots, _, _ = lookup_or_insert(
                        t, sub, jnp.ones(len(sel), jnp.bool_)
                    )
                    t = set_live(t, slots, True)
                    stored = stored.at[slots].set(True)
                    for n in self.names:
                        rws[n] = rws[n].at[slots].set(
                            jnp.asarray(
                                np.asarray(value_cols[f"r_{n}"])[sel]
                            ).astype(self._dtypes[n])
                        )
            tables.append(t)
            rowstacks.append(rws)
            stores.append(stored)
        sharding = NamedSharding(self.mesh, P(self.axis))
        stack = lambda *xs: jnp.stack(xs)
        self.table = jax.device_put(
            jax.tree.map(stack, *tables), sharding
        )
        self.rows = jax.device_put(
            jax.tree.map(stack, *rowstacks), sharding
        )
        self.stored = jax.device_put(jnp.stack(stores), sharding)
        z = jnp.zeros(cap, jnp.bool_)
        self.sdirty = stack_for_mesh(z, self.mesh, self.axis)
        self.epoch_dirty = stack_for_mesh(z, self.mesh, self.axis)
        self.dropped = stack_for_mesh(
            jnp.zeros((), jnp.bool_), self.mesh, self.axis
        )
        # restored rows were DURABLE (emitted before the checkpoint):
        # rebuild the mirrors to every group's current top-k (the
        # downstream MV restored to exactly this view) so post-recovery
        # diffs don't re-emit the standing rows — the single-chip
        # restore's pattern, per shard
        self._emitted = [{} for _ in range(self.n_shards)]
        for s in range(self.n_shards):
            table_s = jax.tree.map(lambda a: a[s], self.table)
            rows_s = {n: a[s] for n, a in self.rows.items()}
            if not bool(jnp.any(table_s.live)):
                continue
            in_topk, _ = _group_topk_mask(
                table_s,
                rows_s,
                jnp.ones(cap, jnp.bool_),
                self.limit,
                self.desc,
                self.group_by,
                self.order_col,
            )
            sel = np.flatnonzero(np.asarray(in_topk))
            pulled = pull_rows({n: rows_s[n] for n in self.names}, sel)
            mirror = self._emitted[s]
            for i in range(len(sel)):
                g = tuple(pulled[c][i].item() for c in self.group_by)
                pkv = tuple(pulled[c][i].item() for c in self.pk)
                mirror.setdefault(g, {})[pkv] = tuple(
                    pulled[n][i].item() for n in self.names
                )
        self._step = None


# -- mesh observability surface (meshprof / scale / memory governor) ------
def _sharded_top_n_state_nbytes(self) -> int:
    return int(
        sum(
            leaf.nbytes
            for leaf in jax.tree.leaves(
                (
                    self.table,
                    self.rows,
                    self.sdirty,
                    self.stored,
                    self.epoch_dirty,
                )
            )
        )
    )


def _sharded_top_n_shard_occupancy(self):
    """Per-shard claimed group-slot counts (autoscale + skew input).
    One packed device read."""
    return np.asarray(
        jnp.sum((self.table.fp1 != jnp.uint32(0)).astype(jnp.int32), axis=1)
    )


ShardedGroupTopN.state_nbytes = _sharded_top_n_state_nbytes
ShardedGroupTopN.state_nbytes_per_shard = stacked_state_nbytes_per_shard
ShardedGroupTopN.shard_occupancy = _sharded_top_n_shard_occupancy
