"""SourceManager — split-to-worker assignment with periodic discovery
and rebalancing.

Reference: src/meta/src/stream/source_manager.rs (54+): meta owns the
split set per source, discovers new partitions on a tick, assigns each
split to exactly one source actor, and ships assignment changes to the
actors as ``SourceChangeSplit`` barrier mutations; offsets travel with
the split so a reassigned split resumes exactly.

TPU re-design: the source executor is a host-side object (device work
starts after parsing), so "actors" here are WORKER SLOTS — disjoint
split subsets polled independently (a graph-mode session polls one
slot per parallel source instance; serial mode uses one slot). The
manager owns only the assignment; offsets stay in the executor's
checkpointable state, so rebalancing is metadata-only and exactly-once
survives any reassignment.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class SourceManager:
    """Assignment authority for every registered source.

    Invariants:
    - every discovered split is owned by exactly one worker slot;
    - rebalancing moves the MINIMUM number of splits (new splits fill
      the least-loaded slots first; a parallelism change reflows only
      the splits that must move);
    - offsets are never touched here (they live with the executor).
    """

    def __init__(self):
        # name -> (executor, parallelism, {split_id: worker})
        self._sources: Dict[str, Tuple[object, int, Dict[str, int]]] = {}
        self.changes_log: List[Tuple[str, str, int]] = []  # (src, split, worker)
        # credit-based admission (runtime/memory_governor.py): when
        # attached, every poll's max_rows_per_split is scaled by the
        # feeding fragment's credit window; credit 0 parks the source
        # (a zero-row poll — offsets stay anchored, exactly-once
        # untouched: lag, never loss)
        self._admission = None
        self._fragment_of: Dict[str, str] = {}

    def register(self, name: str, executor, parallelism: int = 1) -> None:
        if parallelism < 1:
            raise ValueError("parallelism must be >= 1")
        assign: Dict[str, int] = {}
        self._sources[name] = (executor, parallelism, assign)
        self._assign_new(name, [s.split_id for s in executor.splits])

    def unregister(self, name: str) -> None:
        self._sources.pop(name, None)
        self._fragment_of.pop(name, None)

    # -- admission ---------------------------------------------------------
    def attach_admission(
        self, admission, fragment_of: Optional[Dict[str, str]] = None
    ) -> None:
        """Wire an :class:`AdmissionController` (usually
        ``runtime.admission``) into the poll path. ``fragment_of``
        maps source name -> the runtime fragment it feeds, so the
        per-fragment credit window applies; an unmapped source is
        governed by the tightest window (conservative)."""
        self._admission = admission
        if fragment_of:
            self._fragment_of.update(fragment_of)

    def _admit(self, name: str, requested: int) -> int:
        if self._admission is None:
            return requested
        return self._admission.admit_rows(
            self._fragment_of.get(name), requested
        )

    def __contains__(self, name: str) -> bool:
        return name in self._sources

    def parallelism(self, name: str) -> int:
        return self._sources[name][1]

    def assignment(self, name: str) -> Dict[str, int]:
        """split_id -> worker slot (a copy)."""
        return dict(self._sources[name][2])

    def worker_splits(self, name: str, worker: int) -> set:
        _, _, assign = self._sources[name]
        return {sid for sid, w in assign.items() if w == worker}

    # -- discovery / rebalancing -----------------------------------------
    def _loads(self, name: str) -> List[int]:
        _, par, assign = self._sources[name]
        loads = [0] * par
        for w in assign.values():
            loads[w] += 1
        return loads

    def _assign_new(self, name: str, split_ids) -> List[str]:
        """Place unowned splits on the least-loaded slots (the
        reference's diff-assignment on discovery)."""
        _, par, assign = self._sources[name]
        fresh = [sid for sid in split_ids if sid not in assign]
        loads = self._loads(name)
        for sid in fresh:
            w = loads.index(min(loads))
            assign[sid] = w
            loads[w] += 1
            self.changes_log.append((name, sid, w))
        return fresh

    def discover(self, name: str) -> List[str]:
        """Re-enumerate the connector's splits (the periodic tick,
        source_manager.rs:54 discovery loop). Returns newly-assigned
        split ids; dropped splits leave the assignment."""
        executor, _, assign = self._sources[name]
        executor.discover()
        live = {s.split_id for s in executor.splits}
        for sid in [s for s in assign if s not in live]:
            del assign[sid]
        return self._assign_new(name, sorted(live))

    def set_parallelism(self, name: str, parallelism: int) -> Dict[str, int]:
        """Change the worker-slot count, reflowing ONLY the splits that
        must move (reference: scale on source fragments re-splits the
        assignment, preserving offsets). Returns the moves
        {split_id: new_worker}."""
        if parallelism < 1:
            raise ValueError("parallelism must be >= 1")
        executor, _, assign = self._sources[name]
        moves: Dict[str, int] = {}
        # drop slots >= parallelism: their splits must move
        homeless = sorted(
            sid for sid, w in assign.items() if w >= parallelism
        )
        for sid in homeless:
            del assign[sid]
        self._sources[name] = (executor, parallelism, assign)
        loads = self._loads(name)
        # rebalance: every slot should hold ceil/floor(n/par)
        n = len(assign)
        hi = -(-n // parallelism)
        for sid in homeless:
            w = loads.index(min(loads))
            assign[sid] = w
            loads[w] += 1
            moves[sid] = w
            self.changes_log.append((name, sid, w))
        # optional smoothing: pull from overloaded slots into idle ones
        for sid in sorted(assign):
            w = assign[sid]
            if loads[w] > hi:
                tgt = loads.index(min(loads))
                if loads[tgt] < hi and tgt != w:
                    loads[w] -= 1
                    loads[tgt] += 1
                    assign[sid] = tgt
                    moves[sid] = tgt
                    self.changes_log.append((name, sid, tgt))
        return moves

    # -- polling -----------------------------------------------------------
    def poll(
        self,
        name: str,
        worker: Optional[int] = None,
        max_rows_per_split: int = 4096,
        capacity: int = 1 << 12,
    ):
        """Poll one worker slot's splits (or every split when worker is
        None). Disjoint slots never double-read: the assignment
        partitions the split set."""
        executor, par, _ = self._sources[name]
        # admission clamp: credits scale the poll window; 0 rows is a
        # legitimate parked poll (offsets do not advance)
        max_rows_per_split = self._admit(name, max_rows_per_split)
        if worker is None:
            return executor.poll(max_rows_per_split, capacity)
        if not 0 <= worker < par:
            raise IndexError(f"worker {worker} out of range 0..{par - 1}")
        return executor.poll(
            max_rows_per_split, capacity,
            only=self.worker_splits(name, worker),
        )
