"""Fragment-graph runtime: actors, dispatchers, permit channels, merge.

Reference roles replaced (SURVEY.md §2.3 "Runtime (task layer)" + "Exchange"):
- ``LocalStreamManager`` building/driving actors from a fragment graph
  (src/stream/src/task/stream_manager.rs:89) -> ``GraphRuntime``;
- ``Actor`` as the scheduling unit driving its executor chain
  (src/stream/src/executor/actor.rs:131) -> ``FragmentActor`` threads;
- permit-based exchange channels with record budgets and barrier
  bypass (src/stream/src/executor/exchange/permit.rs:35-90) ->
  ``PermitChannel``;
- ``DispatchExecutor`` hash/broadcast/simple/round-robin routing
  (src/stream/src/executor/dispatch.rs:42,425,683,852,932,606) ->
  ``*Dispatcher``;
- ``MergeExecutor`` n-way barrier alignment — the Chandy-Lamport
  alignment point (src/stream/src/executor/merge.rs:32,
  executor/barrier_align.rs) -> the actor's input loop;
- ``LocalBarrierManager`` per-actor barrier collection
  (src/stream/src/task/barrier_manager.rs:857) ->
  ``GraphRuntime.inject_barrier`` waiting on the collect latch.

TPU re-design: actors are host threads (device programs already run
async on the TPU stream, so threads buy pipeline overlap of host
staging + device compute, not GIL-bound CPU parallelism). Hash dispatch
does NOT compact rows per downstream: each downstream receives the
same fixed-capacity chunk with ``valid`` narrowed to its vnode slice —
one fused device op per edge, zero host syncs, static shapes
throughout. Compaction happens only where a kernel needs it (the
sharded all_to_all exchange in parallel/exchange.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp

from risingwave_tpu import utils_sync_point as sync_point
from risingwave_tpu.analysis.jax_sanitizer import transfer_guard
from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.epoch_trace import record_stage
from risingwave_tpu.executors.base import Barrier, Epoch, Executor, Watermark
from risingwave_tpu.ops.hashing import VNODE_COUNT, hash_columns
from risingwave_tpu.profiler import PROFILER
from risingwave_tpu.runtime.pipeline import _pcall, _walk_watermark, walk_chain
from risingwave_tpu.trace import span


def _default_barrier_timeout() -> float:
    import os

    try:
        return float(os.environ.get("RW_BARRIER_TIMEOUT_S", "120"))
    except ValueError:
        return 120.0

# message kinds flowing through channels
CHUNK, BARRIER, WATERMARK, STOP = "chunk", "barrier", "watermark", "stop"


class PermitChannel:
    """Bounded in-process exchange edge (permit.rs:35).

    Data sends cost ``capacity-of-chunk`` record permits and block while
    the budget is exhausted; control messages (barrier / watermark /
    stop) bypass the budget so backpressure can never deadlock the
    barrier (the reference gives barriers their own semaphore,
    permit.rs:60)."""

    def __init__(
        self,
        record_permits: int = 1 << 16,
        cv: Optional[threading.Condition] = None,
        abort: Optional[threading.Event] = None,
        fence: Optional[threading.Event] = None,
    ):
        self._budget = record_permits
        self._avail = record_permits
        self._q: deque = deque()
        # consumers may share one Condition across all their input
        # channels to support wait-on-any (the reference's select over
        # upstream inputs, merge.rs:32)
        self._cv = cv if cv is not None else threading.Condition()
        # set when the graph is failing/being killed: blocked senders
        # must wake and drop instead of wedging forever on a dead
        # consumer's permits
        self._abort = abort
        # per-CONSUMER fence (partial recovery): while the consuming
        # actor is fenced for a scoped rebuild, data sends drop instead
        # of blocking or piling up — the runtime's replay buffer
        # re-derives that data into the rebuilt subtree. Control
        # messages still enqueue (the dead channel is discarded whole).
        self._fence = fence

    def send_chunk(self, chunk: StreamChunk) -> None:
        cost = min(chunk.capacity, self._budget)
        with self._cv:
            while self._avail < cost:
                if self._abort is not None and self._abort.is_set():
                    return  # graph aborting: drop data, never wedge
                if self._fence is not None and self._fence.is_set():
                    return  # consumer fenced for rebuild: drop, replay re-derives
                self._cv.wait(timeout=0.1)
            if self._fence is not None and self._fence.is_set():
                return
            self._avail -= cost
            self._q.append((CHUNK, chunk, cost, time.perf_counter()))
            self._cv.notify_all()

    def send_control(self, kind: str, payload=None) -> None:
        with self._cv:
            self._q.append((kind, payload, 0, time.perf_counter()))
            self._cv.notify_all()

    def recv(self, block: bool = True):
        """Pop one message, returning permits for data (permit.rs:80).
        Returns (kind, payload) or None when non-blocking and empty."""
        with self._cv:
            while not self._q:
                if not block:
                    return None
                self._cv.wait()
            kind, payload, cost, _enq = self._q.popleft()
            if cost:
                self._avail += cost
            self._cv.notify_all()
            return kind, payload

    def peek_kind(self) -> Optional[str]:
        with self._cv:
            return self._q[0][0] if self._q else None

    def oldest_pending(self) -> Optional[dict]:
        """Age of the head message + the first pending barrier's epoch,
        or None when empty — backpressure attribution's raw signal: a
        deep channel whose head is FRESH is draining; one whose head
        has been sitting since epoch N is stuck behind a slow consumer
        (the distinction a bare depth count cannot make)."""
        with self._cv:
            if not self._q:
                return None
            head_ts = self._q[0][3]
            epoch = None
            # bounded scan for the first barrier's epoch (channels are
            # permit-bounded; typical depth is tiny at barrier edges)
            for kind, payload, _cost, _ts in self._q:
                if kind == BARRIER:
                    epoch = getattr(
                        getattr(payload, "epoch", None), "curr", None
                    )
                    break
        return {
            "age_ms": (time.perf_counter() - head_ts) * 1e3,
            "epoch": epoch,
        }

    def __len__(self) -> int:
        with self._cv:
            return len(self._q)


# ---------------------------------------------------------------------------
# Dispatchers (dispatch.rs:425) — pure routing, one fused device op/edge
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(2, 3))
def _vnode_slice_mask(key_lanes, valid, n_down: int, dest: int):
    vnode = (hash_columns(key_lanes, seed=0xC0FFEE) % VNODE_COUNT).astype(
        jnp.int32
    )
    return valid & ((vnode % n_down) == dest)


class Dispatcher:
    """Routes an output chunk onto downstream channels."""

    def __init__(self, outputs: Sequence[PermitChannel]):
        self.outputs = list(outputs)

    def dispatch(self, chunk: StreamChunk) -> None:
        raise NotImplementedError

    def control(self, kind: str, payload=None) -> None:
        for ch in self.outputs:
            ch.send_control(kind, payload)


class HashDispatcher(Dispatcher):
    """vnode(dist key) routing (dispatch.rs:683 + vnode.rs:34): each
    downstream sees the full chunk with ``valid`` narrowed to its vnode
    share — same rows land on the same downstream forever, so keyed
    state is downstream-local."""

    def __init__(self, outputs, dist_keys: Sequence[str]):
        super().__init__(outputs)
        self.dist_keys = list(dist_keys)

    def dispatch(self, chunk: StreamChunk) -> None:
        n = len(self.outputs)
        if n == 1:
            self.outputs[0].send_chunk(chunk)
            return
        lanes = tuple(chunk.col(k) for k in self.dist_keys)
        for d, ch in enumerate(self.outputs):
            keep = _vnode_slice_mask(lanes, chunk.valid, n, d)
            ch.send_chunk(
                StreamChunk(chunk.columns, keep, chunk.nulls, chunk.ops)
            )


class BroadcastDispatcher(Dispatcher):
    """Every downstream gets every chunk (dispatch.rs:852)."""

    def dispatch(self, chunk: StreamChunk) -> None:
        for ch in self.outputs:
            ch.send_chunk(chunk)


class SimpleDispatcher(Dispatcher):
    """1:1 / NoShuffle edge (dispatch.rs:932)."""

    def dispatch(self, chunk: StreamChunk) -> None:
        self.outputs[0].send_chunk(chunk)


class RoundRobinDispatcher(Dispatcher):
    """Whole chunks rotate across downstreams (dispatch.rs:606) — only
    legal above stateless fragments."""

    def __init__(self, outputs):
        super().__init__(outputs)
        self._next = 0

    def dispatch(self, chunk: StreamChunk) -> None:
        self.outputs[self._next].send_chunk(chunk)
        self._next = (self._next + 1) % len(self.outputs)


def _mk_dispatcher(kind, outputs, dist_keys=None) -> Dispatcher:
    if kind == "hash":
        return HashDispatcher(outputs, dist_keys or [])
    if kind == "broadcast":
        return BroadcastDispatcher(outputs)
    if kind == "simple":
        return SimpleDispatcher(outputs)
    if kind == "round_robin":
        return RoundRobinDispatcher(outputs)
    raise ValueError(f"unknown dispatcher kind {kind!r}")


# ---------------------------------------------------------------------------
# Fragment actors
# ---------------------------------------------------------------------------


class _Collector:
    """Terminal 'dispatcher' for sink-less fragments: chunks land in a
    thread-safe list the driver can drain (test/CLI surface)."""

    def __init__(self):
        self.chunks: List[StreamChunk] = []
        self._lock = threading.Lock()

    def dispatch(self, chunk: StreamChunk) -> None:
        with self._lock:
            self.chunks.append(chunk)

    def control(self, kind: str, payload=None) -> None:
        pass

    def drain(self) -> List[StreamChunk]:
        with self._lock:
            out, self.chunks = self.chunks, []
            return out


class FragmentActor(threading.Thread):
    """One actor: aligned input loop -> executor chain -> dispatcher
    (actor.rs:165 run / :181 run_consumer).

    ``inputs`` is [(port, channel)]: port 0 feeds the main (or left)
    chain, port 1 the right chain of a two-input fragment. Barrier
    alignment: a channel that has yielded the current barrier is parked
    (not polled) until every channel reaches it — Chandy-Lamport
    alignment exactly as MergeExecutor/BarrierAligner do."""

    def __init__(
        self,
        name: str,
        chain: Sequence[Executor],
        inputs: Sequence[Tuple[int, PermitChannel]],
        dispatcher,
        mgr: "GraphRuntime",
        join=None,
        right_chain: Sequence[Executor] = (),
        tail: Sequence[Executor] = (),
        halt: Optional[threading.Event] = None,
    ):
        super().__init__(name=f"actor-{name}", daemon=True)
        self.actor_name = name
        self.chain = list(chain)
        self.join_exec = join
        self.right_chain = list(right_chain)
        self.tail = list(tail)
        self.inputs = list(inputs)
        self.dispatcher = dispatcher
        self.mgr = mgr
        # fence/halt for scoped rebuild (partial recovery): when set,
        # the run loop exits WITHOUT forwarding STOP — the whole
        # fenced subtree is discarded and rebuilt around fresh channels
        self.halt = halt if halt is not None else threading.Event()
        # True while processing a message / barrier (False only in the
        # idle wait) — the scoped rebuild's drain-quiesce reads this
        self.busy = True
        self.error: Optional[BaseException] = None
        # per-(channel,column) watermark frontier for min-alignment
        self._wm_seen: Dict[Tuple[int, str], int] = {}
        self._wm_sent: Dict[str, int] = {}
        self._stopped: List[bool] = [False] * len(self.inputs)

    # -- chain plumbing ---------------------------------------------------
    def _through(self, chain, chunks, barrier=None):
        return walk_chain(chain, chunks, barrier)

    def _emit(self, chunks: Sequence[StreamChunk]) -> None:
        for c in chunks:
            self.dispatcher.dispatch(c)

    def _process_chunk(self, port: int, chunk: StreamChunk) -> None:
        if self.join_exec is None:
            self._emit(self._through(self.chain, [chunk]))
            return
        if port == 0:
            outs = []
            for c in self._through(self.chain, [chunk]):
                outs.extend(
                    _pcall(self.join_exec, "apply", self.join_exec.apply_left, c)
                )
        else:
            outs = []
            for c in self._through(self.right_chain, [chunk]):
                outs.extend(
                    _pcall(self.join_exec, "apply", self.join_exec.apply_right, c)
                )
        self._emit(self._through(self.tail, outs))

    def _process_barrier(self, b: Barrier) -> None:
        # stall-injection site for tests (and the q7-wedge forensic
        # path): a delay here holds THIS actor's collection back while
        # the rest of the graph reaches the barrier
        sync_point.hit(f"actor_barrier:{self.actor_name}")
        import time as _time

        # epoch-correlated span: every actor a barrier crosses emits a
        # slice carrying (epoch, fragment, actor) — chrome_trace links
        # them with flow events, so one barrier is one arrow chain
        # across the actor threads in Perfetto
        t0 = _time.perf_counter()
        with span(
            "actor.barrier",
            epoch=b.epoch.curr,
            fragment=self.actor_name,
            actor=self.actor_name,
        ), PROFILER.barrier_window(fragment=self.actor_name):
            self._process_barrier_inner(b)
            t1 = _time.perf_counter()
            # flush + emit happened above; finish_barrier below is the
            # barrier-only device fence (staged-scalar materialization);
            # transfer_guard (when armed) rejects implicit transfers here
            with transfer_guard():
                for ex in self.executors:
                    ex.finish_barrier()
        t2 = _time.perf_counter()
        record_stage("dispatch", (t1 - t0) * 1e3, fragment=self.actor_name)
        record_stage("device_step", (t2 - t1) * 1e3, fragment=self.actor_name)
        if b.checkpoint and self.mgr.capture_deltas:
            # pipelined barriers: seal this epoch's delta NOW, before
            # any next-epoch chunk in the input queue mutates state
            # (shared-buffer seal; uploader.rs:548 overlap analogue)
            for ex in self.executors:
                cap = getattr(ex, "capture_checkpoint", None)
                if cap is not None:
                    cap()
        self.dispatcher.control(BARRIER, b)
        self.mgr._collect(self.actor_name, b)

    def _process_barrier_inner(self, b: Barrier) -> None:
        # watermarks generated behind the barrier are sent AFTER the
        # flushed data chunks: channels are FIFO, so sending the
        # watermark first would let it overtake the very rows it covers
        # and a downstream window/filter would drop them as late
        wms: List[Watermark] = []
        if self.join_exec is None:
            outs = self._through(self.chain, [], barrier=b)
            gen: List[StreamChunk] = []
            for i, ex in enumerate(self.chain):
                wm = ex.emit_watermark()
                if wm is not None:
                    down, flushed = _walk_watermark(self.chain[i + 1 :], wm)
                    gen.extend(flushed)
                    if down is not None:
                        wms.append(down)
            self._emit(outs + gen)
        else:
            joined: List[StreamChunk] = []
            for c in self._through(self.chain, [], barrier=b):
                joined.extend(
                    _pcall(self.join_exec, "apply", self.join_exec.apply_left, c)
                )
            for c in self._through(self.right_chain, [], barrier=b):
                joined.extend(
                    _pcall(self.join_exec, "apply", self.join_exec.apply_right, c)
                )
            joined.extend(
                _pcall(self.join_exec, "flush", self.join_exec.on_barrier, b)
            )
            outs = self._through(self.tail, joined, barrier=b)
            gen, gwms = self._generated_watermarks_join()
            wms.extend(gwms)
            self._emit(outs + gen)
        for wm in wms:
            self._send_watermark_downstream(wm)

    def _generated_watermarks_join(self):
        """Poll emit_watermark across a two-input fragment's chains
        (mirrors TwoInputPipeline._generated_watermarks): side-chain
        watermarks walk the rest of their chain, through the join's
        per-side cleanup/alignment, then the tail. Returns
        (chunks_to_emit, watermarks_for_downstream)."""
        outs: List[StreamChunk] = []
        wms: List[Watermark] = []
        aligned: Optional[Watermark] = None
        for chain, feed in (
            (self.chain, self.join_exec.apply_left),
            (self.right_chain, self.join_exec.apply_right),
        ):
            for i, ex in enumerate(chain):
                wm = ex.emit_watermark()
                if wm is None:
                    continue
                wm, pending = _walk_watermark(chain[i + 1 :], wm)
                for c in pending:
                    outs.extend(feed(c))
                if wm is not None:
                    down, flushed = self.join_exec.on_watermark(wm)
                    outs.extend(flushed)
                    if down is not None:
                        aligned = down
        outs = self._through(self.tail, outs)
        if aligned is not None:
            dt, touts = _walk_watermark(self.tail, aligned)
            outs.extend(touts)
            if dt is not None:
                wms.append(dt)
        for i, ex in enumerate(self.tail):
            wm = ex.emit_watermark()
            if wm is not None:
                dt, touts = _walk_watermark(self.tail[i + 1 :], wm)
                outs.extend(touts)
                if dt is not None:
                    wms.append(dt)
        return outs, wms

    def _process_watermark(self, chan_idx: int, wm: Watermark) -> None:
        """Min-align watermarks across input channels (the reference
        aligns per-input watermarks on merge, executor/merge.rs), then
        walk the chain with the aligned value."""
        self._wm_seen[(chan_idx, wm.column)] = wm.value
        self._try_align(wm.column)

    def _realign_after_stop(self) -> None:
        """A channel just stopped: columns waiting on it may now align
        across the remaining live inputs."""
        for col in {c for (_ci, c) in self._wm_seen}:
            self._try_align(col)

    def _try_align(self, column: str) -> None:
        # align against LIVE channels only: a stopped upstream never
        # sends another watermark, so counting it would stall EOWC /
        # window operators downstream forever
        live = [i for i in range(len(self.inputs)) if not self._stopped[i]]
        vals = [
            v
            for (ci, col), v in self._wm_seen.items()
            if col == column and not self._stopped[ci]
        ]
        if not vals or len(vals) < len(live):
            return  # some live input has not reached any watermark yet
        aligned = min(vals)
        if aligned <= self._wm_sent.get(column, -(1 << 62)):
            return
        self._wm_sent[column] = aligned
        awm = Watermark(column, aligned)
        if self.join_exec is None:
            down, outs = _walk_watermark(self.chain, awm)
            self._emit(outs)
            if down is not None:
                self._send_watermark_downstream(down)
            return
        outs: List[StreamChunk] = []
        down_join: Optional[Watermark] = None
        for side_chain, feed in (
            (self.chain, self.join_exec.apply_left),
            (self.right_chain, self.join_exec.apply_right),
        ):
            swm, pending = _walk_watermark(side_chain, awm)
            for c in pending:
                outs.extend(feed(c))
            if swm is not None:
                dj, flushed = self.join_exec.on_watermark(swm)
                outs.extend(flushed)
                if dj is not None:
                    down_join = dj
        self._emit(self._through(self.tail, outs))
        if down_join is not None:
            dt, touts = _walk_watermark(self.tail, down_join)
            self._emit(touts)
            if dt is not None:
                self._send_watermark_downstream(dt)

    def _send_watermark_downstream(self, wm: Watermark) -> None:
        self.dispatcher.control(WATERMARK, wm)

    # -- input loop -------------------------------------------------------
    def run(self) -> None:  # pragma: no cover - exercised via runtime
        try:
            self._run_loop()
        except BaseException as e:  # noqa: BLE001 - surfaced to driver
            self.error = e
            self.mgr._actor_failed(self.actor_name, e)
        finally:
            self.busy = False  # a dead actor must not wedge drain-quiesce

    def _run_loop(self) -> None:
        n = len(self.inputs)
        parked: List[Optional[Barrier]] = [None] * n
        stopped = self._stopped
        while True:
            if self.halt.is_set():
                # fenced for a scoped rebuild: exit quietly (no STOP —
                # the downstream subtree is fenced and rebuilt with us)
                return
            progressed = False
            for i, (port, ch) in enumerate(self.inputs):
                if stopped[i] or parked[i] is not None:
                    continue
                msg = ch.recv(block=False)
                if msg is None:
                    continue
                progressed = True
                kind, payload = msg
                if kind == CHUNK:
                    self._process_chunk(port, payload)
                elif kind == WATERMARK:
                    self._process_watermark(i, payload)
                elif kind == BARRIER:
                    parked[i] = payload
                elif kind == STOP:
                    stopped[i] = True
                    self._realign_after_stop()
            live = [i for i in range(n) if not stopped[i]]
            if not live:
                self.dispatcher.control(STOP)
                return
            pend = [parked[i] for i in live]
            if all(b is not None for b in pend):
                b = pend[0]
                for other in pend[1:]:
                    if other.epoch != b.epoch:
                        raise RuntimeError(
                            f"{self.actor_name}: misaligned barriers "
                            f"{other.epoch} vs {b.epoch}"
                        )
                for i in live:
                    parked[i] = None
                self._process_barrier(b)
                progressed = True
            if not progressed:
                # select over inputs (merge.rs:32): all the actor's
                # channels share one Condition, so wait until ANY
                # unparked live channel has a message, then re-poll
                waitable = [
                    self.inputs[i][1] for i in live if parked[i] is None
                ]
                if waitable:
                    cv = waitable[0]._cv
                    self.busy = False
                    try:
                        with cv:
                            cv.wait_for(
                                lambda: self.halt.is_set()
                                or any(len(ch._q) for ch in waitable),
                                timeout=1.0,
                            )
                    finally:
                        self.busy = True

    @property
    def executors(self) -> List[Executor]:
        exs = list(self.chain) + list(self.right_chain)
        if self.join_exec is not None:
            exs.append(self.join_exec)
        exs.extend(self.tail)
        return exs


# ---------------------------------------------------------------------------
# Graph spec + runtime
# ---------------------------------------------------------------------------


@dataclass
class FragmentSpec:
    """One fragment of the stream graph (stream_fragmenter/mod.rs:26).

    ``build(instance_idx)`` returns either a list of executors
    (single-input chain) or a dict ``{"left": [...], "right": [...],
    "join": ex, "tail": [...]}``. ``inputs`` names upstream fragments
    as (fragment_name, port). ``dispatch`` is "simple" | "broadcast" |
    "round_robin" | ("hash", [dist_keys]). ``parallelism`` instantiates
    N actors; hash-dispatching upstreams route vnodes across them
    (Distribution::Hash, schedule.rs:131)."""

    name: str
    build: Callable[[int], object]
    inputs: List[Tuple[str, int]] = field(default_factory=list)
    dispatch: object = "simple"
    parallelism: int = 1


class GraphRuntime:
    """LocalStreamManager analogue: owns channels + actors, injects
    barriers at sources, waits for whole-graph collection.

    Actor supervision (partial recovery): an actor failure is
    attributed to its FRAGMENT; the supervisor computes the
    downstream-closure blast radius and fences ONLY that subtree
    (threads exit, channels into it drop data) — fragments outside the
    blast keep running so a scoped rebuild can splice a fresh subtree
    back in (``rebuild_scoped``). When the blast radius reaches a
    source fragment or covers the whole graph, the supervisor falls
    back to the stop-the-world abort (today's contract)."""

    def __init__(
        self,
        specs: Sequence[FragmentSpec],
        channel_permits: int = 1 << 16,
        epoch_batch: bool = True,
    ):
        self.specs = {s.name: s for s in specs}
        self._channel_permits = channel_permits
        self._epoch_batch = epoch_batch
        # pipelined barriers: actors seal checkpoint deltas at the
        # barrier instead of the runtime staging after a full drain
        self.capture_deltas = False
        self.actors: List[FragmentActor] = []
        self.collectors: Dict[str, _Collector] = {}
        self._source_channels: Dict[str, List[PermitChannel]] = {}
        self._collect_lock = threading.Condition()
        self._collected: Dict[int, set] = {}
        # last epoch each actor fully collected (stall-dump attribution:
        # the actor whose last epoch lags is the stuck one)
        self._last_collected: Dict[str, int] = {}
        self._failure: Optional[BaseException] = None
        self._epoch = 0
        self._source_rr: Dict[str, int] = {}
        self._abort = threading.Event()
        # -- actor supervisor state (fragment-scoped failover) ----------
        # actor name -> the exception that killed it
        self.actor_errors: Dict[str, BaseException] = {}
        # fragments whose actors died / are fenced (the blast radius)
        self.failed_fragments: Set[str] = set()
        self.fenced_fragments: Set[str] = set()
        self._build(specs)

    # -- graph build (ActorGraphBuilder analogue, actor.rs:648) ----------
    def _build(self, specs: Sequence[FragmentSpec]) -> None:
        # wiring is RETAINED (not just consumed) so a scoped rebuild can
        # replace one subtree's channels/actors and re-point the live
        # upstream dispatchers at the fresh channels:
        #   _in_ch[name][inst]         -> [(port, channel)]
        #   _out_edges[name][inst]     -> [(down_name, [channels])]
        #   _edge_disp[(up,ui,down,k)] -> the per-edge Dispatcher (k =
        #                                 ordinal of the (up,down) pair,
        #                                 for duplicate edges e.g. both
        #                                 join ports fed by one source)
        #   _cvs/_halts[(name, inst)]  -> per-actor Condition / fence
        self._in_ch: Dict[str, List[List[Tuple[int, PermitChannel]]]] = {
            s.name: [[] for _ in range(s.parallelism)] for s in specs
        }
        # out_edges[up_name][up_instance] — each UPSTREAM INSTANCE gets
        # its own channel into every downstream instance (merge.rs:32
        # selects over per-upstream-ACTOR inputs): M parallel senders
        # sharing one channel would deliver M barriers down a single
        # input and double-flush the consumer
        self._out_edges: Dict[
            str, List[List[Tuple[str, List[PermitChannel]]]]
        ] = {s.name: [[] for _ in range(s.parallelism)] for s in specs}
        self._edge_disp: Dict[Tuple[str, int, str, int], Dispatcher] = {}
        # one Condition per actor instance, shared by ALL its input
        # channels — enables select/wait-on-any in the input loop
        self._cvs = {
            (s.name, i): threading.Condition()
            for s in specs
            for i in range(s.parallelism)
        }
        self._halts = {
            (s.name, i): threading.Event()
            for s in specs
            for i in range(s.parallelism)
        }
        for s in specs:
            self._wire_inputs(s)

        # source fragments: the manager is their upstream — channels
        # must exist BEFORE actors copy their input lists
        for s in specs:
            if not s.inputs:
                srcs = []
                for inst in range(s.parallelism):
                    ch = PermitChannel(
                        self._channel_permits,
                        cv=self._cvs[(s.name, inst)],
                        abort=self._abort,
                        fence=self._halts[(s.name, inst)],
                    )
                    self._in_ch[s.name][inst].append((0, ch))
                    srcs.append(ch)
                self._source_channels[s.name] = srcs

        for s in specs:
            for inst in range(s.parallelism):
                self._spawn_actor(s, inst)

    def _wire_inputs(self, s: FragmentSpec) -> None:
        """Create the channels feeding fragment ``s`` and register them
        on the upstream edge lists (build + scoped-rebuild shared)."""
        for up_name, port in s.inputs:
            up = self.specs[up_name]
            for ui in range(up.parallelism):
                chans = []
                for di in range(s.parallelism):
                    ch = PermitChannel(
                        self._channel_permits,
                        cv=self._cvs[(s.name, di)],
                        abort=self._abort,
                        fence=self._halts[(s.name, di)],
                    )
                    self._in_ch[s.name][di].append((port, ch))
                    chans.append(ch)
                self._out_edges[up_name][ui].append((s.name, chans))

    def _spawn_actor(self, s: FragmentSpec, inst: int) -> FragmentActor:
        built = s.build(inst)
        if self._epoch_batch:
            # collapse each chain's maximal fusible run into ONE
            # donated device program per barrier (runtime/fused_step);
            # RW_FUSED_STEP=0 falls back to the per-epoch batched
            # (interpreted) path. Either way the actor's data path
            # only changes — the pipeline's checkpoint registry keeps
            # holding the original executor objects, so recovery
            # rebuilds re-fuse around restored state automatically.
            from risingwave_tpu.executors.epoch_batch import (
                fuse_epoch_batch,
            )
            from risingwave_tpu.runtime.fused_step import (
                fuse_chain,
                fused_enabled,
            )

            if fused_enabled():
                fuse = lambda ch, lbl: fuse_chain(ch, label=lbl)
            else:
                fuse = lambda ch, lbl: fuse_epoch_batch(ch)
            if isinstance(built, dict):
                if fused_enabled():
                    # the tail is fed by the actor's join: pass it as
                    # the upstream so a lattice-compatible join-fed MV
                    # tail fuses (fixed out_cap emission = closed shape
                    # family) instead of interpreting per chunk
                    tail = fuse_chain(
                        built.get("tail", []),
                        label=f"{s.name}/tail",
                        upstream=built.get("join"),
                    )
                else:
                    tail = fuse(built.get("tail", []), f"{s.name}/tail")
                built = dict(
                    built,
                    left=fuse(built.get("left", []), f"{s.name}/left"),
                    right=fuse(built.get("right", []), f"{s.name}/right"),
                    tail=tail,
                )
            else:
                built = fuse(built, s.name)
        downstream = self._out_edges[s.name][inst]
        if downstream:
            # one dispatcher fanning to every downstream edge:
            # wrap per-edge dispatchers in a multiplexer
            per_edge = []
            seen: Dict[str, int] = {}
            for down_name, chans in downstream:
                kind = s.dispatch
                keys = None
                if isinstance(kind, tuple):
                    kind, keys = kind
                d = _mk_dispatcher(kind, chans, keys)
                o = seen.get(down_name, 0)
                seen[down_name] = o + 1
                self._edge_disp[(s.name, inst, down_name, o)] = d
                per_edge.append(d)
            dispatcher = _MultiDispatcher(per_edge)
        else:
            coll = self.collectors.setdefault(s.name, _Collector())
            dispatcher = coll
        if isinstance(built, dict):
            actor = FragmentActor(
                f"{s.name}#{inst}",
                built.get("left", []),
                self._in_ch[s.name][inst],
                dispatcher,
                self,
                join=built["join"],
                right_chain=built.get("right", []),
                tail=built.get("tail", []),
                halt=self._halts[(s.name, inst)],
            )
        else:
            actor = FragmentActor(
                f"{s.name}#{inst}",
                built,
                self._in_ch[s.name][inst],
                dispatcher,
                self,
                halt=self._halts[(s.name, inst)],
            )
        self.actors.append(actor)
        return actor

    # -- supervisor topology helpers -------------------------------------
    @staticmethod
    def fragment_of(actor_name: str) -> str:
        """Actor names are ``{fragment}#{instance}``."""
        return actor_name.rsplit("#", 1)[0]

    def source_fragment_names(self) -> Set[str]:
        return {s.name for s in self.specs.values() if not s.inputs}

    def downstream_closure(self, fragment: str) -> Set[str]:
        """Every fragment transitively consuming ``fragment``'s output."""
        down: Dict[str, List[str]] = {n: [] for n in self.specs}
        for s in self.specs.values():
            for up, _port in s.inputs:
                down.setdefault(up, []).append(s.name)
        out: Set[str] = set()
        stack = [fragment]
        while stack:
            for d in down.get(stack.pop(), ()):
                if d not in out:
                    out.add(d)
                    stack.append(d)
        return out

    def blast_radius(self, fragment: str) -> Set[str]:
        """The fragments a failure in ``fragment`` poisons: itself plus
        its downstream closure (state derived from its output can no
        longer be trusted past the last committed epoch)."""
        return {fragment} | self.downstream_closure(fragment)

    def _fence(self, fragments: Set[str]) -> None:
        """Fence a subtree: its actor threads exit (halt events), and
        channels into it start dropping data (the channel-level fence
        is the same event). Callers hold no locks."""
        for (name, _inst), h in self._halts.items():
            if name in fragments:
                h.set()
        # wake every fenced actor's select wait AND any sender blocked
        # on a fenced channel's permits (they share the consumer's cv)
        for (name, inst), cv in self._cvs.items():
            if name in fragments:
                with cv:
                    cv.notify_all()

    def rebuild_scoped(self, fragments: Set[str]) -> None:
        """Splice a fresh subtree in place of ``fragments`` (which must
        be downstream-closed and source-free — the supervisor's blast
        radius): halt + reap their actors, drain-quiesce the surviving
        actors so nothing from the failed window leaks past the fence,
        rebuild the subtree's channels/actors around the SAME executor
        objects (their state is restored separately), and re-point the
        live upstream dispatchers at the fresh channels."""
        fragments = set(fragments)
        unknown = fragments - set(self.specs)
        if unknown:
            raise KeyError(f"unknown fragments {sorted(unknown)}")
        for n in fragments:
            if not self.specs[n].inputs:
                raise ValueError(
                    f"cannot scope-rebuild source fragment {n!r} — the "
                    "blast radius reached a source; use a full rebuild"
                )
            missing = self.downstream_closure(n) - fragments
            if missing:
                raise ValueError(
                    f"scope {sorted(fragments)} is not downstream-closed: "
                    f"{n!r} also feeds {sorted(missing)}"
                )
        # 1. fence + reap the subtree's actors
        self._fence(fragments)
        doomed = [
            a for a in self.actors
            if self.fragment_of(a.actor_name) in fragments
        ]
        for a in doomed:
            a.join(timeout=10.0)
        stuck = [a.actor_name for a in doomed if a.is_alive()]
        if stuck:
            raise RuntimeError(
                f"scoped rebuild: fenced actors would not halt: {stuck}"
            )
        self.actors = [
            a for a in self.actors
            if self.fragment_of(a.actor_name) not in fragments
        ]
        # 2. drain-quiesce the survivors: any message still queued from
        # the failed window must land in the OLD fenced channels (and
        # drop there) BEFORE dispatchers are re-pointed at fresh ones —
        # otherwise pre-fence data would leak into the rebuilt subtree
        # and the replay would double-apply it
        deadline = time.monotonic() + 15.0

        def _quiet() -> bool:
            # dead survivors (a concurrent failure in a DISJOINT subtree)
            # are someone else's recovery; only live actors must idle
            return all(
                not a.busy and all(len(ch) == 0 for _p, ch in a.inputs)
                for a in self.actors
                if a.is_alive()
            )
        while True:
            if _quiet():
                time.sleep(0.02)  # grace: recv->process handoff window
                if _quiet():
                    break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "scoped rebuild: surviving actors did not quiesce"
                )
            time.sleep(0.005)
        # 3. fresh per-actor state + channels for the subtree
        ordered = [s for s in self.specs.values() if s.name in fragments]
        for s in ordered:
            for inst in range(s.parallelism):
                self._cvs[(s.name, inst)] = threading.Condition()
                self._halts[(s.name, inst)] = threading.Event()
            self._in_ch[s.name] = [[] for _ in range(s.parallelism)]
            self._out_edges[s.name] = [[] for _ in range(s.parallelism)]
            # stale drained output of the crashed epoch dies with the
            # old collector; the replay refills a fresh one
            self.collectors.pop(s.name, None)
        for s in ordered:
            self._wire_scoped_inputs(s, fragments)
        # 4. reset supervisor + collection state FOR THIS SCOPE ONLY —
        # a concurrent failure in a disjoint subtree (its actors died
        # while we rebuilt this one) must stay recorded, or the next
        # barrier would stall unattributably against its dead actors
        with self._collect_lock:
            for a in [
                a
                for a in self.actor_errors
                if self.fragment_of(a) in fragments
            ]:
                del self.actor_errors[a]
            self.failed_fragments -= fragments
            self.fenced_fragments -= fragments
            self._failure = next(iter(self.actor_errors.values()), None)
            self._collected.clear()
            self._collect_lock.notify_all()
        fresh = []
        for s in ordered:
            for inst in range(s.parallelism):
                fresh.append(self._spawn_actor(s, inst))
        for a in fresh:
            a.start()

    def _wire_scoped_inputs(self, s: FragmentSpec, fragments: Set[str]) -> None:
        """``_wire_inputs`` for a scoped rebuild: edges from upstreams
        OUTSIDE the scope re-point the existing live dispatcher at the
        fresh channels (matching duplicate edges by ordinal)."""
        seen: Dict[Tuple[str, str], int] = {}
        for up_name, port in s.inputs:
            up = self.specs[up_name]
            o = seen.get((up_name, s.name), 0)
            seen[(up_name, s.name)] = o + 1
            for ui in range(up.parallelism):
                chans = []
                for di in range(s.parallelism):
                    ch = PermitChannel(
                        self._channel_permits,
                        cv=self._cvs[(s.name, di)],
                        abort=self._abort,
                        fence=self._halts[(s.name, di)],
                    )
                    self._in_ch[s.name][di].append((port, ch))
                    chans.append(ch)
                if up_name in fragments:
                    self._out_edges[up_name][ui].append((s.name, chans))
                else:
                    edges = self._out_edges[up_name][ui]
                    idx = [
                        i for i, (dn, _c) in enumerate(edges)
                        if dn == s.name
                    ][o]
                    edges[idx] = (s.name, chans)
                    self._edge_disp[(up_name, ui, s.name, o)].outputs = (
                        list(chans)
                    )

    def start(self) -> "GraphRuntime":
        for a in self.actors:
            a.start()
        return self

    # -- driver surface ---------------------------------------------------
    def inject_chunk(self, source: str, chunk: StreamChunk, instance=None):
        chans = self._source_channels[source]
        if instance is None:  # round-robin over source instances
            rr = self._source_rr.get(source, 0)
            self._source_rr[source] = (rr + 1) % len(chans)
            instance = rr
        chans[instance].send_chunk(chunk)

    def inject_watermark(
        self, column: str, value: int, source: Optional[str] = None
    ) -> None:
        for name, chans in self._source_channels.items():
            if source is not None and name != source:
                continue
            for ch in chans:
                ch.send_control(WATERMARK, Watermark(column, value))

    def inject_barrier_nowait(
        self, checkpoint: bool = True, epoch: Optional[int] = None
    ) -> Barrier:
        """Send a barrier into every source WITHOUT waiting for
        collection — channels are FIFO, so pushes enqueued after this
        belong to the next epoch while actors still process this one
        (the reference's in-flight barriers, barrier/mod.rs:538)."""
        prev = self._epoch
        target = epoch if epoch is not None else prev + 1
        if target <= prev:
            raise ValueError(f"epoch {target} <= previous {prev}")
        self._epoch = target
        b = Barrier(Epoch(prev, self._epoch), checkpoint)
        with self._collect_lock:
            self._collected[target] = set()
        for chans in self._source_channels.values():
            for ch in chans:
                ch.send_control(BARRIER, b)
        return b

    def wait_barrier(self, epoch: int, timeout: Optional[float] = None) -> None:
        """Block until every actor collected ``epoch``
        (barrier_manager.rs:857 collect).

        ``timeout`` is a deadman for a silently-stuck actor, not the
        failure path (a raising actor sets ``_failure`` and wakes us
        immediately). Default comes from ``RW_BARRIER_TIMEOUT_S`` (else
        120s): the first epoch on a tunneled TPU spends minutes inside
        XLA compiles, so device benches raise it via the env var."""
        if timeout is None:
            timeout = _default_barrier_timeout()
        from risingwave_tpu import blackbox

        deadline = time.perf_counter() + timeout
        pred = (
            lambda: self._failure is not None
            or len(self._collected.get(epoch, ())) == len(self.actors)
        )
        with self._collect_lock:
            try:
                # sliced wait: the full deadman stands, but an armed
                # device-wedge sentinel converts the hang into a
                # structured DeviceWedged within ~a slice instead of
                # burning the whole barrier timeout (the q7 wedge used
                # to sit here for 360s and then die evidence-free)
                while True:
                    remain = deadline - time.perf_counter()
                    ok = self._collect_lock.wait_for(
                        pred, timeout=max(0.0, min(1.0, remain))
                    )
                    if ok or remain <= 0:
                        break
                    wedged = blackbox.SENTINEL.wedged_error()
                    if wedged is not None:
                        got = self._collected.get(epoch, set())
                        stuck = sorted(
                            a.actor_name
                            for a in self.actors
                            if a.actor_name not in got
                        )
                        # forensics on a SIDE thread, raise NOW: the
                        # dump's device sections (memory_stats, array
                        # census) can block on the very wedge being
                        # reported, and it must not do so holding the
                        # collect lock — fail-fast first, evidence
                        # best-effort (same arm-first rule the
                        # sentinel's bundle capture follows)
                        from risingwave_tpu.epoch_trace import dump_stalls

                        threading.Thread(
                            target=dump_stalls,
                            args=(
                                f"device wedged while barrier {epoch} "
                                f"awaited {stuck}: {wedged}",
                            ),
                            kwargs={"graph": self},
                            daemon=True,
                            name="rw-wedge-dump",
                        ).start()
                        raise wedged
                if self._failure is not None:
                    raise RuntimeError("actor failed") from self._failure
                if not ok:
                    got = self._collected.get(epoch, set())
                    stuck = sorted(
                        a.actor_name
                        for a in self.actors
                        if a.actor_name not in got
                    )
                    # forensic artifact BEFORE the epoch is abandoned
                    # (the q7 wedge left zero diagnostics without this)
                    from risingwave_tpu.epoch_trace import dump_stalls

                    dump_stalls(
                        f"barrier {epoch} timed out after {timeout}s; "
                        f"stuck actors: {stuck}",
                        graph=self,
                    )
                    raise TimeoutError(
                        f"barrier {epoch} not collected: "
                        f"{len(got)}/{len(self.actors)} actors "
                        f"(stuck: {', '.join(stuck)})"
                    )
            finally:
                self._collected.pop(epoch, None)

    def inject_barrier(
        self,
        checkpoint: bool = True,
        timeout: Optional[float] = None,
        epoch: Optional[int] = None,
    ) -> Barrier:
        """Send a barrier into every source and block until every actor
        collected it. ``epoch`` pins the barrier's curr epoch (a
        runtime passes its own clock so the graph's epochs line up with
        checkpoint manifests)."""
        t0 = time.perf_counter()
        b = self.inject_barrier_nowait(checkpoint=checkpoint, epoch=epoch)
        self.wait_barrier(b.epoch.curr, timeout=timeout)
        if PROFILER.enabled:
            # slow-barrier auto-capture for graph-only drivers (the
            # StreamingRuntime hooks its own barrier clock separately)
            PROFILER.observe_barrier((time.perf_counter() - t0) * 1e3)
        return b

    def stop(self, timeout: float = 30.0) -> None:
        for chans in self._source_channels.values():
            for ch in chans:
                ch.send_control(STOP)
        for a in self.actors:
            a.join(timeout=timeout)
        if any(a.is_alive() for a in self.actors):
            # graceful drain failed (e.g. an actor died and its upstream
            # is wedged on permits): abort wakes blocked senders to drop
            self._abort.set()
            for a in self.actors:
                a.join(timeout=5.0)
        # wake anyone blocked in wait_barrier on an epoch this graph
        # will never collect (a pipelined closer during recovery)
        with self._collect_lock:
            if self._failure is None and self._collected:
                self._failure = RuntimeError("graph stopped")
            self._collect_lock.notify_all()

    def drain(self, name: str) -> List[StreamChunk]:
        return self.collectors[name].drain()

    def stall_snapshot(self) -> Dict[str, object]:
        """Forensic view for dump_stalls: per-actor liveness, input
        permit-channel depths, last-collected epoch, and which actors
        every pending epoch is still waiting on (the await-tree dump's
        actor table). Cheap and lock-safe — called while wedged."""
        with self._collect_lock:
            pending = {e: set(s) for e, s in self._collected.items()}
            last = dict(self._last_collected)
            failure = repr(self._failure) if self._failure else None
            failed = sorted(self.failed_fragments)
            blast = sorted(self.fenced_fragments)
            errors = {a: repr(e) for a, e in self.actor_errors.items()}
        actors = []
        for a in self.actors:
            # oldest-pending AGE per input channel (not just depth): a
            # deep-but-draining channel shows age ~0; one stuck since
            # epoch N names the epoch it has been holding
            oldest = []
            for _p, ch in a.inputs:
                op = ch.oldest_pending()
                oldest.append(
                    None
                    if op is None
                    else {
                        "age_ms": round(op["age_ms"], 3),
                        "epoch": op["epoch"],
                    }
                )
            actors.append(
                {
                    "actor": a.actor_name,
                    # fragment provenance: a partial-recovery wedge is
                    # debuggable from the artifact alone (which subtree
                    # was fenced, which fragment each actor belongs to)
                    "fragment": self.fragment_of(a.actor_name),
                    "fenced": self.fragment_of(a.actor_name)
                    in self.fenced_fragments,
                    "alive": a.is_alive(),
                    "last_collected_epoch": last.get(a.actor_name, 0),
                    "input_depths": [len(ch) for _p, ch in a.inputs],
                    "input_oldest": oldest,
                    "error": repr(a.error) if a.error else None,
                }
            )
        names = [a.actor_name for a in self.actors]
        return {
            "epoch": self._epoch,
            "failure": failure,
            "failed_fragments": failed,
            "blast_radius": blast,
            "actor_errors": errors,
            "actors": actors,
            "epochs_pending": {
                str(e): {
                    "collected": sorted(got),
                    "stuck": sorted(n for n in names if n not in got),
                }
                for e, got in pending.items()
            },
        }

    @property
    def executors(self) -> List[Executor]:
        out = []
        for a in self.actors:
            out.extend(a.executors)
        return out

    # -- actor callbacks --------------------------------------------------
    def _collect(self, actor_name: str, b: Barrier) -> None:
        with self._collect_lock:
            self._last_collected[actor_name] = max(
                self._last_collected.get(actor_name, 0), b.epoch.curr
            )
            # stragglers from an abandoned (timed-out) epoch are dropped,
            # not re-registered — only live epochs have an entry
            if b.epoch.curr in self._collected:
                self._collected[b.epoch.curr].add(actor_name)
                self._collect_lock.notify_all()

    def _actor_failed(self, actor_name: str, err: BaseException) -> None:
        """Actor supervisor (replaces the old global-abort contract):
        attribute the failure to the actor's fragment, compute the
        blast radius, and fence ONLY that subtree — fragments outside
        it keep running and a scoped rebuild splices a fresh subtree
        back in. Stop-the-world abort remains the fallback when the
        blast radius reaches a source or covers the whole graph."""
        frag = self.fragment_of(actor_name)
        blast = self.blast_radius(frag)
        whole = bool(blast & self.source_fragment_names()) or blast >= set(
            self.specs
        )
        with self._collect_lock:
            self.actor_errors[actor_name] = err
            self.failed_fragments.add(frag)
            self.fenced_fragments |= blast
            if self._failure is None:
                self._failure = err
            self._collect_lock.notify_all()
        if whole:
            # no fragment can make progress: wake senders blocked on
            # the dead consumer and drop (today's full-recovery path)
            self._abort.set()
        else:
            self._fence(blast)
        try:
            from risingwave_tpu.event_log import EVENT_LOG
            from risingwave_tpu.metrics import REGISTRY

            REGISTRY.counter("actor_failures_total").inc(fragment=frag)
            EVENT_LOG.record(
                "actor_failure",
                actor=actor_name,
                fragment=frag,
                blast_radius=sorted(blast),
                whole_graph=whole,
                cause=repr(err),
            )
        except Exception:  # pragma: no cover - telemetry must not mask err
            pass


class _MultiDispatcher:
    """Fans one fragment's output across all its downstream edges, each
    with its own dispatcher kind (DispatchExecutor holds one
    DispatcherImpl per downstream fragment edge, dispatch.rs:42)."""

    def __init__(self, dispatchers: Sequence[Dispatcher]):
        self.dispatchers = list(dispatchers)

    def dispatch(self, chunk: StreamChunk) -> None:
        for d in self.dispatchers:
            d.dispatch(chunk)

    def control(self, kind: str, payload=None) -> None:
        for d in self.dispatchers:
            d.control(kind, payload)
