"""Shape-stability layer: pow2 bucket allocation + the recompile-storm
governor.

XLA compiles one program per abstract input signature, and on the
tunneled TPU one compile costs ~30-40s — so a state buffer whose
capacity wanders freely re-traces every fused program that touches it
until the device queue deadlocks (the q7 wedge, RW-E803; BENCH_TPU_2/3
"device wedged; stopping").  The fix is the fixed-capacity
region-padded state model (PAPERS.md, "Streaming Computations with
Region-Based State on SIMD Architectures"): every device-visible
shape is drawn from a small DECLARED pow2 lattice, buffers are padded
to their bucket with validity masks, and capacity transitions follow a
grow-eagerly / shrink-lazily hysteresis so steady-state churn can
never oscillate across a bucket boundary.

Three layers live here:

- :class:`BucketPolicy` / :class:`BucketAllocator` — the capacity
  planner every window-keyed executor routes its ``_maybe_grow`` /
  barrier bookkeeping through.  The allocator's ``lattice`` is exactly
  what the executor declares as ``window_buckets`` in its
  ``trace_contract()`` (analysis/shape_domain.py), so the fusion
  analyzer's static proof and the runtime's actual shape set are the
  same object: total traces <= lattice size, one per bucket, never one
  per shape.
- emission bucketing helpers (:func:`emission_bucket`) — host-diff
  executors (dynamic filter rv flips, plain/retractable TopN) used to
  emit ``max(2, n)``-sized chunks, minting a fresh downstream program
  per distinct delta count; padding the emission to a pow2 bucket with
  masked lanes closes that set too.
- :class:`ShapeGovernor` — the runtime back-stop for when stability is
  violated anyway: per-barrier ``SignatureWatch`` hazard deltas feed a
  budget (``RW_FUSION_RECOMPILE_BUDGET``); exceeding it pins the
  offending executor to its max (high-water) bucket — shrink disabled,
  capacity immediately restored to the largest bucket it ever used —
  with a ``shape_governor`` event + metric, instead of letting the
  re-trace storm pile onto the device.  A SLOW device heartbeat
  (blackbox.DeviceSentinel) drops the budget to zero: the first
  hazard on a struggling tunnel throttles proactively, before WEDGED.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

__all__ = [
    "BucketAllocator",
    "BucketPolicy",
    "ShapeGovernor",
    "emission_bucket",
    "flush_pad",
    "flush_pad_schedule",
    "lattice_between",
    "needs_plan",
    "padding_fraction",
    "padding_stats",
    "plan_capacity",
    "pow2_at_least",
    "validate_lattice",
]

# lattice span above the configured capacity: initial_cap << STEPS is
# the largest bucket growth may reach before the existing overflow
# latches ("grow capacity") fire. 8 doublings = 256x headroom, and a
# <= 9-entry lattice bounds worst-case traces per kernel.
DEFAULT_MAX_STEPS = 8
# a declared lattice may never exceed this capacity (2^26 slots of one
# int64 lane = 512 MiB: past any sane single-buffer HBM budget)
ABS_MAX_CAP = 1 << 26


def pow2_at_least(n: int) -> int:
    """Smallest power of two >= max(n, 1)."""
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


def lattice_between(lo: int, hi: int) -> Tuple[int, ...]:
    """All pow2 capacities in [lo, hi] (lo/hi rounded up to pow2)."""
    lo = pow2_at_least(lo)
    hi = max(pow2_at_least(hi), lo)
    out = []
    c = lo
    while c <= hi:
        out.append(c)
        c <<= 1
    return tuple(out)


def emission_bucket(n: int, floor: int = 2) -> int:
    """Pow2 emission capacity for an n-row host-built delta chunk.
    Downstream programs then see at most log2(max_delta) distinct
    shapes instead of one per distinct count."""
    return pow2_at_least(max(int(n), floor))


def flush_pad(out_cap: int, emitted_bound: int) -> int:
    """The agg-flush emission lattice: one delta chunk's capacity,
    quantized to exactly TWO buckets (small | full) from a bound on
    its emitted rows. Every consumer of a flush lane — the interpreted
    exact slicer (hash_agg._delta_to_chunk), the fused single-input
    program and the fused two-input join programs — draws pads from
    THIS function, so the flush-lane shape family is one closed
    {small, full} pair per out_cap and the downstream compile set
    cannot drift apart between paths."""
    full = 2 * int(out_cap)
    small = min(256, full)
    return small if 2 * int(emitted_bound) <= small else full


def flush_pad_schedule(
    dirty_bound: int, capacity: int, out_cap: int
) -> Tuple[int, ...]:
    """Per-round flush pads for one barrier, from the HOST dirty bound
    (zero device reads): round r drains up to ``out_cap`` dirty
    groups, so its emitted-rows bound is what remains of the clamped
    dirty bound. Always at least one round (a trailing over-estimate
    emits an all-invalid chunk — masked lanes, a no-op downstream)."""
    out_cap = int(out_cap)
    bound = min(int(dirty_bound), int(capacity))
    rounds = max(1, -(-bound // out_cap))
    return tuple(
        flush_pad(out_cap, min(max(bound - r * out_cap, 0), out_cap))
        for r in range(rounds)
    )


def validate_lattice(buckets) -> Optional[str]:
    """Why the bucketing layer cannot satisfy a declared
    ``window_buckets`` lattice, or None when it can (RW-E806's
    predicate). Satisfiable = non-empty, all power-of-two ints,
    strictly increasing, and within the absolute allocator bound."""
    try:
        caps = tuple(int(b) for b in buckets)
    except (TypeError, ValueError):
        return f"lattice is not a capacity sequence: {buckets!r}"
    if not caps:
        return "lattice is empty"
    for b in caps:
        if b <= 0 or b & (b - 1):
            return f"capacity {b} is not a power of two"
        if b > ABS_MAX_CAP:
            return (
                f"capacity {b} exceeds the allocator bound {ABS_MAX_CAP}"
            )
    if any(b >= c for b, c in zip(caps, caps[1:])):
        return f"lattice is not strictly increasing: {caps}"
    return None


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass(frozen=True)
class BucketPolicy:
    """Hysteresis parameters of one buffer's bucket walk.

    ``grow_at`` is the load factor that triggers eager growth (shared
    with the hash tables' rehash contract); shrink is LAZY: occupancy
    must sit below ``shrink_at * capacity`` for ``patience``
    consecutive barriers before the buffer compacts down — a window
    churning right at a bucket boundary therefore grows once and stays,
    it can never flap."""

    min_cap: int
    max_cap: int
    grow_at: float = 0.5
    shrink_at: float = 0.125
    patience: int = 4

    def __post_init__(self):
        if self.min_cap & (self.min_cap - 1) or self.min_cap <= 0:
            raise ValueError(f"min_cap {self.min_cap} not a power of two")
        if self.max_cap < self.min_cap:
            raise ValueError("max_cap < min_cap")
        if not (0.0 < self.shrink_at < self.grow_at <= 1.0):
            raise ValueError(
                "need 0 < shrink_at < grow_at <= 1 for hysteresis"
            )

    @staticmethod
    def from_capacity(
        capacity: int,
        max_steps: Optional[int] = None,
        grow_at: float = 0.5,
    ) -> "BucketPolicy":
        """The default policy for an executor configured with
        ``capacity``: lattice spans capacity .. capacity << steps
        (``RW_BUCKET_MAX_STEPS`` overrides; shrink floor = the
        configured capacity, honoring the operator's sizing)."""
        steps = (
            max_steps
            if max_steps is not None
            else _env_int("RW_BUCKET_MAX_STEPS", DEFAULT_MAX_STEPS)
        )
        # a configured capacity beyond the allocator bound clamps the
        # LATTICE (never raises: the capacity was legal before this
        # layer existed) — plan() tolerates cap > max_cap, so the
        # buffer simply never grows, and the declared lattice stays
        # satisfiable (no self-inflicted RW-E806)
        lo = min(pow2_at_least(capacity), ABS_MAX_CAP)
        hi = min(lo << max(steps, 0), ABS_MAX_CAP)
        return BucketPolicy(
            min_cap=lo,
            max_cap=max(hi, lo),
            grow_at=grow_at,
            patience=_env_int("RW_BUCKET_SHRINK_PATIENCE", 4),
        )

    def lattice(self) -> Tuple[int, ...]:
        return lattice_between(self.min_cap, self.max_cap)


class BucketAllocator:
    """Capacity planner for one (or one family of) padded state
    buffer(s). The owning executor calls:

    - ``should_plan(cap, bound, incoming)`` — the cheap pre-check its
      ``_maybe_grow`` already does, extended with pending-shrink and
      governor-pin wakeups;
    - ``plan(cap, incoming, claimed, survivors)`` — the
      ``plan_rehash`` replacement: next capacity drawn from the
      lattice (grow eagerly, clamped at ``max_cap``; pinned buffers
      jump back to their high-water bucket), or None;
    - ``note_barrier(cap, claimed)`` — per-barrier occupancy
      bookkeeping driving the lazy-shrink streak;
    - ``pin()`` — the governor hook: shrink disabled, next plan()
      returns the high-water bucket.
    """

    def __init__(self, policy: BucketPolicy):
        self.policy = policy
        self.pinned = False
        self.high_water = policy.min_cap
        self._streak = 0
        self._pending_shrink: Optional[int] = None
        # saturated = demand exceeds the lattice max and a same-cap
        # rebuild cannot relieve it; gates the load-factor trigger so
        # the apply path stops paying a device read + rebuild per
        # chunk (re-checked once per barrier via note_barrier)
        self._saturated = False
        # memory-governor veto surface (runtime/memory_governor.py):
        # when set, grow_gate(cap, new_cap) must approve every grow
        # plan() would return. A refusal latches _veto_hold so the
        # apply path stops re-asking per chunk (same per-chunk-storm
        # reasoning as _saturated); note_barrier re-probes. The veto
        # MUST fire before plan() touches hysteresis state: a vetoed
        # grow that later succeeds applies its _pending_shrink/_streak
        # resets exactly once, at the grow that actually happens —
        # the PR 13 K-stale-pack double-tick class of bug otherwise.
        self.grow_gate = None
        self._veto_hold = False
        self.vetoes = 0

    @property
    def lattice(self) -> Tuple[int, ...]:
        return self.policy.lattice()

    # -- apply-path hooks -------------------------------------------------
    def should_plan(self, cap: int, bound: int, incoming: int) -> bool:
        if (
            not self._saturated
            and not self._veto_hold
            and bound + incoming > cap * self.policy.grow_at
        ):
            return True
        if self.pinned and cap < self.high_water:
            return True
        return (
            self._pending_shrink is not None
            and self._pending_shrink < cap
        )

    def plan(
        self,
        cap: int,
        incoming: int,
        claimed: int,
        survivors: int,
        margin: int = 0,
    ) -> Optional[int]:
        """Next capacity, or None (current bucket still fits). A
        returned value == cap is a pure tombstone compaction (the
        plan_rehash contract). Growth beyond ``max_cap`` clamps: the
        executor's existing overflow latch ("grow capacity") then
        reports genuine overflow at the barrier instead of the device
        re-tracing through unbounded fresh shapes.

        ``margin`` is extra headroom folded into the NEED sizing only
        (never the trigger): executors planning from note-based
        occupancy estimates pass their per-epoch incoming here so
        growth converges in one rebuild instead of re-tripping at the
        next bucket's boundary once the true note lands."""
        p = self.policy
        self.high_water = max(self.high_water, cap)
        if self.pinned and cap < self.high_water:
            # governor pin: jump straight back to the high-water bucket
            self._pending_shrink = None
            return self.high_water
        if claimed + incoming > cap * p.grow_at:
            need = cap
            while survivors + incoming + margin > need * p.grow_at:
                need <<= 1
            new_cap = min(max(need, p.min_cap), max(p.max_cap, cap))
            if new_cap > cap and self.grow_gate is not None:
                # governor veto gates GENUINE growth only (a same-cap
                # tombstone compaction frees memory — always allowed)
                try:
                    allowed = bool(self.grow_gate(cap, new_cap))
                except Exception:  # noqa: BLE001 — a broken gate never wedges
                    allowed = True
                if not allowed:
                    # deferred, not denied: hysteresis state untouched —
                    # the resets below belong to the grow that actually
                    # runs, so a veto/release cycle ticks them once
                    self._veto_hold = True
                    self.vetoes += 1
                    return None
            self._pending_shrink = None
            self._streak = 0
            if new_cap == cap and survivors + incoming > cap * p.grow_at:
                # saturated at the lattice max: a same-capacity rebuild
                # cannot relieve the load (unlike a genuine tombstone
                # compaction, where survivors fit) — stop planning per
                # chunk and let the overflow latch report if the table
                # genuinely fills. note_barrier re-checks each barrier.
                self._saturated = True
                return None
            self.high_water = max(self.high_water, new_cap)
            return new_cap
        t = self._pending_shrink
        if t is not None and not self.pinned:
            self._pending_shrink = None
            self._streak = 0
            # never shrink below what this chunk (or the survivors)
            # need — re-growing next chunk would be the exact
            # oscillation this layer exists to prevent
            while survivors + incoming + margin > t * p.grow_at:
                t <<= 1
            if t < cap:
                return t
        return None

    def bump(self, cap: int) -> Optional[int]:
        """ONE-bucket emergency growth for a mid-epoch overflow guard.

        The guard's host insert bound counts padded chunk CAPACITIES,
        not true inserts — letting ``plan()`` size from it over-grows
        by several buckets and re-compiles every program touching the
        buffer (measured +68%% wall on the join-heavy CPU suites).
        The guard only needs to stay ahead of MAX_PROBE until the next
        barrier's true-note planning, so it doubles once (clamped at
        the lattice max; a genuine faster-than-2x single-epoch blow-up
        still trips the executor's overflow latch, the pre-existing
        contract). Shrink state resets like any growth."""
        p = self.policy
        if cap >= p.max_cap:
            return None
        new_cap = min(cap << 1, p.max_cap)
        self.high_water = max(self.high_water, new_cap)
        self._pending_shrink = None
        self._streak = 0
        return new_cap

    # -- barrier hook -----------------------------------------------------
    def note_barrier(self, cap: int, claimed: int) -> None:
        p = self.policy
        self.high_water = max(self.high_water, cap)
        # saturation and the governor-veto hold are re-evaluated once
        # per barrier (expiry/spill may have freed load), never per chunk
        self._saturated = False
        self._veto_hold = False
        if (
            self.pinned
            or cap <= p.min_cap
            or claimed > cap * p.shrink_at
        ):
            self._streak = 0
            self._pending_shrink = None
            return
        self._streak += 1
        if self._streak >= p.patience:
            target = pow2_at_least(
                max(p.min_cap, int(claimed / p.grow_at) + 1)
            )
            if target < cap:
                self._pending_shrink = target

    # -- governor hook ----------------------------------------------------
    def pin(self) -> int:
        """Disable shrink and freeze the buffer at its high-water
        bucket (applied by the next plan()). Returns the pinned
        capacity."""
        self.pinned = True
        self._pending_shrink = None
        self._streak = 0
        return self.high_water

    def snapshot(self) -> Dict:
        return {
            "lattice": list(self.lattice),
            "pinned": self.pinned,
            "high_water": self.high_water,
            "pending_shrink": self._pending_shrink,
            "saturated": self._saturated,
            "veto_hold": self._veto_hold,
            "vetoes": self.vetoes,
        }


def needs_plan(
    alloc: Optional[BucketAllocator],
    cap: int,
    bound: int,
    incoming: int,
    grow_at: float = 0.5,
) -> bool:
    """The apply-path pre-check shared by every ``_maybe_grow``:
    allocator-driven when bucketed, the legacy load-factor check on
    the unbucketed twin (alloc=None)."""
    if alloc is None:
        return bound + incoming > cap * grow_at
    return alloc.should_plan(cap, bound, incoming)


def plan_capacity(
    alloc: Optional[BucketAllocator],
    cap: int,
    incoming: int,
    claimed: int,
    survivors: int,
    grow_at: float = 0.5,
) -> Optional[int]:
    """``plan_rehash`` with the bucket lattice in the loop; falls back
    to the raw unbounded rehash policy on the unbucketed twin."""
    if alloc is None:
        from risingwave_tpu.ops.hash_table import plan_rehash

        return plan_rehash(cap, incoming, claimed, survivors, grow_at)
    return alloc.plan(cap, incoming, claimed, survivors)


def padding_fraction(entries) -> float:
    """Weighted wasted-lane fraction over ``(capacity, live,
    weight_bytes)`` triples — the ZERO-device-read twin of
    :func:`padding_stats`, fed from occupancy scalars that already
    rode a packed barrier read (the fused telemetry lane). Weighting
    by state bytes makes the fraction a traffic model: a padded lane
    of a wide table wastes more HBM bandwidth than one of a narrow
    table. Empty/degenerate input -> 0.0 (nothing padded = nothing
    wasted, the padding_stats convention)."""
    num = den = 0.0
    for cap, live, weight in entries:
        cap, weight = int(cap), float(weight)
        if cap <= 0 or weight <= 0.0:
            continue
        num += weight * (1.0 - min(int(live), cap) / cap)
        den += weight
    return round(num / den, 6) if den else 0.0


def padding_stats(executors) -> Dict[str, object]:
    """Wasted-lane accounting over every padded state buffer the given
    executors expose via ``padding_stats()`` (bench/PROFILE surface —
    this READS device occupancy counters; never call it per barrier).
    Returns totals + the worst per-executor fraction."""
    total_lanes = 0
    live_lanes = 0
    per: Dict[str, Dict] = {}
    for ex in executors:
        fn = getattr(ex, "padding_stats", None)
        if fn is None:
            continue
        try:
            st = fn()
        except Exception:  # noqa: BLE001 — accounting must never fault
            continue
        cap, live = int(st.get("capacity", 0)), int(st.get("live", 0))
        if cap <= 0:
            continue
        total_lanes += cap
        live_lanes += live
        name = type(ex).__name__
        agg = per.setdefault(name, {"capacity": 0, "live": 0})
        agg["capacity"] += cap
        agg["live"] += live
    for st in per.values():
        st["wasted_frac"] = round(
            1.0 - st["live"] / max(st["capacity"], 1), 4
        )
    return {
        "capacity_lanes": total_lanes,
        "live_lanes": live_lanes,
        # no padded buffers = nothing wasted (not 100% wasted)
        "wasted_lane_frac": (
            round(1.0 - live_lanes / total_lanes, 4) if total_lanes else 0.0
        ),
        "per_executor": per,
    }


# ---------------------------------------------------------------------------
# recompile-storm governor
# ---------------------------------------------------------------------------


class ShapeGovernor:
    """Degrade gracefully instead of wedging when shape stability is
    violated at runtime anyway (a workload the static lattice proof
    did not anticipate, an unbucketed third-party executor, ...).

    Fed per barrier from :data:`analysis.jax_sanitizer.SIGNATURES`
    hazard deltas (one hazard = one post-warmup novel abstract input
    signature = one future re-trace). Cumulative hazards per executor
    CLASS above ``RW_FUSION_RECOMPILE_BUDGET`` pin every instance of
    that class to its max bucket via ``pin_max_bucket()``; while the
    device sentinel reports SLOW the budget is zero (first hazard
    throttles — proactive, before the heartbeat goes WEDGED). Each
    action lands in the meta event log (``shape_governor``) and in
    ``shape_governor_actions_total{executor,action,reason}``."""

    def __init__(
        self,
        budget: Optional[int] = None,
        enabled: Optional[bool] = None,
    ):
        if enabled is None:
            enabled = os.environ.get(
                "RW_SHAPE_GOVERNOR", "1"
            ).strip().lower() not in ("0", "off", "false")
        self.enabled = enabled
        self._budget = budget
        self.hazards: Dict[str, int] = {}
        self.pinned: Dict[str, Dict] = {}

    @property
    def budget(self) -> int:
        if self._budget is not None:
            return self._budget
        from risingwave_tpu.analysis.shape_domain import recompile_budget

        return recompile_budget()

    # -- the per-barrier hook --------------------------------------------
    def observe_barrier(self, target) -> List[str]:
        """Consume this barrier's hazard deltas and act. ``target`` is
        a runtime (``.executors()``) or a plain executor list. Costs
        one attribute check per barrier while SignatureWatch is
        disarmed. Returns the executor class names pinned this call."""
        if not self.enabled:
            return []
        from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES

        if not SIGNATURES.enabled:
            return []
        deltas = SIGNATURES.take_hazard_deltas()
        if not deltas:
            return []
        slow = self._device_slow()
        budget = 0 if slow else self.budget
        acted = []
        for name, n in deltas.items():
            total = self.hazards.get(name, 0) + n
            self.hazards[name] = total
            if name in self.pinned:
                continue
            if total > budget:
                self._pin(
                    target,
                    name,
                    total,
                    "slow_device" if slow else "budget_exceeded",
                )
                acted.append(name)
        return acted

    @staticmethod
    def _device_slow() -> bool:
        try:
            from risingwave_tpu import blackbox

            return blackbox.SENTINEL.state == blackbox.SLOW
        except Exception:  # noqa: BLE001 — the governor never faults
            return False

    def _pin(self, target, name: str, hazards: int, reason: str) -> None:
        from risingwave_tpu.event_log import EVENT_LOG
        from risingwave_tpu.metrics import REGISTRY

        executors = (
            target.executors() if hasattr(target, "executors") else target
        )
        pins: List[Dict] = []
        for ex in executors or ():
            if type(ex).__name__ != name:
                continue
            fn = getattr(ex, "pin_max_bucket", None)
            if fn is None:
                continue
            try:
                pins.append(fn())
            except Exception:  # noqa: BLE001 — throttling is best-effort
                continue
        action = "pin_max_bucket" if pins else "no_pin_surface"
        self.pinned[name] = {
            "hazards": hazards,
            "reason": reason,
            "action": action,
            "pins": pins,
        }
        REGISTRY.counter("shape_governor_actions_total").inc(
            executor=name, action=action, reason=reason
        )
        REGISTRY.gauge("shape_governor_pinned").set(float(len(self.pinned)))
        EVENT_LOG.record(
            "shape_governor",
            executor=name,
            action=action,
            reason=reason,
            hazards=hazards,
            budget=self.budget,
        )

    def snapshot(self) -> Dict:
        return {
            "enabled": self.enabled,
            "budget": self.budget,
            "hazards": dict(self.hazards),
            "pinned": {
                k: {kk: vv for kk, vv in v.items() if kk != "pins"}
                for k, v in self.pinned.items()
            },
        }

    def reset(self) -> None:
        self.hazards.clear()
        self.pinned.clear()
