"""Shared arrangements — one refcounted device index serving N MVs.

Reference: *Shared Arrangements* (PAPERS.md, arxiv 1812.02639) — in
timely/differential, operators PUBLISH their maintained indexes and
later queries ATTACH to the published arrangement instead of building
a private twin; the arrangement is refcounted and torn down when the
last reader departs. RisingWave realizes the same idea through
`CREATE INDEX` + delta joins (shared `IndexArrangement`s) but every
`CREATE MATERIALIZED VIEW` still builds private state.

TPU re-design: device state is the scarce resource (HBM) and — post
PR 10 — every private MV also means a private compiled program. This
module closes both gaps at the DDL boundary:

- at CREATE-MV time the session computes a **share-key fingerprint**
  over the statement's structural identity (normalized SELECT AST,
  input relation schemas + watermark specs, capacity / exec-mode /
  parallelism knobs, the bucket-lattice environment). A registry HIT
  attaches the new MV name to the existing refcounted arrangement:
  zero new executors, zero new HBM, zero new compiles — the 1000-MV
  registration storm costs O(distinct shapes), not O(MVs).
- one **writer** (the first MV's pipeline) owns all updates;
  **subscribers** read a per-barrier *published version*: an immutable
  snapshot pointer swapped at the barrier boundary, so a reader can
  never observe a mid-barrier torn state (the concurrent-stateful-
  streaming serving contract, arxiv 1904.03800). Readers that arrive
  mid-epoch get the last published version or a lock-held interim
  snapshot — consistent either way.
- refcounts drop on DROP MV; the arrangement frees (device state,
  fragment, actors) only at zero. Dropping the OWNER while
  subscribers live hands the fragment off to an internal name — the
  writer keeps streaming for its remaining readers.

Publish discipline (the <1%-of-barrier overhead contract): publishing
is a pointer swap; the snapshot itself materializes EAGERLY at the
barrier only while readers are active (`read_demand`), and LAZILY
under the runtime lock when the state provably still sits at the
barrier boundary (`write_gen` unchanged). With no readers the
steady-barrier cost is one attribute check per arrangement.

Checkpoint/restore need no new machinery: only the writer's executors
exist, so a shared arrangement stages ONCE (owner-tagged by its
table_ids) and a restore replaying the DDL log re-attaches every
subscriber to the same arrangement. Partial recovery's blast radius
for the owner fragment covers all subscribers by construction — they
have no fragments of their own, and `on_recovery` re-publishes off
the restored state so no reader serves rolled-back snapshots.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from risingwave_tpu.event_log import EVENT_LOG
from risingwave_tpu.metrics import REGISTRY

__all__ = [
    "Arrangement",
    "ArrangementRegistry",
    "DetachResult",
    "SharedArrangementReader",
    "plan_share_fingerprint",
    "shared_enabled",
]


def shared_enabled() -> bool:
    """RW_SHARED_ARRANGEMENTS=0 is the kill switch: every CREATE MV
    then builds private state (the pre-PR-12 behavior)."""
    return os.environ.get(
        "RW_SHARED_ARRANGEMENTS", "1"
    ).strip().lower() not in ("0", "off", "false")


# the bucket-lattice environment is part of the share key: two plans
# whose window-keyed state would bucket differently must NOT share one
# device index (the lattice IS the compiled shape family — PR 9)
_LATTICE_ENV = (
    "RW_BUCKET_MAX_STEPS",
    "RW_BUCKET_SHRINK_AT",
    "RW_BUCKET_SHRINK_PATIENCE",
)


def _lattice_env_sig() -> Tuple:
    return tuple((k, os.environ.get(k, "")) for k in _LATTICE_ENV)


def _referenced_relations(node, out: set) -> None:
    """Every relation name a SELECT reads (TableRef / WindowTVF /
    joins / subqueries — the parser AST is frozen dataclasses, so a
    generic field walk covers future node kinds too)."""
    import dataclasses as _dc

    from risingwave_tpu.sql import parser as P

    if isinstance(node, P.TableRef):
        out.add(node.name)
        return
    if _dc.is_dataclass(node) and not isinstance(node, type):
        for f in _dc.fields(node):
            _referenced_relations(getattr(node, f.name), out)
        return
    if isinstance(node, (tuple, list)):
        for v in node:
            _referenced_relations(v, out)


def plan_share_fingerprint(
    stmt,
    catalog,
    *,
    capacity: int,
    exec_mode: str,
    parallelism: int,
    session_token: int = 0,
) -> Optional[Tuple]:
    """The share key of one CREATE MATERIALIZED VIEW: structurally
    identical statements over identical input schemas produce EQUAL
    fingerprints (the parser AST is frozen dataclasses — value
    hashing is exact, including literal values: sharing requires
    identical results, not merely identical shapes).

    Conservative by design: a None means "do not share" (unknown
    relations, UNION ALL's separate execution path). ``session_token``
    scopes string-literal code assignment — two sessions' dictionaries
    may encode the same literal differently, so sharing never crosses
    a dictionary boundary."""
    from risingwave_tpu.sql import parser as P

    select = getattr(stmt, "select", stmt)
    if isinstance(select, P.UnionAll):
        return None
    rels: set = set()
    _referenced_relations(getattr(select, "from_", None), rels)
    _referenced_relations(getattr(select, "where", None), rels)
    _referenced_relations(tuple(getattr(select, "items", ())), rels)
    if not rels:
        return None
    schemas = []
    for r in sorted(rels):
        sch = catalog.tables.get(r)
        if sch is None:
            return None  # unknown relation: the normal path will raise
        schemas.append(
            (
                r,
                tuple(
                    (f.name, f.dtype.name, getattr(f, "scale", None))
                    for f in sch.fields
                ),
                catalog.watermarks.get(r),
                bool(catalog.is_mv(r)),
            )
        )
    try:
        return (
            "arr-v1",
            select,
            bool(getattr(stmt, "emit_on_window_close", False)),
            tuple(schemas),
            capacity,
            exec_mode,
            parallelism,
            bool(getattr(catalog, "enable_delta_join", False)),
            _lattice_env_sig(),
            session_token,
        )
    except TypeError:  # an unhashable AST corner: never share it
        return None


class _Version:
    """One published snapshot: immutable once materialized. ``cols``
    is None until someone needs it (lazy) or readers were active at
    publish time (eager); ``write_gen`` records the runtime's write
    counter at the barrier so a lazy materialization can PROVE the
    live state still sits exactly at this barrier boundary."""

    __slots__ = ("epoch", "cols", "write_gen")

    def __init__(self, epoch: Optional[int], cols, write_gen: int):
        self.epoch = epoch
        self.cols = cols
        self.write_gen = write_gen


class Arrangement:
    """One refcounted, barrier-versioned shared device arrangement."""

    def __init__(
        self,
        arr_id: int,
        fingerprint: Tuple,
        planned,
        schema,
        owner: str,
    ):
        self.id = arr_id
        self.fingerprint = fingerprint
        self.planned = planned  # the writer's PlannedMV (pipeline+mview)
        self.schema = schema  # catalog Schema of the MV's output
        self.owner = owner  # original owner MV name (provenance)
        # current runtime fragment names backing this arrangement
        # (owner fragment first, then lowered-join aux fragments);
        # renamed in place on an owner-drop handoff
        self.fragments: List[str] = [owner] + [
            sub.name for sub in getattr(planned, "aux", ())
        ]
        self.refs: set = {owner}
        self.version: Optional[_Version] = None
        self.stable: Optional[_Version] = None  # last MATERIALIZED one
        self.read_demand = False
        # reads since the last publish (fast-path included): while
        # readers are ACTIVE the publish materializes eagerly inside
        # the barrier, so steady serving never touches the runtime
        # lock — without this the demand flag would oscillate (only
        # lock-fallback reads set it) and every other barrier would
        # push readers back onto the lock
        self._reads_since_publish = 0
        self.hidden = False  # owner dropped, writer runs under alias

    @property
    def mview(self):
        return self.planned.mview

    @property
    def fragment(self) -> str:
        """The writer fragment's CURRENT runtime name."""
        return self.fragments[0]

    # -- publish / read ---------------------------------------------------
    def _snapshot_cols(self) -> Dict[str, np.ndarray]:
        return dict(self.mview.to_numpy())

    def publish(self, epoch: int, write_gen: int) -> None:
        """Swap in this barrier's version (caller holds the runtime
        lock via the barrier). Materializes only while readers are
        active — otherwise a pointer swap."""
        demand = self.read_demand or self._reads_since_publish > 0
        self._reads_since_publish = 0
        if demand:
            s = self.stable
            if s is not None and s.write_gen == write_gen:
                # nothing entered the runtime since the last snapshot:
                # republish the same (immutable) cols at the new epoch
                v = _Version(epoch, s.cols, write_gen)
                self.stable = v
                self.read_demand = False
                self.version = v
                return
            t0 = time.perf_counter()
            v = _Version(epoch, self._snapshot_cols(), write_gen)
            self.stable = v
            self.read_demand = False
            REGISTRY.histogram("arrangement_publish_ms").observe(
                (time.perf_counter() - t0) * 1e3, fragment=self.fragment
            )
        else:
            v = _Version(epoch, None, write_gen)
        self.version = v

    def read(self, runtime) -> Tuple[Optional[int], Dict[str, np.ndarray]]:
        """A snapshot-consistent read: never torn, labeled with the
        barrier epoch it corresponds to (None for a lock-held interim
        snapshot before the first barrier-aligned one exists)."""
        REGISTRY.counter("arrangement_shared_reads_total").inc()
        self._reads_since_publish += 1
        v = self.version
        if v is not None and v.cols is not None:
            return v.epoch, v.cols  # lock-free steady path
        with runtime.lock:
            v = self.version
            if v is not None and v.cols is not None:
                return v.epoch, v.cols
            self.read_demand = True  # the next publish materializes
            if v is not None and v.write_gen == runtime._write_gen:
                # nothing entered the runtime since the barrier: the
                # live state IS the published version — materialize it
                v.cols = self._snapshot_cols()
                self.stable = v
                return v.epoch, v.cols
            s = self.stable
            if s is not None:
                return s.epoch, s.cols
            # cold start under mid-epoch writes: a lock-held interim
            # snapshot (atomic, not barrier-aligned — epoch=None; not
            # cached as stable so barrier-aligned reads stay exact)
            return None, self._snapshot_cols()


class SharedArrangementReader:
    """The batch-engine facade bound to one subscriber MV name: every
    ``to_numpy()`` is a published-version read (lock-free once the
    version materialized), so `query()` never holds the runtime lock
    across the scan and never sees a torn mid-barrier state."""

    def __init__(self, registry: "ArrangementRegistry", name: str):
        self._registry = registry
        self._name = name

    @property
    def _arr(self) -> Arrangement:
        arr = self._registry._by_name.get(self._name)
        if arr is None:
            raise KeyError(
                f"shared arrangement for {self._name!r} is gone (dropped)"
            )
        return arr

    @property
    def pk(self):
        return self._arr.mview.pk

    @property
    def columns(self):
        return self._arr.mview.columns

    def read_versioned(self):
        """(epoch, cols) — the serving tier's labeled read."""
        return self._arr.read(self._registry.runtime)

    def to_numpy(self) -> Dict[str, np.ndarray]:
        _, cols = self.read_versioned()
        return dict(cols)

    def snapshot(self):
        """pk tuple -> value tuple, decoded off the published version
        (the host-map executors' interface, for backfill/probes)."""
        arr = self._arr
        cols = self.to_numpy()
        pk = tuple(arr.mview.pk)
        value_cols = tuple(arr.mview.columns)
        n = len(next(iter(cols.values()))) if cols else 0
        out = {}
        for i in range(n):
            k = tuple(np.asarray(cols[c])[i].item() for c in pk)
            v = tuple(
                None
                if f"{c}__null" in cols and bool(cols[f"{c}__null"][i])
                else np.asarray(cols[c])[i].item()
                for c in value_cols
            )
            out[k] = v
        return out


class DetachResult:
    """What a DROP of ``name`` means for its arrangement (the session
    finishes the catalog/runtime side per kind):

    - ``none``         not arrangement-tracked: normal drop path
    - ``owner_free``   owner dropped, no subscribers: normal drop path
                       (the arrangement record is already gone)
    - ``handoff``      owner dropped, subscribers live: the writer
                       fragment was renamed (``renames``) and keeps
                       running — do NOT unregister it
    - ``subscriber``   a subscriber dropped, others (or the owner)
                       remain: catalog cleanup only
    - ``subscriber_free`` the LAST reference dropped and it was a
                       subscriber: tear the hidden writer down
                       (``arrangement.fragments`` names)
    """

    __slots__ = ("kind", "arrangement", "renames")

    def __init__(self, kind: str, arrangement=None, renames=()):
        self.kind = kind
        self.arrangement = arrangement
        self.renames = tuple(renames)


class ArrangementRegistry:
    """Per-runtime registry: fingerprint -> arrangement, plus the MV
    name -> arrangement index for reads/drops. All mutation happens
    under the runtime lock (DDL path); ``publish`` runs inside the
    barrier; reads synchronize only through the version pointer."""

    def __init__(self, runtime):
        import weakref

        self._runtime_ref = weakref.ref(runtime)
        self._by_fp: Dict[Tuple, Arrangement] = {}
        self._by_name: Dict[str, Arrangement] = {}
        self._facades: Dict[str, SharedArrangementReader] = {}
        self._live: List[Arrangement] = []
        self._next_id = 0
        self._lock = threading.RLock()
        self.attaches = 0
        self.frees = 0
        # overload-ladder SHEDDING hook (runtime/memory_governor.py):
        # while set, publish is pointer-swap-only — eager in-barrier
        # materialization pauses, readers fall back to the lock path
        # (lazy per-demand snapshots / the last stable version: a
        # lagged-but-consistent view) and demand re-latches once the
        # ladder recovers below SHEDDING
        self.shed_eager = False

    @property
    def runtime(self):
        rt = self._runtime_ref()
        if rt is None:
            raise RuntimeError("runtime is gone")
        return rt

    @property
    def enabled(self) -> bool:
        return shared_enabled()

    # -- registration -----------------------------------------------------
    def lookup(self, fingerprint: Tuple) -> Optional[Arrangement]:
        arr = self._by_fp.get(fingerprint)
        if arr is None:
            return None
        # sanity: the writer fragment must still be live in the runtime
        if arr.fragment not in self.runtime.fragments:
            return None
        return arr

    def adopt(self, fingerprint: Tuple, planned, schema) -> Arrangement:
        """Record a freshly-registered MV as the owner of a (so far
        unshared) arrangement — the share target for later identical
        CREATEs."""
        with self._lock:
            stale = self._by_fp.get(fingerprint)
            if stale is not None:
                # a prior owner vanished without a session-level DROP
                # (direct runtime surgery): its record must not shadow
                # the new live arrangement
                self._forget(stale)
            self._next_id += 1
            arr = Arrangement(
                self._next_id, fingerprint, planned, schema, planned.name
            )
            self._by_fp[fingerprint] = arr
            self._by_name[planned.name] = arr
            self._live.append(arr)
            self._gauges()
            return arr

    def attach(self, arr: Arrangement, name: str) -> SharedArrangementReader:
        """Refcount++ and bind ``name`` to the arrangement's published
        versions. O(1): no executors, no state, no compiles."""
        with self._lock:
            arr.refs.add(name)
            self._by_name[name] = arr
            facade = SharedArrangementReader(self, name)
            self._facades[name] = facade
            arr.read_demand = True  # first publish must be readable
            self.attaches += 1
            REGISTRY.counter("arrangement_attaches_total").inc()
            self._gauges()
        EVENT_LOG.record(
            "arrangement_attach",
            name=name,
            owner=arr.owner,
            fragment=arr.fragment,
            refs=len(arr.refs),
        )
        return facade

    def reader(self, name: str) -> Optional[SharedArrangementReader]:
        return self._facades.get(name)

    def serves(self, name: str) -> bool:
        """True when ``name`` reads through a published-version facade
        (subscribers; owners keep their live locked read path)."""
        return name in self._facades

    def fragment_for(self, name: str) -> Optional[str]:
        """The runtime fragment actually backing an attached MV name
        (MV-on-shared-MV subscriptions route here)."""
        arr = self._by_name.get(name)
        if arr is None or name not in self._facades:
            return None
        return arr.fragment

    def refcount(self, name: str) -> int:
        arr = self._by_name.get(name)
        return len(arr.refs) if arr is not None else 0

    # -- teardown ---------------------------------------------------------
    def detach(self, name: str) -> DetachResult:
        """Refcount--; see DetachResult for what the caller must do."""
        with self._lock:
            arr = self._by_name.pop(name, None)
            if arr is None:
                return DetachResult("none")
            arr.refs.discard(name)
            was_subscriber = self._facades.pop(name, None) is not None
            if not arr.refs:
                self._forget(arr)
                return DetachResult(
                    "subscriber_free" if was_subscriber else "owner_free",
                    arrangement=arr,
                )
            if was_subscriber:
                self._gauges()
                return DetachResult("subscriber", arrangement=arr)
            # the OWNER name dropped with subscribers still attached:
            # hand the writer off to internal names so the user-visible
            # name frees up while the fragment keeps streaming
            renames = []
            rt = self.runtime
            for i, frag in enumerate(list(arr.fragments)):
                if frag not in rt.fragments:
                    continue  # already torn down out-of-band
                alias = f"__arr{arr.id}.{frag}"
                rt.rename_fragment(frag, alias)
                arr.fragments[i] = alias
                renames.append((frag, alias))
            arr.hidden = True
            self._gauges()
            EVENT_LOG.record(
                "arrangement_handoff",
                name=name,
                fragment=arr.fragment,
                refs=len(arr.refs),
            )
            return DetachResult("handoff", arrangement=arr, renames=renames)

    def _forget(self, arr: Arrangement) -> None:
        self._by_fp.pop(arr.fingerprint, None)
        if arr in self._live:
            self._live.remove(arr)
        for n in list(self._by_name):
            if self._by_name[n] is arr:
                del self._by_name[n]
        self.frees += 1
        REGISTRY.counter("arrangement_frees_total").inc()
        self._gauges()
        EVENT_LOG.record(
            "arrangement_free", owner=arr.owner, fragment=arr.fragment
        )

    def _gauges(self) -> None:
        REGISTRY.gauge("arrangements_live").set(float(len(self._live)))
        REGISTRY.gauge("arrangement_refs_total").set(
            float(sum(len(a.refs) for a in self._live))
        )

    # -- barrier / recovery hooks ----------------------------------------
    def publish(self, epoch: int) -> None:
        """Barrier-boundary version swap for every live arrangement
        (called from the runtime's trace finalization, under the
        barrier). Shared-reader overhead when nobody reads: one list
        walk of pointer swaps."""
        if not self._live:
            return
        rt = self._runtime_ref()
        if rt is None or rt.in_flight_barriers > 1:
            # pipelined barriers close in the closer lane without the
            # runtime lock — versioned serving is a serial-clock
            # feature (sessions always run in_flight=1)
            return
        gen = rt._write_gen
        if self.shed_eager:
            # SHEDDING: no in-barrier materialization — swap the
            # version pointer only. Read demand stays latched in the
            # arrangement, so the first post-shed publish materializes
            # again for its readers.
            for arr in self._live:
                arr._reads_since_publish = 0
                arr.version = _Version(epoch, None, gen)
            return
        for arr in self._live:
            arr.publish(epoch, gen)

    def on_recovery(self, epoch: int) -> None:
        """State rolled back: stale published snapshots must not serve
        (they may postdate the restored epoch). Fresh versions
        materialize off the restored state at the next read/publish."""
        rt = self._runtime_ref()
        gen = rt._write_gen if rt is not None else 0
        for arr in self._live:
            arr.stable = None
            arr.version = _Version(epoch, None, gen)
            arr.read_demand = bool(
                len(arr.refs) > 1 or arr.hidden
            )

    # -- introspection ----------------------------------------------------
    def stats(self) -> Dict:
        with self._lock:
            return {
                "arrangements": len(self._live),
                "refs": sum(len(a.refs) for a in self._live),
                "shared": sum(
                    1
                    for a in self._live
                    if len(a.refs) > 1 or a.hidden
                ),
                "attaches": self.attaches,
                "frees": self.frees,
                "by_owner": {
                    a.owner: sorted(a.refs) for a in self._live
                },
            }
