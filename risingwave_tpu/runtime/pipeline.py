"""Single-fragment pipeline: an ordered executor chain + epoch driver.

Reference roles:
- the actor's executor chain (src/stream/src/executor/mod.rs:180 — each
  executor wraps its input stream; here the host feeds messages down an
  ordered list instead);
- barrier flow-through: a barrier entering the chain flushes each
  executor in turn, and an executor's flush output is DATA for every
  executor below it (src/stream/src/task/barrier_manager.rs:634 +
  executor flush_data patterns);
- watermark propagation (executor/watermark_filter.rs): watermarks pass
  through every executor, letting stateful ones clean closed state.

The epoch counter follows the reference epoch encoding
(physical ms << 16, src/common/src/util/epoch.rs:36).
"""

from __future__ import annotations

import time
from collections import deque
from typing import List, Optional, Sequence

from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES, transfer_guard
from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.blackbox import RECORDER
from risingwave_tpu.executors.base import Barrier, Epoch, Executor, Watermark
from risingwave_tpu.profiler import PROFILER


class FreshnessSurface:
    """Host-side freshness sampling shared by every fragment shape
    (Pipeline / TwoInputPipeline / GraphPipeline): the wall time of the
    epoch's FIRST ingest, the max event-time watermark frontier seen,
    and one sample per barrier (freshness.py consumes these at
    ``runtime._end_trace``; bench.py summarizes them per query). Pure
    host timestamps and dict appends — zero device dispatches.
    """

    FRESHNESS_WINDOW = 512

    def _init_freshness(self) -> None:
        self._ingest_wall: Optional[float] = None
        self.low_watermark: Optional[int] = None
        self.freshness_samples: deque = deque(maxlen=self.FRESHNESS_WINDOW)
        self.last_freshness: Optional[dict] = None

    def _note_ingest(self) -> None:
        if self._ingest_wall is None:
            self._ingest_wall = time.time()

    def _note_watermark(self, value) -> None:
        try:
            v = int(value)
        except (TypeError, ValueError):
            return
        if self.low_watermark is None or v > self.low_watermark:
            self.low_watermark = v

    def _sample_freshness(self, barrier_ms: float) -> dict:
        now = time.time()
        ingest, self._ingest_wall = self._ingest_wall, None
        s = {
            "epoch": self._epoch,
            "ingest_wall": ingest,
            "low_watermark": self.low_watermark,
            "commit_to_visible_ms": round(barrier_ms, 3),
            "source_to_visible_ms": (
                round((now - ingest) * 1e3, 3) if ingest else None
            ),
            "event_time_lag_ms": (
                round(now * 1000.0 - self.low_watermark, 3)
                if self.low_watermark is not None
                else None
            ),
        }
        self.last_freshness = s
        self.freshness_samples.append(s)
        return s


def walk_chain(chain: Sequence[Executor], chunks, barrier=None):
    """Feed chunks (then optionally a barrier) down an executor chain;
    every executor's output — including its barrier flush — is data for
    the executors below it. The single chain-walking loop shared by
    Pipeline, TwoInputPipeline and the graph runtime's FragmentActor."""
    pending = list(chunks)
    # recompile-hazard fingerprinting (analysis/jax_sanitizer) and the
    # dispatch-wall profiler: one attribute check each when disarmed —
    # the hot path stays flat
    watch = SIGNATURES if SIGNATURES.enabled else None
    prof = PROFILER if PROFILER.enabled else None
    for ex in chain:
        nxt: List[StreamChunk] = []
        for c in pending:
            if watch is not None:
                watch.observe(ex, c)
            if prof is None:
                nxt.extend(ex.apply(c))
            else:
                nxt.extend(prof.run(ex, "apply", ex.apply, c))
        if barrier is not None:
            if prof is None:
                nxt.extend(ex.on_barrier(barrier))
            else:
                nxt.extend(prof.run(ex, "flush", ex.on_barrier, barrier))
        pending = nxt
    return pending


def _pcall(ex, phase, fn, *args):
    """Profiler-gated call for executor entry points OUTSIDE walk_chain
    (join apply_left/right, on_barrier in two-input shapes) — also the
    recompile-hazard fingerprint tap for those paths: serial AND
    graph-mode join executors feed SignatureWatch here, so two-input
    shapes get the same shape-stability coverage as chain executors."""
    if SIGNATURES.enabled and phase == "apply" and args:
        SIGNATURES.observe(ex, args[0])
    if PROFILER.enabled:
        return PROFILER.run(ex, phase, fn, *args)
    return fn(*args)


class Pipeline(FreshnessSurface):
    """An ordered chain of executors driven by the host epoch loop."""

    def __init__(self, executors: Sequence[Executor]):
        self.executors = list(executors)
        self._epoch = 0
        self._init_freshness()

    # -- message plumbing -------------------------------------------------
    def push(self, chunk: StreamChunk, start: int = 0) -> List[StreamChunk]:
        """Feed one data chunk into the chain; returns what falls out."""
        self._note_ingest()
        return walk_chain(self.executors[start:], [chunk])

    def barrier(
        self, checkpoint: bool = True, epoch: Optional[int] = None
    ) -> List[StreamChunk]:
        """Inject a barrier; each executor's flush output becomes data
        for the rest of the chain. Returns chunks exiting the chain.
        ``epoch`` pins the barrier's curr epoch (the runtime passes its
        own clock so held sink batches key by the COMMIT epoch);
        standalone pipelines derive one from the wall clock."""
        prev = self._epoch
        self._epoch = (
            epoch
            if epoch is not None
            else max(int(time.time() * 1000) << 16, prev + 1)
        )
        b = Barrier(Epoch(prev, self._epoch), checkpoint)
        t0 = time.perf_counter()
        with PROFILER.barrier_window():
            pending = walk_chain(self.executors, [], barrier=b)
            # executor-GENERATED watermarks (watermark_filter.rs) walk
            # the rest of the chain after the barrier flushes
            for i, ex in enumerate(self.executors):
                wm = ex.emit_watermark()
                if wm is not None:
                    self._note_watermark(wm.value)
                    _, outs = _walk_watermark(self.executors[i + 1 :], wm)
                    pending.extend(outs)
            t1 = time.perf_counter()
            # materialize every executor's staged barrier scalars AFTER
            # the walk: the async transfers overlapped, so the chain
            # pays ~one round-trip; raises still precede the runtime's
            # epoch commit. transfer_guard: when armed
            # (RW_TRANSFER_GUARD, tests) any IMPLICIT host<->device
            # transfer here raises at the offender
            with transfer_guard():
                for ex in self.executors:
                    ex.finish_barrier()
        # stage attribution (EpochTrace lifecycle): the walk is host
        # dispatch; the scalar materialization is the barrier-only
        # device fence
        from risingwave_tpu.epoch_trace import record_stage

        t2 = time.perf_counter()
        record_stage("dispatch", (t1 - t0) * 1e3)
        record_stage("device_step", (t2 - t1) * 1e3)
        self._sample_freshness((t2 - t0) * 1e3)
        # standalone pipelines (bench drivers, tests) feed the black
        # box directly — a runtime-driven barrier records via its
        # EpochTrace instead
        RECORDER.record_pipeline_barrier(
            self._epoch, (t1 - t0) * 1e3, (t2 - t1) * 1e3
        )
        # mesh observability: close this pipeline's per-shard window
        # (no-op unless MESHPROF is armed and watched this chain; the
        # import is deferred — meshprof pulls in the parallel package,
        # which imports the executors this module's package feeds)
        from risingwave_tpu.parallel.meshprof import MESHPROF

        if MESHPROF.enabled:
            MESHPROF.pipeline_barrier(self)
        return pending

    def watermark(self, column: str, value: int) -> List[StreamChunk]:
        """Propagate a watermark; executors may transform it (e.g. hop
        window: event time -> window_start) or consume it; their flush
        outputs flow downstream as data."""
        self._note_watermark(value)
        _, pending = _walk_watermark(self.executors, Watermark(column, value))
        return pending

    @property
    def epoch(self) -> int:
        return self._epoch


def _walk_watermark(chain: Sequence[Executor], wm: Optional[Watermark]):
    """Walk a watermark down an executor chain, feeding each executor's
    flushed output chunks through the rest of the chain as data.
    Returns (surviving watermark | None, chunks exiting the chain)."""
    pending: List[StreamChunk] = []
    for ex in chain:
        nxt: List[StreamChunk] = []
        for c in pending:
            nxt.extend(ex.apply(c))
        if wm is not None:
            wm, outs = ex.on_watermark(wm)
            nxt.extend(outs)
        pending = nxt
    return wm, pending


class TwoInputPipeline(FreshnessSurface):
    """Two upstream chains joined by a two-input executor, then a tail.

    Reference shape: a join actor's two MergeExecutor inputs aligned on
    barriers (executor/barrier_align.rs) — the host driver is the
    aligner: it feeds each side's chunks in arrival order and calls
    ``barrier`` only when both sides reached it.
    """

    def __init__(
        self,
        left: Sequence[Executor],
        right: Sequence[Executor],
        join,
        tail: Sequence[Executor],
    ):
        self.left = list(left)
        self.right = list(right)
        self.join = join
        self.tail = list(tail)
        self._epoch = 0
        self._init_freshness()
        # whole-pipeline fusion overlay (runtime/fused_step
        # fuse_two_input): when set, pushes buffer into the wrapper and
        # the barrier runs ONE donated device program — the member
        # chains above stay intact as the checkpoint/lint/watermark
        # surface (the wrapper is an execution strategy, not an owner)
        self._fused = None

    def _through(self, chain, chunks, barrier=None):
        return walk_chain(chain, chunks, barrier)

    def push_left(self, chunk: StreamChunk) -> List[StreamChunk]:
        self._note_ingest()
        if self._fused is not None:
            return self._fused.buffer_left(chunk)
        outs = []
        for c in self._through(self.left, [chunk]):
            outs.extend(_pcall(self.join, "apply", self.join.apply_left, c))
        return self._through(self.tail, outs)

    def push_right(self, chunk: StreamChunk) -> List[StreamChunk]:
        self._note_ingest()
        if self._fused is not None:
            return self._fused.buffer_right(chunk)
        outs = []
        for c in self._through(self.right, [chunk]):
            outs.extend(_pcall(self.join, "apply", self.join.apply_right, c))
        return self._through(self.tail, outs)

    def barrier(
        self, checkpoint: bool = True, epoch: Optional[int] = None
    ) -> List[StreamChunk]:
        prev = self._epoch
        self._epoch = (
            epoch
            if epoch is not None
            else max(int(time.time() * 1000) << 16, prev + 1)
        )
        b = Barrier(Epoch(prev, self._epoch), checkpoint)
        t0 = time.perf_counter()
        with PROFILER.barrier_window():
            if self._fused is not None:
                # ONE donated device program for the whole fragment
                # barrier; finish defers to the K-boundary under
                # RW_FUSED_PIPELINE_DEPTH (the wrapper decides)
                outs = _pcall(
                    self._fused, "flush", self._fused.on_barrier, b
                )
                outs.extend(self._generated_watermarks())
                t1 = time.perf_counter()
                with transfer_guard():
                    self._fused.finish_barrier()
            else:
                joined: List[StreamChunk] = []
                for c in self._through(self.left, [], barrier=b):
                    joined.extend(
                        _pcall(self.join, "apply", self.join.apply_left, c)
                    )
                for c in self._through(self.right, [], barrier=b):
                    joined.extend(
                        _pcall(self.join, "apply", self.join.apply_right, c)
                    )
                joined.extend(
                    _pcall(self.join, "flush", self.join.on_barrier, b)
                )
                outs = self._through(self.tail, joined, barrier=b)
                outs.extend(self._generated_watermarks())
                t1 = time.perf_counter()
                with transfer_guard():
                    for ex in self.executors:
                        ex.finish_barrier()
        from risingwave_tpu.epoch_trace import record_stage

        t2 = time.perf_counter()
        record_stage("dispatch", (t1 - t0) * 1e3)
        record_stage("device_step", (t2 - t1) * 1e3)
        self._sample_freshness((t2 - t0) * 1e3)
        RECORDER.record_pipeline_barrier(
            self._epoch, (t1 - t0) * 1e3, (t2 - t1) * 1e3
        )
        from risingwave_tpu.parallel.meshprof import MESHPROF

        if MESHPROF.enabled:
            MESHPROF.pipeline_barrier(self)
        return outs

    def _generated_watermarks(self) -> List[StreamChunk]:
        """Poll emit_watermark on every executor; a side-chain watermark
        walks the rest of its chain, through the join's alignment, then
        the tail (the same route a driver-injected one takes)."""
        outs: List[StreamChunk] = []
        aligned: Optional[Watermark] = None
        for chain, feed in (
            (self.left, self.join.apply_left),
            (self.right, self.join.apply_right),
        ):
            for i, ex in enumerate(chain):
                wm = ex.emit_watermark()
                if wm is None:
                    continue
                self._note_watermark(wm.value)
                wm, pending = _walk_watermark(chain[i + 1 :], wm)
                for c in pending:
                    outs.extend(feed(c))
                if wm is not None:
                    down, flushed = self.join.on_watermark(wm)
                    outs.extend(flushed)
                    if down is not None:
                        aligned = down
        outs = self._through(self.tail, outs)
        _, tail_outs = _walk_watermark(self.tail, aligned)
        outs.extend(tail_outs)
        for i, ex in enumerate(self.tail):
            wm = ex.emit_watermark()
            if wm is not None:
                _, touts = _walk_watermark(self.tail[i + 1 :], wm)
                outs.extend(touts)
        return outs

    def watermark(self, column: str, value: int) -> List[StreamChunk]:
        """Send a watermark down both input chains; each side's
        (possibly transformed) watermark reaches the join, which cleans
        that side's window state and emits an ALIGNED downstream
        watermark (min over both inputs) once both sides advanced —
        which then walks the tail chain (reference: per-input watermark
        alignment on multi-input executors)."""
        self._note_watermark(value)
        if self._fused is not None:
            # buffered rows precede the watermark in stream order: the
            # fused wrapper applies them (data-only program), then the
            # walk below runs over member state interpreted — state
            # lives in the members between programs, so interop is
            # exact (the FusedChainExecutor.on_watermark discipline)
            self._fused.flush_data()
        outs: List[StreamChunk] = []
        aligned: Optional[Watermark] = None
        for side_chain, feed in (
            (self.left, self.join.apply_left),
            (self.right, self.join.apply_right),
        ):
            wm, pending = _walk_watermark(side_chain, Watermark(column, value))
            for c in pending:
                outs.extend(feed(c))
            if wm is not None:
                down, flushed = self.join.on_watermark(wm)
                outs.extend(flushed)
                if down is not None:
                    aligned = down
        # data chunks enter the tail BEFORE the aligned watermark closes
        # anything they belong to
        data_outs = self._through(self.tail, outs)
        _, tail_outs = _walk_watermark(self.tail, aligned)
        return data_outs + tail_outs

    @property
    def executors(self) -> List[Executor]:
        """Every executor in the fragment, for checkpoint enumeration."""
        return self.left + self.right + [self.join] + self.tail

    @property
    def epoch(self) -> int:
        return self._epoch
