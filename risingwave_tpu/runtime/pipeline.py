"""Single-fragment pipeline: an ordered executor chain + epoch driver.

Reference roles:
- the actor's executor chain (src/stream/src/executor/mod.rs:180 — each
  executor wraps its input stream; here the host feeds messages down an
  ordered list instead);
- barrier flow-through: a barrier entering the chain flushes each
  executor in turn, and an executor's flush output is DATA for every
  executor below it (src/stream/src/task/barrier_manager.rs:634 +
  executor flush_data patterns);
- watermark propagation (executor/watermark_filter.rs): watermarks pass
  through every executor, letting stateful ones clean closed state.

The epoch counter follows the reference epoch encoding
(physical ms << 16, src/common/src/util/epoch.rs:36).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Epoch, Executor, Watermark


class Pipeline:
    """An ordered chain of executors driven by the host epoch loop."""

    def __init__(self, executors: Sequence[Executor]):
        self.executors = list(executors)
        self._epoch = 0

    # -- message plumbing -------------------------------------------------
    def push(self, chunk: StreamChunk, start: int = 0) -> List[StreamChunk]:
        """Feed one data chunk into the chain; returns what falls out."""
        pending = [chunk]
        for ex in self.executors[start:]:
            nxt: List[StreamChunk] = []
            for c in pending:
                nxt.extend(ex.apply(c))
            pending = nxt
        return pending

    def barrier(self, checkpoint: bool = True) -> List[StreamChunk]:
        """Inject a barrier; each executor's flush output becomes data
        for the rest of the chain. Returns chunks exiting the chain."""
        prev = self._epoch
        self._epoch = max(int(time.time() * 1000) << 16, prev + 1)
        b = Barrier(Epoch(prev, self._epoch), checkpoint)
        pending: List[StreamChunk] = []
        for i, ex in enumerate(self.executors):
            nxt: List[StreamChunk] = []
            for c in pending:
                nxt.extend(ex.apply(c))
            nxt.extend(ex.on_barrier(b))
            pending = nxt
        return pending

    def watermark(self, column: str, value: int) -> List[StreamChunk]:
        """Propagate a watermark; executors may transform it (e.g. hop
        window: event time -> window_start) or consume it; their flush
        outputs flow downstream as data."""
        wm: Optional[Watermark] = Watermark(column, value)
        pending: List[StreamChunk] = []
        for ex in self.executors:
            nxt: List[StreamChunk] = []
            for c in pending:
                nxt.extend(ex.apply(c))
            if wm is not None:
                wm, outs = ex.on_watermark(wm)
                nxt.extend(outs)
            pending = nxt
        return pending

    @property
    def epoch(self) -> int:
        return self._epoch
