"""HBM memory governor + overload control: attribution becomes action.

PR 16 gave every barrier a backpressure *verdict* (freshness.py
``attribute_backpressure``: which fragment, how many ms, channel ages)
and PR 13/15 gave state a *planner* (bucketing.BucketAllocator) — but
nothing connected them: sources ingest unboundedly, allocators grow
eagerly with no global ceiling, and a skewed key storm ends in device
OOM instead of controlled lag. This module closes the loop, after the
reference's memory controller (src/compute/src/memory/controller.rs:
an LRU watermark driven by jemalloc stats) and the back-pressured
exchange (permits.rs), rebuilt for the host-pumped TPU model:

- :class:`MemoryGovernor` — the global device-state ledger. Per-table
  footprint from executor ``state_nbytes()`` contracts + the bucketing
  allocator's capacity notes, cross-checked against deviceprof modeled
  bytes and (when the backend exposes it) sampled
  ``Device.memory_stats()``. Enforces ``RW_HBM_BUDGET_BYTES`` (or
  ``RW_HBM_BUDGET_FRAC`` of the sampled device limit) by vetoing
  ``BucketAllocator`` growth that would cross the budget (the
  ``grow_gate`` surface — growth is *deferred*, never denied: the
  allocator re-probes each barrier once spill/lazy-shrink has freed
  room) and by triggering the cold-tier spill the executors already
  expose (``evict_cold`` via ``cold_reader``/``cold_get_rows``)
  above the spill watermark. Lag, never loss — and never OOM.
- :class:`OverloadLadder` — NORMAL -> THROTTLED -> SHEDDING ->
  DEGRADED with hysteresis: escalation is immediate (overload must be
  met now), de-escalation descends ONE rung after a sticky cool-down
  of consecutive calm barriers, so a load flapping at a threshold
  cannot flap the ladder (the same grow-eagerly/shrink-lazily
  discipline the bucket walk uses). Every transition is a structured
  ``overload`` event + ``overload_transitions_total`` counter.
- :class:`AdmissionController` — per-fragment credit windows in
  [0, 1] derived from the ladder rung, governor pressure and the
  barrier's backpressure verdict (the named bottleneck's feeders are
  clamped hardest). ``SourceManager.poll`` multiplies its
  ``max_rows_per_split`` by the credit; credit 0 parks the source at
  its anchored split offsets (a zero-row poll: offsets do not
  advance, exactly-once untouched).

The governor rides ``StreamingRuntime._end_trace`` (both the serial
and the pipelined closer path), is dormant unless armed (a budget via
env/ctor, or ``RW_OVERLOAD_LADDER=1``), self-measures its host cost
(``host_ms`` — the same <1% budget class as freshness tracking and
the blackbox ring) and never faults a barrier.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

__all__ = [
    "NORMAL",
    "THROTTLED",
    "SHEDDING",
    "DEGRADED",
    "LADDER",
    "AdmissionController",
    "MemoryGovernor",
    "OverloadLadder",
]

# the degradation ladder, mildest first; gauge value = list index
NORMAL = "NORMAL"
THROTTLED = "THROTTLED"
SHEDDING = "SHEDDING"
DEGRADED = "DEGRADED"
LADDER = (NORMAL, THROTTLED, SHEDDING, DEGRADED)

# rung -> base admission credit (fraction of the configured poll size)
_BASE_CREDIT = {
    NORMAL: 1.0,
    THROTTLED: 0.5,
    SHEDDING: 0.25,
    DEGRADED: 0.0,  # parked at the anchored offsets
}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _device_memory_stats() -> Optional[Dict]:
    """One guarded ``memory_stats()`` sample from device 0, or None
    (CPU backends and older plugins may not expose it)."""
    try:
        import jax

        dev = jax.local_devices()[0]
        st = dev.memory_stats()
        return st if isinstance(st, dict) else None
    except Exception:  # noqa: BLE001 — sampling is best-effort
        return None


class OverloadLadder:
    """The degradation state machine. ``step(score)`` is called once
    per barrier with the combined pressure score (budget fractions:
    1.0 = at the HBM budget / at the queue-age budget) and returns the
    current rung.

    Escalation: immediate, to the highest rung whose enter threshold
    the score meets (overload is met the barrier it appears).
    De-escalation: one rung at a time, only after ``cooldown``
    CONSECUTIVE barriers below that rung's exit threshold (enter *
    ``exit_margin``) — the sticky cool-down that keeps a boundary-
    riding load from flapping the ladder. ``flaps`` counts
    re-escalations that land within ``cooldown`` barriers of a
    de-escalation (the throttle-flap budget perf_gate holds)."""

    def __init__(
        self,
        throttle_at: Optional[float] = None,
        shed_at: Optional[float] = None,
        degrade_at: Optional[float] = None,
        cooldown: Optional[int] = None,
        exit_margin: float = 0.85,
    ):
        self.throttle_at = (
            throttle_at
            if throttle_at is not None
            else _env_float("RW_OVERLOAD_THROTTLE_AT", 0.75)
        )
        self.shed_at = (
            shed_at
            if shed_at is not None
            else _env_float("RW_OVERLOAD_SHED_AT", 0.90)
        )
        self.degrade_at = (
            degrade_at
            if degrade_at is not None
            else _env_float("RW_OVERLOAD_DEGRADE_AT", 0.98)
        )
        self.cooldown = (
            cooldown
            if cooldown is not None
            else _env_int("RW_OVERLOAD_COOLDOWN_BARRIERS", 8)
        )
        self.exit_margin = exit_margin
        self.state = NORMAL
        self.flaps = 0
        self._calm = 0  # consecutive barriers below the exit threshold
        self._since_descent = 10**9  # barriers since the last de-escalation
        self.last_score = 0.0
        self.transitions: List[Dict] = []

    def _enter_threshold(self, state: str) -> float:
        return {
            THROTTLED: self.throttle_at,
            SHEDDING: self.shed_at,
            DEGRADED: self.degrade_at,
        }.get(state, 0.0)

    def _target(self, score: float) -> str:
        if score >= self.degrade_at:
            return DEGRADED
        if score >= self.shed_at:
            return SHEDDING
        if score >= self.throttle_at:
            return THROTTLED
        return NORMAL

    def step(self, score: float, epoch: int = 0) -> str:
        self.last_score = score
        self._since_descent += 1
        target = self._target(score)
        cur_i, tgt_i = LADDER.index(self.state), LADDER.index(target)
        if tgt_i > cur_i:
            # escalate NOW, possibly several rungs at once
            if self._since_descent <= self.cooldown:
                self.flaps += 1
            self._record(target, score, epoch)
            self._calm = 0
        elif tgt_i < cur_i:
            # below this rung's exit threshold? count calm barriers,
            # then descend exactly one rung
            exit_at = self._enter_threshold(self.state) * self.exit_margin
            if score < exit_at:
                self._calm += 1
                if self._calm >= self.cooldown:
                    self._record(LADDER[cur_i - 1], score, epoch)
                    self._calm = 0
                    self._since_descent = 0
            else:
                self._calm = 0
        else:
            self._calm = 0
        return self.state

    def _record(self, new: str, score: float, epoch: int) -> None:
        from risingwave_tpu.event_log import EVENT_LOG
        from risingwave_tpu.metrics import REGISTRY

        old, self.state = self.state, new
        ev = {
            "ts": time.time(),
            "epoch": epoch,
            "from": old,
            "to": new,
            "score": round(score, 4),
        }
        self.transitions.append(ev)
        del self.transitions[:-256]
        REGISTRY.counter("overload_transitions_total").inc(
            **{"from": old, "to": new}
        )
        REGISTRY.gauge("overload_state").set(float(LADDER.index(new)))
        EVENT_LOG.record(
            "overload",
            epoch=epoch,
            mode=new,
            prev=old,
            score=round(score, 4),
        )

    def snapshot(self) -> Dict:
        return {
            "state": self.state,
            "score": round(self.last_score, 4),
            "flaps": self.flaps,
            "cooldown": self.cooldown,
            "transitions": list(self.transitions[-32:]),
        }


class AdmissionController:
    """Per-fragment credit windows for source admission.

    ``credit(fragment)`` in [0, 1] multiplies the source's configured
    poll size (``SourceManager.poll``); ``rederive`` is called by the
    governor each barrier with the ladder rung, the memory pressure
    and the backpressure verdict detail. Credits move toward their
    target multiplicatively (halve on the way down, recover by at
    most ``recover_step`` per barrier on the way up) — the per-
    fragment hysteresis that damps throttle flapping below the ladder
    transitions themselves. A fragment named as the barrier's
    bottleneck is clamped one extra halving."""

    def __init__(self, recover_step: float = 0.25, floor: float = 0.0):
        self.credits: Dict[str, float] = {}
        self.recover_step = recover_step
        self.floor = floor
        self.parked_polls = 0
        self.rederives = 0

    def credit(self, fragment: Optional[str]) -> float:
        if not self.credits:
            return 1.0
        if fragment is None or fragment not in self.credits:
            # an unmapped source is governed by the tightest window
            return min(self.credits.values())
        return self.credits[fragment]

    def admit_rows(self, fragment: Optional[str], requested: int) -> int:
        """Clamp one poll's ``max_rows_per_split``; 0 = parked (the
        caller performs a zero-row poll so offsets stay anchored)."""
        c = self.credit(fragment)
        rows = int(requested * c)
        if rows <= 0 and c <= 0.0:
            self.parked_polls += 1
            return 0
        return max(rows, 1)

    def rederive(
        self,
        state: str,
        pressure: float,
        detail: Optional[Dict[str, Dict]] = None,
        bottleneck: Optional[str] = None,
        fragments=(),
    ) -> None:
        self.rederives += 1
        base = _BASE_CREDIT.get(state, 1.0)
        names = set(fragments) | set(detail or ()) | set(self.credits)
        for name in names:
            target = base
            if bottleneck is not None and name == bottleneck and target > 0:
                target *= 0.5  # the named bottleneck's feed halves again
            cur = self.credits.get(name, 1.0)
            if target <= 0.0:
                # DEGRADED parks NOW: the emergency rung anchors the
                # source at its split offsets (credit exactly 0 — a
                # zero-row poll), it does not trickle toward zero
                nxt = 0.0
            elif target < cur:
                # clamp fast: at least halve toward the target now
                nxt = max(target, cur * 0.5)
            else:
                # recover slowly: bounded step per barrier
                nxt = min(target, cur + self.recover_step)
            self.credits[name] = max(self.floor, min(1.0, round(nxt, 4)))

    def reset(self) -> None:
        self.credits.clear()

    def snapshot(self) -> Dict:
        return {
            "credits": dict(self.credits),
            "parked_polls": self.parked_polls,
            "rederives": self.rederives,
        }


class MemoryGovernor:
    """Global device-state ledger + the control actions above it.

    Armed when a budget resolves (``budget_bytes`` ctor arg,
    ``RW_HBM_BUDGET_BYTES``, or ``RW_HBM_BUDGET_FRAC`` of the sampled
    device ``bytes_limit``) or when ``RW_OVERLOAD_LADDER=1`` asks for
    queue-pressure-only laddering; otherwise ``observe_barrier`` is a
    single attribute check and NOTHING is gated (tier-1 behavior
    unchanged). One instance per runtime, like ShapeGovernor."""

    def __init__(self, budget_bytes: Optional[int] = None):
        env_b = os.environ.get("RW_HBM_BUDGET_BYTES")
        if budget_bytes is None and env_b:
            try:
                budget_bytes = int(env_b)
            except ValueError:
                budget_bytes = None
        if budget_bytes is None and os.environ.get("RW_HBM_BUDGET_FRAC"):
            st = _device_memory_stats()
            limit = (st or {}).get("bytes_limit")
            if limit:
                budget_bytes = int(
                    _env_float("RW_HBM_BUDGET_FRAC", 0.8) * limit
                )
        self.budget_bytes = budget_bytes
        self.enabled = budget_bytes is not None or os.environ.get(
            "RW_OVERLOAD_LADDER", ""
        ).strip().lower() in ("1", "on", "true")
        # spill watermark: relieve (cold-tier spill) above this budget
        # fraction, BEFORE the hard veto wall at 1.0
        self.spill_at = _env_float("RW_HBM_SPILL_AT", 0.85)
        # queue-age budget for the pressure score's second component
        self.queue_ms_budget = _env_float("RW_OVERLOAD_QUEUE_MS", 2000.0)
        self.sample_every = max(1, _env_int("RW_HBM_SAMPLE_EVERY", 16))
        self.ladder = OverloadLadder()
        self.admission = AdmissionController()
        # ledger state (rebuilt per barrier while armed)
        self.ledger_total = 0
        self.ledger_high = 0  # high-water across barriers (pre-relief)
        self._ledger_prev = 0  # previous barrier's pre-relief ledger
        self._flat_streak = 0  # consecutive barriers with a flat ledger
        # flat barriers required before a raised ladder treats a flat
        # ledger as "storm over" and spills down to the exit floor
        self.relief_patience = self.ladder.cooldown + 1
        self.modeled_total = 0
        self.sampled_bytes: Optional[int] = None
        self.sampled_limit: Optional[int] = None
        self._tables: List[Dict] = []
        self._barriers = 0
        self.vetoes = 0
        self.spills = 0
        self.host_ms = 0.0
        self._relief_wanted = False
        self._gated: set = set()
        # DEGRADED bookkeeping: original fused depths + whether WE
        # paused compaction (never clear a pause the store-degraded
        # path owns)
        self._saved_depths: Dict[int, int] = {}
        self._depth_owners: List = []
        self._compact_paused = False

    # -- the per-barrier hook (rides _end_trace) -------------------------
    def observe_barrier(self, runtime, tr=None) -> None:
        if not self.enabled:
            return
        t0 = time.perf_counter()
        try:
            self._observe(runtime, tr)
        except Exception:  # noqa: BLE001 — governance never faults a barrier
            pass
        finally:
            self.host_ms += (time.perf_counter() - t0) * 1e3

    def _observe(self, runtime, tr) -> None:
        self._barriers += 1
        self._rebuild_ledger(runtime)
        self.ledger_high = max(self.ledger_high, self.ledger_total)
        if (
            self.budget_bytes is not None
            and self._barriers % self.sample_every == 0
        ):
            st = _device_memory_stats()
            if st is not None:
                self.sampled_bytes = st.get("bytes_in_use")
                self.sampled_limit = st.get("bytes_limit")
        # score the pressure that EXISTED this barrier, then relieve:
        # the ladder must see the spike relief is responding to (else
        # a successful spill hides every overload from the ladder);
        # the post-relief ledger is what next barrier's gates enforce
        score = self._pressure_score(tr)
        # relief watermark: the steady-state spill line — except in the
        # DESCENT REGION, where spill keeps firing until memory clears
        # the NORMAL-exit floor (residual durable state would otherwise
        # hover between the exit floor and the spill line forever and
        # pin the ladder raised).  The ladder is descending when either
        #   (a) pressure has fallen below the current rung's own entry
        #       threshold (post-peak: the spike that raised the rung has
        #       been relieved), or
        #   (b) the ledger has been flat for `relief_patience` barriers
        #       (the storm has ceased; residual state is all that's
        #       left).  A single quiet barrier mid-storm is NOT enough —
        #       capacity-based footprints go flat between growth
        #       boundaries, and opening the floor there would let relief
        #       pre-empt escalation.
        if self.ledger_total > self._ledger_prev:
            self._flat_streak = 0
        else:
            self._flat_streak += 1
        self._ledger_prev = self.ledger_total
        relief_at = self.spill_at
        if self.ladder.state != NORMAL and (
            score < self.ladder._enter_threshold(self.ladder.state)
            or self._flat_streak >= self.relief_patience
        ):
            relief_at = min(
                relief_at,
                self.ladder.throttle_at * self.ladder.exit_margin,
            )
        if (
            self.budget_bytes is not None
            and self.ledger_total > relief_at * self.budget_bytes
        ) or self._relief_wanted:
            self._relief_wanted = False
            self._relieve(runtime)
            self._rebuild_ledger(runtime)
        prev = self.ladder.state
        state = self.ladder.step(score, epoch=getattr(tr, "epoch", 0))
        if state != prev:
            self._apply_state(runtime, prev, state)
        elif state == DEGRADED:
            # a recovery mid-DEGRADED rebuilds executors at configured
            # depth: re-assert depth=1 on the barrier clock (idempotent)
            self._enter_degraded(runtime)
        detail = getattr(tr, "backpressure", None) if tr is not None else None
        if state != NORMAL or self.admission.credits:
            self.admission.rederive(
                state,
                score,
                detail=detail,
                bottleneck=(
                    getattr(tr, "backpressure_fragment", None)
                    if tr is not None
                    else None
                ),
                fragments=getattr(runtime, "fragments", {}).keys(),
            )
        if tr is not None:
            tr.overload_state = state
        from risingwave_tpu.metrics import REGISTRY

        REGISTRY.gauge("memory_ledger_bytes").set(float(self.ledger_total))
        if self.budget_bytes:
            REGISTRY.gauge("memory_headroom_bytes").set(
                float(self.budget_bytes - self.ledger_total)
            )

    # -- ledger ----------------------------------------------------------
    def _rebuild_ledger(self, runtime) -> None:
        """Walk the executors' accounting contracts into per-table
        rows. Host metadata only (``.nbytes`` + allocator snapshots —
        no device reads, no flushes). Also (re)attaches grow gates:
        recovery rebuilds executors with fresh allocators, so
        attachment must self-heal on the barrier clock."""
        tables: List[Dict] = []
        total = 0
        gate_on = self.budget_bytes is not None
        for ex in runtime.executors():
            nb = None
            fn = getattr(ex, "state_nbytes", None)
            if fn is not None:
                try:
                    nb = int(fn())
                except Exception:  # noqa: BLE001
                    nb = None
            allocs = self._allocators(ex)
            if gate_on:
                for alloc in allocs:
                    if id(alloc) not in self._gated or alloc.grow_gate is None:
                        self._attach_gate(ex, alloc)
            if nb is None and not allocs:
                continue
            # per-shard ledger breakdown (ISSUE 18): sharded executors
            # expose state_nbytes_per_shard() — the mesh rw_memory rows
            # and hot-shard forensics read it from here, not the device
            shards = None
            sfn = getattr(ex, "state_nbytes_per_shard", None)
            if sfn is not None:
                try:
                    shards = [int(v) for v in sfn()]
                except Exception:  # noqa: BLE001
                    shards = None
            tables.append(
                {
                    "table_id": str(getattr(ex, "table_id", "")) or "-",
                    "executor": type(ex).__name__,
                    "ledger_bytes": nb or 0,
                    "high_water": max(
                        (a.high_water for a in allocs), default=0
                    ),
                    "pinned": any(a.pinned for a in allocs),
                    "vetoes": sum(a.vetoes for a in allocs),
                    "saturated": any(a._saturated for a in allocs),
                    "shards": shards,
                }
            )
            total += nb or 0
        self._tables = tables
        self.ledger_total = total
        # deviceprof modeled bytes: what the COMPILED programs say they
        # touch per barrier (a traffic model, not a residency model —
        # the reconciliation column, never the enforcement input)
        try:
            from risingwave_tpu.deviceprof import DEVICEPROF

            self.modeled_total = sum(
                int(f.get("modeled_bytes") or 0)
                for f in DEVICEPROF.fragments.values()
            )
        except Exception:  # noqa: BLE001
            self.modeled_total = 0

    @staticmethod
    def _allocators(ex) -> List:
        b = getattr(ex, "_buckets", None)
        if b is None:
            return []
        if isinstance(b, dict):
            return [a for a in b.values() if a is not None]
        return [b]

    def _attach_gate(self, ex, alloc) -> None:
        gov = self

        def gate(cap: int, new_cap: int, _ex=ex) -> bool:
            nb = 0
            fn = getattr(_ex, "state_nbytes", None)
            if fn is not None:
                try:
                    nb = int(fn())
                except Exception:  # noqa: BLE001
                    nb = 0
            per_slot = (nb / cap) if (nb and cap) else 8.0
            return gov.authorize_grow(
                str(getattr(_ex, "table_id", type(_ex).__name__)),
                cap,
                new_cap,
                per_slot,
            )

        alloc.grow_gate = gate
        self._gated.add(id(alloc))

    def authorize_grow(
        self, table_id: str, cap: int, new_cap: int, per_slot: float
    ) -> bool:
        """The ``BucketAllocator.grow_gate`` contract: may this buffer
        grow cap -> new_cap right now? Deferral, not denial — the
        allocator's ``_veto_hold`` re-probes next barrier, after spill
        and lazy-shrink have had a chance to free room."""
        if self.budget_bytes is None:
            return True
        projected = self.ledger_total + int((new_cap - cap) * per_slot)
        if projected <= self.budget_bytes:
            # optimistically charge the grow so several same-barrier
            # grows cannot each claim the same headroom
            self.ledger_total = projected
            return True
        self.vetoes += 1
        self._relief_wanted = True
        from risingwave_tpu.event_log import EVENT_LOG
        from risingwave_tpu.metrics import REGISTRY

        REGISTRY.counter("memory_governor_vetoes_total").inc()
        EVENT_LOG.record(
            "memory_governor",
            action="veto_grow",
            table_id=table_id,
            cap=cap,
            new_cap=new_cap,
            projected=projected,
            budget=self.budget_bytes,
        )
        return False

    def _relieve(self, runtime) -> None:
        """Cold-tier spill (the `_enforce_memory_budget` discipline):
        join the async commit lane so eviction never races durability,
        then evict durable-cold groups on every executor wired to the
        cold tier. Frees OCCUPANCY now; capacity follows via the
        allocator's lazy shrink."""
        evicted = 0
        try:
            runtime.wait_checkpoints()
            for ex in runtime.executors():
                fn = getattr(ex, "evict_cold", None)
                has_reader = (
                    getattr(ex, "cold_reader", None) is not None
                    or getattr(ex, "cold_get_rows", None) is not None
                )
                if fn is not None and has_reader:
                    evicted += fn()
        except Exception:  # noqa: BLE001 — relief is best-effort
            pass
        self.spills += 1
        from risingwave_tpu.event_log import EVENT_LOG
        from risingwave_tpu.metrics import REGISTRY

        REGISTRY.counter("memory_governor_spills_total").inc()
        if evicted:
            REGISTRY.counter("cold_evictions_total").inc(evicted)
        EVENT_LOG.record(
            "memory_governor",
            action="spill",
            evicted=evicted,
            ledger=self.ledger_total,
            budget=self.budget_bytes,
        )

    # -- pressure + ladder actions ---------------------------------------
    def _pressure_score(self, tr) -> float:
        mem = (
            self.ledger_total / self.budget_bytes
            if self.budget_bytes
            else 0.0
        )
        queue = 0.0
        if tr is not None and self.queue_ms_budget > 0:
            ages = [
                d.get("oldest_age_ms") or 0.0
                for d in (getattr(tr, "backpressure", None) or {}).values()
            ]
            if ages:
                # normalized so queue age AT budget lands on the
                # DEGRADED threshold, same scale as the memory axis
                queue = (
                    max(ages) / self.queue_ms_budget
                ) * self.ladder.degrade_at
        return max(mem, queue)

    def _apply_state(self, runtime, old: str, new: str) -> None:
        old_i, new_i = LADDER.index(old), LADDER.index(new)
        shed_i, deg_i = LADDER.index(SHEDDING), LADDER.index(DEGRADED)
        reg = getattr(runtime, "arrangements", None)
        if reg is not None:
            # SHEDDING: attached-MV eager materialization pauses —
            # publish becomes pointer-swap-only; readers fall back to
            # the lock path and demand re-latches after recovery
            reg.shed_eager = new_i >= shed_i
        if new_i >= deg_i and old_i < deg_i:
            self._enter_degraded(runtime)
        elif new_i < deg_i and old_i >= deg_i:
            self._exit_degraded(runtime)

    def _enter_degraded(self, runtime) -> None:
        # pipeline depth -> 1: each fused executor drains its pending
        # K-window packs on the next finish_barrier, then runs barrier-
        # synchronous (remember originals for the recovery path).
        # Idempotent on purpose: a recovery mid-DEGRADED rebuilds
        # executors at their configured depth, so the per-barrier
        # re-assert must reduce the NEW ones without forgetting the
        # saved depths of the already-reduced survivors.
        for ex in runtime.executors():
            d = getattr(ex, "depth", None)
            if isinstance(d, int) and d > 1:
                self._saved_depths[id(ex)] = d
                self._depth_owners.append(ex)
                ex.depth = 1
        # defer compaction (reuse the store-degraded pause latch, but
        # remember that WE set it: never clear the store path's pause)
        pause = getattr(runtime, "_compact_pause", None)
        if pause is not None and not pause.is_set():
            pause.set()
            self._compact_paused = True

    def _exit_degraded(self, runtime) -> None:
        for ex in self._depth_owners:
            saved = self._saved_depths.get(id(ex))
            if saved is not None and getattr(ex, "depth", None) == 1:
                ex.depth = saved
        self._saved_depths.clear()
        self._depth_owners = []
        if self._compact_paused:
            self._compact_paused = False
            if not getattr(runtime, "_degraded", False):
                pause = getattr(runtime, "_compact_pause", None)
                if pause is not None:
                    pause.clear()

    # -- introspection ---------------------------------------------------
    def ledger_snapshot(self) -> List[Dict]:
        """Per-table rows for ``rw_memory`` (copies)."""
        return [dict(t) for t in self._tables]

    def snapshot(self) -> Dict:
        return {
            "enabled": self.enabled,
            "budget_bytes": self.budget_bytes,
            "ledger_bytes": self.ledger_total,
            "ledger_high_bytes": self.ledger_high,
            "modeled_bytes": self.modeled_total,
            "sampled_bytes": self.sampled_bytes,
            "sampled_limit": self.sampled_limit,
            "headroom_bytes": (
                self.budget_bytes - self.ledger_total
                if self.budget_bytes is not None
                else None
            ),
            "vetoes": self.vetoes,
            "spills": self.spills,
            "host_ms": round(self.host_ms, 4),
            "barriers": self._barriers,
            "ladder": self.ladder.snapshot(),
            "admission": self.admission.snapshot(),
        }
