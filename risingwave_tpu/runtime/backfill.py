"""Backfill — creating an MV over an existing MV.

Reference: src/stream/src/executor/backfill/no_shuffle_backfill.rs:66 —
a new downstream MV first consumes a SNAPSHOT of the upstream
materialized state, then switches to the upstream's live change stream;
the snapshot and the stream stitch exactly because the snapshot is
taken at a barrier boundary.

TPU re-design: fragments are host-driven and barriers are synchronous,
so the stitch point is trivial to realize: ``snapshot_chunks`` reads
the upstream MaterializeExecutor's committed rows between two barriers
(no in-flight deltas exist then), emits them as INSERT chunks, and the
runtime's fragment subscription (StreamingRuntime.register(upstream=…))
routes every later upstream delta into the downstream pipeline — the
"no-shuffle" upstream-to-backfill edge.
"""

from __future__ import annotations

from typing import List

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk


def snapshot_chunks(
    mview, capacity: int = 1024, dictionaries=None
) -> List[StreamChunk]:
    """Upstream MV rows -> INSERT chunks (the backfill snapshot phase).

    ``mview`` is a MaterializeExecutor; its snapshot is keyed
    pk-tuple -> value-tuple. NULL components become null lanes.
    """
    snap = mview.snapshot()
    names = list(mview.pk) + list(mview.columns)
    # host-map executors carry ``_dtypes``; the device-resident MV
    # exposes ``dtypes`` — both map column -> numpy/jnp dtype
    dt_map = getattr(mview, "_dtypes", None) or getattr(mview, "dtypes", {})
    dtypes = {
        name: np.dtype(dt_map.get(name, np.int64)) for name in names
    }
    rows = [list(k) + list(v) for k, v in snap.items()]
    out: List[StreamChunk] = []
    for at in range(0, len(rows), capacity):
        part = rows[at : at + capacity]
        cols, nulls = {}, {}
        for j, name in enumerate(names):
            vals = [r[j] for r in part]
            isnull = np.array([v is None for v in vals], bool)
            filled = np.asarray(
                [0 if v is None else v for v in vals], dtypes[name]
            )
            cols[name] = filled
            if isnull.any():
                nulls[name] = isnull
        out.append(
            StreamChunk.from_numpy(cols, capacity, nulls=nulls or None)
        )
    return out
