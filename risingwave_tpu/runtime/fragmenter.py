"""Planner output -> actor-graph execution: the unified runtime path.

The reference has ONE path from SQL to running operators: the frontend
fragments the stream plan at exchange edges
(src/frontend/src/stream_fragmenter/mod.rs:26-60), meta expands
fragments x parallelism into actors with dispatchers and vnode mappings
(src/meta/src/stream/stream_graph/actor.rs:648,
stream_graph/schedule.rs:131), and compute nodes run them over permit
channels (src/stream/src/executor/dispatch.rs:683). This module is that
path for the TPU build: it takes the StreamPlanner's executor chains
and re-expresses them as a ``GraphRuntime`` fragment graph —

  source frag --hash(dist cols)--> parallel frag x N --simple--> mat frag

- Each parallel instance is an independently planned, fresh executor
  chain (the actor build step, stream_manager.rs:89 create_nodes).
- Keyed state is hash-partitioned by a dispatch-key subset of the
  stateful executor's keys that traces back to source columns; one
  logical state table spans all instances with disjoint vnode ownership
  (consistent_hash/vnode.rs:34) via ``PartitionedStateView``.
- The facade ``GraphPipeline`` exposes the serial Pipeline surface
  (push/barrier/watermark/executors), so the SAME StreamingRuntime
  checkpoint/recovery/barrier machinery drives both execution modes.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Executor
from risingwave_tpu.executors.dedup import AppendOnlyDedupExecutor
from risingwave_tpu.executors.filter import FilterExecutor
from risingwave_tpu.executors.hash_agg import HashAggExecutor
from risingwave_tpu.executors.hop_window import HopWindowExecutor
from risingwave_tpu.executors.project import ProjectExecutor
from risingwave_tpu.expr import expr as E
from risingwave_tpu.ops.hashing import VNODE_COUNT, hash_columns
from risingwave_tpu.parallel.meshprof import MESHPROF
from risingwave_tpu.runtime.graph import FragmentSpec, GraphRuntime
from risingwave_tpu.runtime.pipeline import (
    FreshnessSurface,
    Pipeline,
    TwoInputPipeline,
)
from risingwave_tpu.storage.state_table import Checkpointable, StateDelta

# stateless executors a hash exchange may commute past (rows travel
# independently; no cross-row state): anything else ends the parallel
# prefix and runs in the singleton tail fragment
_PARALLEL_STATELESS = (FilterExecutor, ProjectExecutor, HopWindowExecutor)
# keyed stateful executors whose state partitions cleanly by a subset
# of their key tuple (HashAgg dirty-group state, append-only dedup)
_KEYED = (HashAggExecutor, AppendOnlyDedupExecutor)


def _keys_of(ex) -> Tuple[str, ...]:
    return tuple(getattr(ex, "group_keys", None) or getattr(ex, "keys", ()))


def _trace_source_col(chain: Sequence[Executor], name: str) -> Optional[str]:
    """Walk ``name`` backwards through a chain prefix to the source
    column it is an UNMODIFIED copy of (None if computed/renamed-over/
    untraceable). Conservative: only executors whose column flow we
    fully understand participate."""
    cur = name
    for ex in reversed(list(chain)):
        if isinstance(ex, ProjectExecutor):
            expr = dict(ex.outputs).get(cur)
            if not isinstance(expr, E.Col):
                return None
            cur = expr.name
        elif isinstance(ex, HopWindowExecutor):
            if cur == ex.out_start:
                return None  # computed column
        elif isinstance(ex, FilterExecutor):
            pass
        elif isinstance(ex, _KEYED):
            if cur not in _keys_of(ex):
                return None  # agg/dedup emit only their key columns
        else:
            return None
    return cur


def _key_lane_index(ex, pos: int) -> Optional[int]:
    """Checkpoint key-lane index (k{i}) of key POSITION ``pos``: HashAgg
    interleaves a bool null-indicator lane after each NULLABLE group
    key, so lane != position when nullable keys precede. A nullable
    dispatch key itself is disqualified (the dispatcher hashes the raw
    value lane; NULL rows would route by fill garbage)."""
    nb = getattr(ex, "nullable", None)
    if nb is None:
        return pos
    if nb[pos]:
        return None
    return pos + sum(1 for q in range(pos) if nb[q])


def _view_positions(
    chain_before: Sequence[Executor],
    ex,
    dispatch_srcs: Sequence[str],
) -> Optional[Tuple[int, ...]]:
    """For a keyed executor whose input has passed ``chain_before``:
    the checkpoint key-LANE index of each dispatch source column, in
    dispatch order (restore routing must hash the same values in the
    same order as the upstream HashDispatcher). None if any dispatch
    column is not one of the executor's (non-nullable) keys."""
    key_tuple = _keys_of(ex)
    out = []
    for s in dispatch_srcs:
        q = next(
            (
                qi
                for qi, k in enumerate(key_tuple)
                if _trace_source_col(chain_before, k) == s
            ),
            None,
        )
        if q is None:
            return None
        lane = _key_lane_index(ex, q)
        if lane is None:
            return None
        out.append(lane)
    return tuple(out)


class PartitionedStateView(Checkpointable):
    """One LOGICAL state table physically partitioned across N actor
    instances by vnode of the dispatch columns (the reference's 'same
    table_id, disjoint vnodes per actor' model). Presents the
    Checkpointable surface: deltas concatenate (key spaces are
    disjoint), restores route rows to the owning instance with the
    exact hash the upstream HashDispatcher used."""

    def __init__(self, instances: Sequence[object], positions: Dict[str, Tuple[int, ...]]):
        self._instances = list(instances)
        self._positions = dict(positions)  # table_id -> key-lane positions

    # -- Checkpointable ---------------------------------------------------
    @property
    def table_id(self) -> str:
        return self._instances[0].table_id

    def checkpoint_table_ids(self) -> List[str]:
        return self._instances[0].checkpoint_table_ids()

    def state_digest(self) -> int:
        """Wrapping sum over instance digests (disjoint key spaces;
        sum — not xor — so equal-state instances don't cancel)."""
        from risingwave_tpu.integrity import U64_MASK

        d = 0
        for inst in self._instances:
            d = (d + inst.state_digest()) & U64_MASK
        return d

    def checkpoint_delta(self) -> List[StateDelta]:
        by_tid: Dict[str, List[StateDelta]] = {}
        order: List[str] = []
        for inst in self._instances:
            # instances capture per-epoch deltas in their actor threads
            # under pipelined barriers; consume those (epoch order) or
            # fall back to a live pull in synchronous mode
            for d in inst.staged_or_live_delta():
                if d.table_id not in by_tid:
                    order.append(d.table_id)
                by_tid.setdefault(d.table_id, []).append(d)
        out = []
        for tid in order:
            ds = by_tid[tid]
            if len(ds) == 1:
                out.append(ds[0])
                continue
            keys = {
                k: np.concatenate([d.key_cols[k] for d in ds])
                for k in ds[0].key_cols
            }
            vals = {
                k: np.concatenate([d.value_cols[k] for d in ds])
                for k in ds[0].value_cols
            }
            tomb = np.concatenate([d.tombstone for d in ds])
            out.append(StateDelta(tid, keys, vals, tomb, ds[0].key_order))
        return out

    def restore_state(self, table_id, key_cols, value_cols) -> None:
        n = len(self._instances)
        if not key_cols or n == 1:
            for inst in self._instances:
                inst.restore_state(table_id, key_cols, value_cols)
            return
        pos = self._positions[table_id]
        lanes = [jnp.asarray(key_cols[f"k{p}"]) for p in pos]
        # EXACTLY the dispatcher's routing (graph.py _vnode_slice_mask):
        # a row restored to the wrong instance would be unreachable
        vnode = np.asarray(
            hash_columns(lanes, seed=0xC0FFEE) % VNODE_COUNT
        ).astype(np.int64)
        dest = vnode % n
        for i, inst in enumerate(self._instances):
            m = dest == i
            inst.restore_state(
                table_id,
                {k: v[m] for k, v in key_cols.items()},
                {k: v[m] for k, v in value_cols.items()},
            )

    # -- runtime hook fan-out ---------------------------------------------
    def state_nbytes(self) -> int:
        return sum(
            getattr(i, "state_nbytes", lambda: 0)() for i in self._instances
        )

    def evict_cold(self) -> int:
        total = 0
        for i in self._instances:
            fn = getattr(i, "evict_cold", None)
            if fn is not None and getattr(i, "cold_reader", None) is not None:
                total += fn()
        return total

    def on_epoch_durable(self, epoch: int) -> None:
        for i in self._instances:
            fn = getattr(i, "on_epoch_durable", None)
            if fn is not None:
                fn(epoch)

    def discard_pending(self) -> None:
        for i in self._instances:
            fn = getattr(i, "discard_pending", None)
            if fn is not None:
                fn()

    def discard_captured(self) -> None:
        for i in self._instances:
            i.discard_captured()

    def on_recover(self, epoch: int) -> None:
        for i in self._instances:
            fn = getattr(i, "on_recover", None)
            if fn is not None:
                fn(epoch)

    @property
    def minput(self):
        for i in self._instances:
            m = getattr(i, "minput", None)
            if m:
                return m
        return {}

    @property
    def checkpoint_enabled(self):
        return getattr(self._instances[0], "checkpoint_enabled", False)

    @checkpoint_enabled.setter
    def checkpoint_enabled(self, v):
        for i in self._instances:
            if hasattr(i, "checkpoint_enabled"):
                i.checkpoint_enabled = v

    @property
    def cold_reader(self):
        return getattr(self._instances[0], "cold_reader", None)

    @cold_reader.setter
    def cold_reader(self, fn):
        for i in self._instances:
            if hasattr(i, "cold_reader"):
                i.cold_reader = fn


class GraphPipeline(FreshnessSurface):
    """Pipeline-compatible facade over a ``GraphRuntime`` actor graph:
    the object a StreamingRuntime registers, barriers, checkpoints, and
    recovers — while pushes flow through dispatchers, permit channels,
    and (possibly parallel) FragmentActor threads.

    Contract differences vs the serial Pipeline are epoch-granular:
    ``push``/``watermark`` return [] (processing is async inside the
    actors) and ``barrier`` returns everything the terminal fragment
    emitted during the epoch — the StreamingRuntime routes barrier
    output to subscribers before their own barrier runs, so MV-on-MV
    edges see identical per-epoch content in both modes."""

    def __init__(
        self,
        specs: Sequence[FragmentSpec],
        source_map: Dict[str, str],  # side ("single"/"left"/"right") -> frag
        out_fragment: str,
        ckpt_executors: Sequence[object],
        epoch_batch: bool = True,
        ckpt_fragments: Optional[Sequence[str]] = None,
    ):
        self._specs = list(specs)
        self._epoch_batch = epoch_batch
        self.graph = GraphRuntime(
            self._specs, epoch_batch=epoch_batch
        ).start()
        self._sources = dict(source_map)
        self._out = out_fragment
        self._executors = list(ckpt_executors)
        # graph-fragment provenance of each ckpt executor (parallel to
        # ckpt_executors): lets partial recovery decide which fragments'
        # state a scoped rebuild must restore. None = unknown — scoped
        # intra-graph rebuild is then ineligible (full-graph rebuild,
        # still scoped at the runtime/MV level).
        if ckpt_fragments is not None and len(ckpt_fragments) != len(
            self._executors
        ):
            raise ValueError(
                "ckpt_fragments must parallel ckpt_executors "
                f"({len(ckpt_fragments)} vs {len(self._executors)})"
            )
        self._ckpt_fragments = (
            list(ckpt_fragments) if ckpt_fragments is not None else None
        )
        self.__dict__["_epoch_val"] = 0
        self._init_freshness()

    def rebuild(self, fragments: Optional[Sequence[str]] = None) -> None:
        """Replace dead actors: fresh threads + channels around the
        SAME executor instances (their state is restored separately by
        the runtime's recovery). The watchdog calls this before
        recover() when a graph-backed fragment fails.

        With ``fragments`` (a downstream-closed, source-free set from
        ``scoped_recovery_plan``), only that subtree is rebuilt: actors
        outside the blast radius keep their threads, channels, and live
        state — the fragment-scoped failover path."""
        if fragments:
            self.graph.rebuild_scoped(set(fragments))
            return
        try:
            self.graph.stop(timeout=1.0)
        except BaseException:
            pass  # a wedged/failed graph cannot block the rebuild
        self.graph = GraphRuntime(
            self._specs, epoch_batch=self._epoch_batch
        ).start()
        self.graph._epoch = self._epoch
        self.graph.capture_deltas = getattr(self, "_capture", False)

    # -- partial-recovery surface (the runtime's supervisor reads these)
    def failure_scope(self) -> Optional[Dict[str, object]]:
        """Structured view of the graph supervisor's failure state, or
        None while healthy: which fragments failed, the computed blast
        radius, and the per-actor errors."""
        g = self.graph
        if not getattr(g, "actor_errors", None):
            return None
        return {
            "failed_fragments": sorted(g.failed_fragments),
            "blast_radius": sorted(g.fenced_fragments),
            "errors": {a: repr(e) for a, e in g.actor_errors.items()},
        }

    def scoped_recovery_plan(self):
        """Decide how much of THIS pipeline a partial recovery must
        touch. Returns ``(graph_fragments, executors)``:

        - ``(blast, exs)`` — a scoped intra-graph rebuild is sound: only
          the blast radius's actors are rebuilt and only ``exs`` (its
          state tables) restore; actors outside keep running. Sound iff
          the blast excludes every source fragment, every STATEFUL
          fragment is inside it (replaying source data back through a
          live stateful fragment would double-apply), and every
          terminal fragment is inside it (otherwise the replay's output
          would be re-drained into subscribers).
        - ``(None, all_executors)`` — fall back to a full-graph rebuild
          (the MV as a whole still recovers scoped at the runtime
          level)."""
        full = (None, list(self._executors))
        g = self.graph
        blast = set(getattr(g, "fenced_fragments", ()) or ())
        if not blast or self._ckpt_fragments is None:
            return full
        sources = {s.name for s in self._specs if not s.inputs}
        consumed = {u for s in self._specs for (u, _p) in s.inputs}
        terminals = {s.name for s in self._specs if s.name not in consumed}
        stateful = {
            f
            for ex, f in zip(self._executors, self._ckpt_fragments)
            if isinstance(ex, Checkpointable)
        }
        if (
            (blast & sources)
            or not stateful <= blast
            or not terminals <= blast
        ):
            return full
        exs = [
            ex
            for ex, f in zip(self._executors, self._ckpt_fragments)
            if f in blast
        ]
        return set(blast), exs

    # the runtime assigns p._epoch on registration/recovery; keep the
    # actor graph's barrier clock in lockstep so injected epochs stay
    # monotonic relative to whatever the runtime restored
    @property
    def _epoch(self) -> int:
        return self.__dict__["_epoch_val"]

    @_epoch.setter
    def _epoch(self, v: int) -> None:
        self.__dict__["_epoch_val"] = v
        self.graph._epoch = v

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def executors(self) -> List[object]:
        return self._executors

    # -- message surface --------------------------------------------------
    def push(self, chunk: StreamChunk, start: int = 0) -> List[StreamChunk]:
        self._note_ingest()
        self.graph.inject_chunk(self._sources["single"], chunk)
        return []

    def push_left(self, chunk: StreamChunk) -> List[StreamChunk]:
        self._note_ingest()
        self.graph.inject_chunk(self._sources["left"], chunk)
        return []

    def push_right(self, chunk: StreamChunk) -> List[StreamChunk]:
        self._note_ingest()
        self.graph.inject_chunk(self._sources["right"], chunk)
        return []

    def watermark(self, column: str, value: int) -> List[StreamChunk]:
        self._note_watermark(value)
        self.graph.inject_watermark(column, value)
        return []  # flushed output surfaces at the next barrier drain

    def barrier(
        self, checkpoint: bool = True, epoch: Optional[int] = None
    ) -> List[StreamChunk]:
        t0 = time.perf_counter()
        target = self.barrier_nowait(checkpoint=checkpoint, epoch=epoch)
        outs = self.wait_barrier(target)
        self._sample_freshness((time.perf_counter() - t0) * 1e3)
        return outs

    # -- pipelined barriers (in-flight epochs, barrier/mod.rs:538) -------
    def barrier_nowait(
        self, checkpoint: bool = True, epoch: Optional[int] = None
    ) -> int:
        """Inject the barrier and return its epoch WITHOUT draining:
        pushes made after this belong to the next epoch while the
        actors are still flushing this one."""
        prev = self._epoch
        target = (
            epoch
            if epoch is not None
            else max(int(time.time() * 1000) << 16, prev + 1)
        )
        self._epoch = prev  # keep graph clock aligned before inject
        self.graph.inject_barrier_nowait(checkpoint=checkpoint, epoch=target)
        self.__dict__["_epoch_val"] = target
        return target

    def wait_barrier(self, epoch: int) -> List[StreamChunk]:
        """Block until every actor collected ``epoch``; drain what the
        terminal fragment emitted."""
        self.graph.wait_barrier(epoch)
        outs = self.graph.drain(self._out)
        # mesh observability: close this pipeline's per-shard window
        # (one matrix read + phase split; no-op unless armed AND this
        # graph carries sharded executors that were watched)
        if MESHPROF.enabled:
            MESHPROF.pipeline_barrier(self)
        return outs

    def set_capture(self, enabled: bool) -> None:
        """Actors seal checkpoint deltas at the barrier (pipelined
        checkpointing); survives ``rebuild``."""
        self._capture = enabled
        self.graph.capture_deltas = enabled

    def close(self) -> None:
        self.graph.stop()


# ---------------------------------------------------------------------------
# sharded (multi-chip) fragment mode: one actor per fragment, the
# parallelism INSIDE it — stacked state over a jax Mesh, vnode exchange
# via all_to_all under shard_map (parallel/sharded_*.py). Unlike the
# actor-parallel mode, no dispatch-column tracing is needed: every
# sharded op re-exchanges its input by its OWN keys on device.
# ---------------------------------------------------------------------------


class StackSplitExecutor(Executor):
    """Flat (cap,) chunk -> stacked (n, cap) chunk, shard i seeing rows
    i, i+n, i+2n... (round-robin source split). The downstream sharded
    op's on-device exchange re-routes rows by key vnode, so the split
    here only balances load."""

    def __init__(self, n_shards: int):
        self.n = n_shards

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        n = self.n
        idx = jnp.arange(chunk.valid.shape[-1], dtype=jnp.int32)
        valid = jnp.stack([chunk.valid & (idx % n == i) for i in range(n)])
        bcast = lambda a: jnp.broadcast_to(a[None], (n,) + a.shape)
        return [
            StreamChunk(
                columns={k: bcast(v) for k, v in chunk.columns.items()},
                valid=valid,
                nulls={k: bcast(v) for k, v in chunk.nulls.items()},
                ops=bcast(chunk.ops),
            )
        ]

    def lint_info(self):
        # layout-only boundary: same lanes in and out (schema threading
        # through sharded chains survives the stacking edge)
        return {}


class FlattenExecutor(Executor):
    """Stacked (n, cap) chunk -> flat (n*cap,) chunk (host boundary)."""

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        from risingwave_tpu.parallel.sharded_join import flatten_stacked

        if chunk.valid.ndim == 1:
            return [chunk]  # already flat (e.g. a sharded agg flush)
        return [flatten_stacked(chunk)]

    def lint_info(self):
        # layout-only boundary: same lanes in and out
        return {}


def _sharded_equiv(ex, mesh, stacked_out: bool = False):
    """Sharded replacement for a keyed single-chip executor, carrying
    the SAME table_id (the checkpoint is one logical table either
    way). None when the executor's features aren't sharded yet."""
    from risingwave_tpu.parallel.sharded_agg import ShardedHashAgg
    from risingwave_tpu.parallel.sharded_join import ShardedDedup

    if isinstance(ex, HashAggExecutor):
        if ex.window_key is not None or any(
            c.materialized for c in ex.calls
        ):
            return None
        return ShardedHashAgg(
            mesh,
            ex.group_keys,
            ex.calls,
            ex._dtypes,
            capacity=ex.table.capacity,
            out_cap=ex.out_cap,
            nullable_keys=tuple(
                k for k, nb in zip(ex.group_keys, ex.nullable) if nb
            ),
            table_id=ex.table_id,
            stacked_out=stacked_out,
        )
    if isinstance(ex, AppendOnlyDedupExecutor):
        if ex.window_key is not None:
            return None
        return ShardedDedup(
            mesh,
            ex.keys,
            {k: lane.dtype for k, lane in zip(ex.keys, ex.table.keys)},
            capacity=ex.table.capacity,
            table_id=ex.table_id,
        )
    from risingwave_tpu.executors.top_n_plain import (
        RetractableGroupTopNExecutor,
    )
    from risingwave_tpu.parallel.sharded_top_n import ShardedGroupTopN

    if isinstance(ex, RetractableGroupTopNExecutor):
        if ex.window_key is not None:
            return None
        return ShardedGroupTopN(
            mesh,
            ex.group_by,
            ex.order_col,
            ex.limit,
            ex.pk,
            {n: ex._dtypes[n] for n in ex.names},
            desc=ex.desc,
            capacity=ex.table.capacity,
            table_id=ex.table_id,
        )
    return None


def _shard_single_chain(chain, mesh):
    """chain -> sharded chain, or None when the shape can't shard:
    stateless* + ONE keyed (replaced by its sharded twin between
    StackSplit/Flatten) + anything (fed flat chunks as before)."""
    from risingwave_tpu.parallel.sharded_agg import ShardedHashAgg

    from risingwave_tpu.executors.row_id_gen import RowIdGenExecutor
    from risingwave_tpu.executors.top_n_plain import (
        RetractableGroupTopNExecutor,
    )
    from risingwave_tpu.parallel.sharded_top_n import ShardedGroupTopN

    keyed_idx = None
    for j, ex in enumerate(chain):
        if isinstance(ex, _KEYED + (RetractableGroupTopNExecutor,)):
            keyed_idx = j
            break
        # RowIdGen is safe here: the prefix runs FLAT, single-threaded,
        # BEFORE the StackSplit (ids stay globally unique) — unlike the
        # actor-parallel mode where per-instance generators would
        # collide
        if not isinstance(
            ex, _PARALLEL_STATELESS + (RowIdGenExecutor,)
        ):
            return None
    if keyed_idx is None:
        return None
    sharded = _sharded_equiv(chain[keyed_idx], mesh)
    if sharded is None:
        return None
    n = mesh.devices.size
    mid = [StackSplitExecutor(n), sharded]
    if not isinstance(sharded, (ShardedHashAgg, ShardedGroupTopN)):
        # dedup emits STACKED chunks from apply; GroupTopN/agg emit
        # host chunks at the barrier — only the former needs a flatten
        mid.append(FlattenExecutor())
    return list(chain[:keyed_idx]) + mid + list(chain[keyed_idx + 1 :])


def _shard_tail(tail, mesh, value_dtypes, value_nulls, capacity=None):
    """Replace a fixed-width materializer tail with a vnode-partitioned
    ``ShardedMaterialize`` (VERDICT r4 #6): Col-only projects stay
    stacked, the MV partitions by pk over the mesh, and a final Flatten
    keeps drained output flat for subscribers. ``value_dtypes`` /
    ``value_nulls`` describe the lanes arriving at the tail (from the
    upstream join or agg). Returns (tail_chain, sharded_mview) or None
    when the shape can't swap (nullable/unknown pk lane, non-Col
    projects, non-materializer tail)."""
    from risingwave_tpu.executors.materialize import (
        DeviceMaterializeExecutor,
        MaterializeExecutor,
    )
    from risingwave_tpu.parallel.sharded_mv import ShardedMaterialize

    if not tail:
        return None
    *pre, mat = tail
    for ex in pre:
        if not isinstance(ex, ProjectExecutor) or not all(
            isinstance(e, E.Col) for _n, e in ex.outputs
        ):
            return None
    renames: Dict[str, str] = {}  # output name -> source lane name
    for ex in pre:
        new = {n: renames.get(e.name, e.name) for n, e in ex.outputs}
        renames = new
    src_of = lambda n_: renames.get(n_, n_) if renames else n_
    if isinstance(mat, DeviceMaterializeExecutor):
        pk, columns = mat.pk, mat.columns
        dtypes = dict(mat.dtypes)
        nullable = tuple(mat.state.vnulls)
        capacity = mat.table.capacity
    elif isinstance(mat, MaterializeExecutor):
        pk, columns = mat.pk, mat.columns
        dtypes, nullable = {}, []
        for n_ in pk + columns:
            d = value_dtypes.get(src_of(n_))
            if d is None:
                return None
            dtypes[n_] = jnp.dtype(d)
            if src_of(n_) in value_nulls:
                if n_ in pk:
                    return None  # nullable pk: host-map executor only
                nullable.append(n_)
        nullable = tuple(nullable)
        # per-shard capacity follows the plan's sizing (the upstream
        # join/agg capacity), like every other sharded op
        capacity = capacity or (1 << 14)
    else:
        return None
    smv = ShardedMaterialize(
        mesh,
        pk,
        columns,
        dtypes,
        table_id=mat.table_id,
        capacity=capacity,
        nullable=nullable,
    )
    return list(pre) + [smv, FlattenExecutor()], smv


def sharded_planned_mv(planner_factory, sql: str, n_shards: int):
    """Plan ``sql`` and run it as SHARDED fragments over an n-device
    jax Mesh: keyed state stacked across devices, exchanges on ICI via
    all_to_all under shard_map — the multi-chip execution mode. Falls
    back to a single-actor graph when the shape can't shard."""
    from risingwave_tpu.parallel.sharded_agg import make_mesh
    from risingwave_tpu.parallel.sharded_join import ShardedHashJoin

    mesh = make_mesh(n_shards)
    proto = planner_factory().plan(sql)
    from risingwave_tpu.sql.planner import PlannedMV

    mview = proto.mview
    if isinstance(proto.pipeline, TwoInputPipeline):
        tp = proto.pipeline
        left = _shard_side_chain(tp.left, mesh)
        right = _shard_side_chain(tp.right, mesh)
        if left is None or right is None:
            gp = _two_input_graph([proto], None)
        else:
            join = tp.join
            sj = ShardedHashJoin(
                mesh,
                join.left_keys,
                join.right_keys,
                {n_: a.dtype for n_, a in join.left.rows.items()},
                {n_: a.dtype for n_, a in join.right.rows.items()},
                capacity=join.left.capacity,
                fanout=join.left.fanout,
                out_cap=join.out_cap,
                left_nullable=tuple(join.left.row_nulls),
                right_nullable=tuple(join.right.row_nulls),
                join_type=join.join_type,
                table_id=join.table_id,
            )
            tail = None
            if join.join_type == "inner":
                # outer joins append computed null lanes per emission
                # side — only inner emissions carry exactly the declared
                # nullable sets, so only those swap to the sharded MV
                out_dtypes = {
                    n_: a.dtype for n_, a in join.left.rows.items()
                }
                out_dtypes.update(
                    {n_: a.dtype for n_, a in join.right.rows.items()}
                )
                out_nulls = set(join.left.row_nulls) | set(
                    join.right.row_nulls
                )
                tail = _shard_tail(
                    tp.tail,
                    mesh,
                    out_dtypes,
                    out_nulls,
                    capacity=join.left.capacity,
                )
            if tail is None:
                tail_chain = [FlattenExecutor()] + list(tp.tail)
            else:
                tail_chain, mview = tail
            build = {
                "left": left,
                "right": right,
                "join": sj,
                "tail": tail_chain,
            }
            specs = [
                FragmentSpec("left_src", lambda i: []),
                FragmentSpec("right_src", lambda i: []),
                FragmentSpec(
                    "join",
                    lambda i, b=build: dict(b),
                    inputs=[("left_src", 0), ("right_src", 1)],
                ),
            ]
            ckpt = left + right + [sj] + build["tail"]
            gp = GraphPipeline(
                specs,
                {"left": "left_src", "right": "right_src"},
                "join",
                ckpt,
                ckpt_fragments=["join"] * len(ckpt),
            )
    else:
        chain = _shard_single_chain(list(proto.pipeline.executors), mesh)
        if chain is None:
            gp = _singleton_graph(list(proto.pipeline.executors))
        else:
            swapped = _shard_single_tail(chain, mesh)
            if swapped is not None:
                chain, mview = swapped
            specs = [FragmentSpec("mv", lambda i, c=tuple(chain): list(c))]
            gp = GraphPipeline(
                specs, {"single": "mv"}, "mv", chain,
                ckpt_fragments=["mv"] * len(chain),
            )
    return PlannedMV(
        proto.name, gp, mview, proto.inputs, schema=proto.schema
    )


def _shard_single_tail(chain, mesh):
    """After ``_shard_single_chain``, try to keep the MV sharded too:
    [..., ShardedHashAgg, (Flatten?), projects..., DeviceMaterialize]
    becomes [..., agg(stacked flush), projects..., ShardedMaterialize,
    Flatten]. Only the device materializer swaps here (its dtypes and
    null lanes are declared; the host-map executor's are inferred only
    on the join path). Returns (chain, mview) or None."""
    from risingwave_tpu.parallel.sharded_agg import ShardedHashAgg

    agg_idx = next(
        (
            j
            for j, ex in enumerate(chain)
            if isinstance(ex, ShardedHashAgg)
        ),
        None,
    )
    if agg_idx is None:
        return None
    rest = chain[agg_idx + 1 :]
    swapped = _shard_tail(rest, mesh, {}, set())
    if swapped is None:
        return None
    tail_chain, smv = swapped
    agg = chain[agg_idx]
    agg.stacked_out = True
    return list(chain[: agg_idx + 1]) + tail_chain, smv


def _shard_side_chain(chain, mesh):
    """A join side shards when it is stateless* + optional ONE keyed op
    (append-only dedup -> ShardedDedup; windowless non-materialized
    HashAgg -> ShardedHashAgg whose barrier flush stays STACKED and
    feeds the join directly — the q7 per-window-MAX side) + rename-only
    projects (element-wise on stacked chunks). Returns the sharded
    chain or None."""
    from risingwave_tpu.executors.row_id_gen import RowIdGenExecutor

    out = []
    seen_keyed = False
    for ex in chain:
        if isinstance(ex, _KEYED):
            if seen_keyed:
                return None
            # feature-check BEFORE building: _sharded_equiv allocates
            # mesh-stacked device state
            if isinstance(ex, HashAggExecutor):
                sharded = _sharded_equiv(ex, mesh, stacked_out=True)
            else:
                sharded = _sharded_equiv(ex, mesh)
            if sharded is None:
                return None
            seen_keyed = True
            out.append(StackSplitExecutor(mesh.devices.size))
            out.append(sharded)
        elif isinstance(ex, ProjectExecutor):
            if seen_keyed and not all(
                isinstance(e, E.Col) for _n, e in ex.outputs
            ):
                return None  # only renames are stacked-safe
            out.append(ex)
        elif isinstance(ex, (FilterExecutor, HopWindowExecutor)):
            if seen_keyed:
                return None  # pre-exchange ops only before the keyed op
            out.append(ex)
        elif isinstance(ex, RowIdGenExecutor):
            if seen_keyed:
                return None  # runs on flat host-side chunks only
            out.append(ex)
        else:
            return None
    if not seen_keyed:
        # stateless side: split right before the join's own exchange
        out.append(StackSplitExecutor(mesh.devices.size))
    return out


# ---------------------------------------------------------------------------
# fragment -> chain extraction (static analysis surface)
# ---------------------------------------------------------------------------


def fragment_chains(pipeline) -> Dict[str, Dict[str, List[object]]]:
    """Normalize ANY pipeline shape into ``{fragment: {section:
    executor chain}}`` for static analysis (plan verifier / fusion
    analyzer). Sections name the input side feeding the chain:
    ``single``/``left``/``right`` (source-fed — the analyzer can seed
    an abstract schema), ``join_tail`` (the join executor + tail of a
    two-input shape), or ``chain`` (a graph fragment fed by other
    fragments — schema threads through lint_info, not sources).

    GraphPipeline fragments are SHADOW-built (``spec.build(0)``) on the
    host device only to read static metadata — the live actors hold
    their own executors; nothing here touches HBM or actor state."""
    if hasattr(pipeline, "_specs") and hasattr(pipeline, "graph"):
        from risingwave_tpu.analysis.plan_verifier import _host_device

        out: Dict[str, Dict[str, List[object]]] = {}
        frag_side = {
            frag: side for side, frag in pipeline._sources.items()
        }
        for s in pipeline._specs:
            try:
                with _host_device():
                    built = s.build(0)
            except Exception:  # noqa: BLE001 — builder needs live inputs
                built = None
            if isinstance(built, dict):
                out[s.name] = {
                    "left": list(built.get("left", ())),
                    "right": list(built.get("right", ())),
                    "join_tail": (
                        [built["join"]]
                        if built.get("join") is not None
                        else []
                    )
                    + list(built.get("tail", ())),
                }
            elif isinstance(built, (list, tuple)):
                side = frag_side.get(s.name)
                key = side or ("single" if not s.inputs else "chain")
                out[s.name] = {key: list(built)}
            else:
                out[s.name] = {}
        return out
    if hasattr(pipeline, "join") and hasattr(pipeline, "left"):
        return {
            "left": {"left": list(pipeline.left)},
            "right": {"right": list(pipeline.right)},
            "out": {
                "join_tail": [pipeline.join] + list(pipeline.tail)
            },
        }
    if hasattr(pipeline, "executors"):
        return {"mv": {"single": list(pipeline.executors)}}
    return {}


def is_mesh_executor(ex) -> bool:
    """True for mesh-resident executors (those declaring a
    ``mesh_contract()``) — the sharded ops the mesh analyzer proves."""
    return callable(getattr(ex, "mesh_contract", None))


def is_mesh_boundary(ex) -> bool:
    """True for the host-routing stack/flatten boundary executors — the
    edges where rows cross between flat host chunks and the stacked
    mesh layout (the RW-E901 exchange edges a fully SPMD fragment would
    absorb into its program)."""
    return isinstance(ex, (StackSplitExecutor, FlattenExecutor))


def sharded_chains(pipeline) -> Dict[str, Dict[str, List[object]]]:
    """``fragment_chains`` restricted to the SHARDED fragments: those
    whose chains contain at least one mesh-resident executor (or one of
    the stack/flatten boundary adapters feeding it). This is the mesh
    analyzer's extraction surface — per fragment, per section, the
    executor chain with the mesh ops and their host boundaries in
    source order."""
    out: Dict[str, Dict[str, List[object]]] = {}
    for frag, sections in fragment_chains(pipeline).items():
        kept = {
            sec: list(chain)
            for sec, chain in sections.items()
            if any(
                is_mesh_executor(e) or is_mesh_boundary(e) for e in chain
            )
        }
        if kept:
            out[frag] = kept
    return out


# ---------------------------------------------------------------------------
# planner output -> fragment graph
# ---------------------------------------------------------------------------


def graph_planned_mv(
    planner_factory, sql: str, parallelism: int = 1, epoch_batch: bool = True
):
    """Plan ``sql`` once per instance with FRESH planners (identical,
    deterministic table_ids across instances — they are partitions of
    the same logical tables) and return a PlannedMV whose pipeline is a
    GraphPipeline. Shapes that cannot partition fall back to a
    single-actor graph — same SQL, same results, still actors."""
    n = max(1, parallelism)
    proto = planner_factory().plan(sql)
    if getattr(proto, "aux", ()):
        # lowered multi-MV plans (nested joins / decorrelated scalar
        # subqueries) are wired through runtime subscription edges; the
        # actor-graph wrapper would drop the aux list — run them serial
        return proto
    # decide partitionability on the prototype BEFORE paying for N-1
    # more planner passes — a non-partitionable shape falls back to a
    # single-actor graph using only the prototype
    if isinstance(proto.pipeline, TwoInputPipeline):
        sides = _split_join(proto.pipeline) if n > 1 else None
        plans = (
            [proto] + [planner_factory().plan(sql) for _ in range(n - 1)]
            if sides is not None
            else [proto]
        )
        gp = _two_input_graph(plans, sides, epoch_batch=epoch_batch)
    else:
        split = (
            _split_single(list(proto.pipeline.executors)) if n > 1 else None
        )
        plans = (
            [proto] + [planner_factory().plan(sql) for _ in range(n - 1)]
            if split is not None
            else [proto]
        )
        gp = _single_graph(plans, split, epoch_batch=epoch_batch)
    from risingwave_tpu.sql.planner import PlannedMV

    return PlannedMV(
        proto.name, gp, proto.mview, proto.inputs, schema=proto.schema
    )


def _singleton_graph(chain, source_map_side="single", epoch_batch=True):
    name = "mv"
    specs = [FragmentSpec(name, lambda i, ch=tuple(chain): list(ch))]
    return GraphPipeline(
        specs, {source_map_side: name}, name, list(chain),
        epoch_batch=epoch_batch,
        ckpt_fragments=[name] * len(chain),
    )


def _single_graph(plans, split, epoch_batch=True) -> GraphPipeline:
    chains = [list(p.pipeline.executors) for p in plans]
    chain0 = chains[0]
    n = len(plans)

    if split is None or n == 1:
        return _singleton_graph(chain0, epoch_batch=epoch_batch)
    prefix_len, dispatch_cols, positions_by_idx = split

    specs = [
        FragmentSpec(
            "src", lambda i: [], dispatch=("hash", list(dispatch_cols))
        ),
        FragmentSpec(
            "par",
            lambda i: list(chains[i][:prefix_len]),
            inputs=[("src", 0)],
            parallelism=n,
        ),
        FragmentSpec(
            "mat",
            lambda i: list(chain0[prefix_len:]),
            inputs=[("par", 0)],
        ),
    ]
    ckpt: List[object] = []
    frags: List[str] = []
    for j in range(prefix_len):
        ex0 = chain0[j]
        if isinstance(ex0, Checkpointable):
            ckpt.append(
                PartitionedStateView(
                    [chains[i][j] for i in range(n)], positions_by_idx[j]
                )
            )
            frags.append("par")
    ckpt.extend(chain0[prefix_len:])
    frags.extend(["mat"] * len(chain0[prefix_len:]))
    return GraphPipeline(
        specs, {"single": "src"}, "mat", ckpt, epoch_batch=epoch_batch,
        ckpt_fragments=frags,
    )


def _split_single(chain):
    """Find the parallel prefix of a single-input chain: stateless ops
    up to and including the FIRST keyed stateful executor. Returns
    (prefix_len, dispatch source cols, {chain idx -> {table_id ->
    positions}}) or None when the shape cannot partition."""
    keyed_idx = None
    for j, ex in enumerate(chain):
        if isinstance(ex, _KEYED):
            keyed_idx = j
            break
        if not isinstance(ex, _PARALLEL_STATELESS):
            return None
    if keyed_idx is None:
        return None
    keyed = chain[keyed_idx]
    keys = _keys_of(keyed)
    before = chain[:keyed_idx]
    dispatch, lanes = [], []
    for pos, k in enumerate(keys):
        src = _trace_source_col(before, k)
        lane = _key_lane_index(keyed, pos)
        if src is not None and lane is not None:
            dispatch.append(src)
            lanes.append(lane)
    if not dispatch:
        return None
    positions = {
        keyed_idx: {
            tid: tuple(lanes) for tid in keyed.checkpoint_table_ids()
        }
    }
    return keyed_idx + 1, dispatch, positions


def _two_input_graph(plans, sides, epoch_batch=True) -> GraphPipeline:
    tp0 = plans[0].pipeline
    n = len(plans)
    if sides is None or n == 1:
        build = {
            "left": tp0.left,
            "right": tp0.right,
            "join": tp0.join,
            "tail": tp0.tail,
        }
        specs = [
            FragmentSpec("left_src", lambda i: []),
            FragmentSpec("right_src", lambda i: []),
            FragmentSpec(
                "join",
                lambda i, b=build: dict(b),
                inputs=[("left_src", 0), ("right_src", 1)],
            ),
        ]
        return GraphPipeline(
            specs,
            {"left": "left_src", "right": "right_src"},
            "join",
            tp0.executors,
            epoch_batch=epoch_batch,
            ckpt_fragments=["join"] * len(tp0.executors),
        )
    ldisp, rdisp, join_positions, side_positions = sides

    def build_join(i):
        tp = plans[i].pipeline
        return {
            "left": tp.left,
            "right": tp.right,
            "join": tp.join,
            "tail": [],
        }

    specs = [
        FragmentSpec(
            "left_src", lambda i: [], dispatch=("hash", list(ldisp))
        ),
        FragmentSpec(
            "right_src", lambda i: [], dispatch=("hash", list(rdisp))
        ),
        FragmentSpec(
            "join",
            build_join,
            inputs=[("left_src", 0), ("right_src", 1)],
            parallelism=n,
        ),
        FragmentSpec("mat", lambda i: list(tp0.tail), inputs=[("join", 0)]),
    ]
    ckpt: List[object] = []
    frags: List[str] = []
    for side_name in ("left", "right"):
        chain0 = getattr(tp0, side_name)
        for j, ex0 in enumerate(chain0):
            if isinstance(ex0, Checkpointable):
                ckpt.append(
                    PartitionedStateView(
                        [getattr(plans[i].pipeline, side_name)[j] for i in range(n)],
                        side_positions[(side_name, j)],
                    )
                )
                frags.append("join")
    ckpt.append(
        PartitionedStateView(
            [plans[i].pipeline.join for i in range(n)], join_positions
        )
    )
    frags.append("join")
    ckpt.extend(tp0.tail)
    frags.extend(["mat"] * len(tp0.tail))
    return GraphPipeline(
        specs,
        {"left": "left_src", "right": "right_src"},
        "mat",
        ckpt,
        epoch_batch=epoch_batch,
        ckpt_fragments=frags,
    )


def _split_join(tp):
    """Partitionability of a two-input join fragment. Returns
    (left dispatch cols, right dispatch cols, join table positions,
    {(side, idx) -> table positions}) or None."""
    join = tp.join
    lkeys = tuple(join.left_keys)
    rkeys = tuple(join.right_keys)
    ldisp, rdisp, jpos = [], [], []
    for p in range(len(lkeys)):
        ls = _trace_source_col(tp.left, lkeys[p])
        rs = _trace_source_col(tp.right, rkeys[p])
        if ls is not None and rs is not None:
            ldisp.append(ls)
            rdisp.append(rs)
            jpos.append(p)
    if not jpos:
        return None
    # every side executor must be either parallel-safe stateless or a
    # keyed stateful whose key tuple covers the side's dispatch columns
    side_positions: Dict[Tuple[str, int], Dict[str, Tuple[int, ...]]] = {}
    for side_name, disp in (("left", ldisp), ("right", rdisp)):
        chain = getattr(tp, side_name)
        for j, ex in enumerate(chain):
            if isinstance(ex, _PARALLEL_STATELESS):
                continue
            if isinstance(ex, _KEYED):
                pos = _view_positions(chain[:j], ex, disp)
                if pos is None:
                    return None
                side_positions[(side_name, j)] = {
                    tid: pos for tid in ex.checkpoint_table_ids()
                }
                continue
            return None
    tid = join.table_id
    join_positions = {
        f"{tid}.left": tuple(jpos),
        f"{tid}.right": tuple(jpos),
    }
    return ldisp, rdisp, join_positions, side_positions
