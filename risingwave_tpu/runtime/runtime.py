"""StreamingRuntime — the meta-lite control plane for one process.

Reference roles replaced:
- ``GlobalBarrierManager`` event loop + ``ScheduledBarriers`` min-
  interval tick (src/meta/src/barrier/mod.rs:532, barrier/schedule.rs:348);
- ``CheckpointControl`` in-flight epoch tracking + ``complete_barrier``
  -> ``HummockManager::commit_epoch`` (barrier/mod.rs:845);
- the async uploader overlapping checkpoint IO with the next epoch's
  compute (src/storage/src/hummock/event_handler/uploader.rs:548);
- recovery from max_committed_epoch (barrier/recovery.rs:353).

TPU re-design: fragments are host-driven pipelines over device state,
so the runtime is a synchronous epoch clock plus an ASYNC checkpoint
lane: at a checkpoint barrier the runtime stages every executor's
delta (the only device-touching step, O(changed rows) and mark flips
happen HERE, on the main thread), then hands SST build + upload +
manifest commit to a background worker that preserves epoch order. A
worker failure is fatal for live state (marks are already flipped):
the next barrier raises and the driver must recover() from the last
durable manifest — the reference's failed-barrier recovery contract.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.metrics import REGISTRY
from risingwave_tpu.storage.object_store import ObjectStore
from risingwave_tpu.storage.sstable import build_sst
from risingwave_tpu.storage.state_table import Checkpointable, CheckpointManager


class StreamingRuntime:
    """Owns fragments (pipelines), the barrier clock, and checkpoints.

    Args:
      store: object store for checkpoints (None = no persistence).
      barrier_interval_ms: the reference's ``barrier_interval_ms``
        system param (default 1000) — used by ``tick()`` pacing.
      checkpoint_frequency: every Nth barrier is a checkpoint
        (system_param/mod.rs:78).
      async_checkpoint: overlap SST build/upload with the next epochs'
        compute (uploader analogue). ``wait_checkpoints()`` joins.
    """

    @classmethod
    def from_config(cls, cfg, store: Optional[ObjectStore] = None):
        """Build from an RwConfig (config.rs load path): the system
        params drive the barrier clock; storage config drives the
        store root + compaction cadence."""
        from risingwave_tpu.storage.object_store import LocalFsObjectStore

        if store is None:
            store = LocalFsObjectStore(cfg.storage.object_store_root)
        return cls(
            store,
            barrier_interval_ms=cfg.system.barrier_interval_ms,
            checkpoint_frequency=cfg.system.checkpoint_frequency,
            compact_at=cfg.storage.compact_at,
        )

    def __init__(
        self,
        store: Optional[ObjectStore] = None,
        barrier_interval_ms: int = 1000,
        checkpoint_frequency: int = 1,
        async_checkpoint: bool = True,
        compact_at: int = 8,
    ):
        self.fragments: Dict[str, object] = {}
        self._aux_state: List[object] = []
        self.barrier_interval_ms = barrier_interval_ms
        self.checkpoint_frequency = checkpoint_frequency
        self.mgr = (
            CheckpointManager(store, compact_at=compact_at)
            if store is not None
            else None
        )
        self.async_checkpoint = async_checkpoint
        self._epoch = self.mgr.max_committed_epoch if self.mgr else 0
        self._barrier_seq = 0
        self._last_barrier_at = 0.0
        self.barrier_latencies_ms: List[float] = []
        self._worker: Optional[threading.Thread] = None
        self._work_q: deque = deque()
        self._work_event = threading.Event()
        self._work_err: List[BaseException] = []
        self._inflight = 0
        self._inflight_lock = threading.Lock()

    # -- fragments -------------------------------------------------------
    def register(self, name: str, pipeline) -> None:
        self.fragments[name] = pipeline

    def register_state(self, obj) -> None:
        """Register a non-pipeline Checkpointable (e.g. a source's
        split offsets) into the checkpoint/recovery cycle."""
        self._aux_state.append(obj)

    def executors(self) -> List[object]:
        out = []
        for p in self.fragments.values():
            out.extend(p.executors)
        out.extend(self._aux_state)
        return out

    # -- barrier clock ---------------------------------------------------
    @property
    def epoch(self) -> int:
        return self._epoch

    def next_epoch(self) -> int:
        return max(int(time.time() * 1000) << 16, self._epoch + 1)

    def barrier(self) -> Dict[str, List[StreamChunk]]:
        """Inject one barrier into every fragment; commit a checkpoint
        every ``checkpoint_frequency``-th barrier. Returns each
        fragment's emitted chunks."""
        t0 = time.perf_counter()
        prev, self._epoch = self._epoch, self.next_epoch()
        self._barrier_seq += 1
        is_ckpt = (
            self.mgr is not None
            and self._barrier_seq % self.checkpoint_frequency == 0
        )
        outs = {}
        for name, p in self.fragments.items():
            p._epoch = prev  # fragments share the runtime's clock
            # non-checkpoint barriers must NOT commit sinks (exactly-
            # once: sink commits may never run ahead of durability)
            outs[name] = p.barrier(checkpoint=is_ckpt)
            p._epoch = self._epoch
        if is_ckpt:
            self._commit(self._epoch)
        ms = (time.perf_counter() - t0) * 1e3
        self.barrier_latencies_ms.append(ms)
        REGISTRY.histogram("barrier_latency_ms").observe(ms)
        REGISTRY.counter("barriers_total").inc()
        return outs

    def tick(self) -> bool:
        """Barrier iff ``barrier_interval_ms`` elapsed since the last
        one (ScheduledBarriers min-interval tick). Returns whether a
        barrier fired."""
        now = time.time()
        if (now - self._last_barrier_at) * 1000 < self.barrier_interval_ms:
            return False
        self._last_barrier_at = now
        self.barrier()
        return True

    def p99_barrier_ms(self) -> float:
        if not self.barrier_latencies_ms:
            return 0.0
        return float(np.percentile(self.barrier_latencies_ms, 99))

    # -- checkpoint lane -------------------------------------------------
    def _commit(self, epoch: int) -> None:
        self._raise_worker_error()
        if not self.async_checkpoint:
            self.mgr.commit_epoch(epoch, self.executors())
            return
        # stage synchronously on the main thread (device pull + eager
        # mark flips), upload asynchronously
        staged = []
        for ex in self.executors():
            if isinstance(ex, Checkpointable):
                staged.extend(ex.checkpoint_delta())
        with self._inflight_lock:
            self._inflight += 1
        self._work_q.append((epoch, staged))
        self._ensure_worker()
        self._work_event.set()

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._worker_loop, daemon=True
            )
            self._worker.start()

    def _worker_loop(self):
        while True:
            self._work_event.wait(timeout=0.5)
            self._work_event.clear()
            while self._work_q:
                epoch, staged = self._work_q.popleft()
                try:
                    self._upload_epoch(epoch, staged)
                except BaseException as e:  # surfaced on main thread
                    self._work_err.append(e)
                finally:
                    with self._inflight_lock:
                        self._inflight -= 1

    def _upload_epoch(self, epoch: int, staged) -> None:
        """Worker-side: SSTs + manifest, in epoch order (the queue is
        FIFO and single-worker, so order holds)."""
        mgr = self.mgr
        tables = mgr.version["tables"]
        for delta in staged:
            if len(delta.tombstone) == 0:
                continue
            blob = build_sst(
                delta.table_id,
                epoch,
                delta.key_cols,
                delta.value_cols,
                delta.tombstone,
                delta.key_order,
            )
            path = f"{mgr.prefix}/sst/{delta.table_id}/{epoch:020d}.sst"
            mgr.store.put(path, blob)
            tables.setdefault(delta.table_id, []).append(
                {"path": path, "epoch": epoch}
            )
        mgr.version["max_committed_epoch"] = epoch
        mgr._persist_version()
        mgr._maybe_compact(epoch)

    def wait_checkpoints(self) -> None:
        """Join the async lane (the FLUSH / sync-epoch analogue)."""
        while True:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.002)
        self._raise_worker_error()

    def _raise_worker_error(self):
        if self._work_err:
            raise RuntimeError(
                "async checkpoint failed"
            ) from self._work_err[0]

    # -- recovery --------------------------------------------------------
    def recover(self) -> None:
        """Rebuild all fragment state from the last committed epoch."""
        if not self.mgr:
            raise RuntimeError("no object store configured")
        self.mgr.recover(self.executors())
        self._epoch = self.mgr.max_committed_epoch
        for p in self.fragments.values():
            p._epoch = self._epoch
