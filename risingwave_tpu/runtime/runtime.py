"""StreamingRuntime — the meta-lite control plane for one process.

Reference roles replaced:
- ``GlobalBarrierManager`` event loop + ``ScheduledBarriers`` min-
  interval tick (src/meta/src/barrier/mod.rs:532, barrier/schedule.rs:348);
- ``CheckpointControl`` in-flight epoch tracking + ``complete_barrier``
  -> ``HummockManager::commit_epoch`` (barrier/mod.rs:845);
- the async uploader overlapping checkpoint IO with the next epoch's
  compute (src/storage/src/hummock/event_handler/uploader.rs:548);
- recovery from max_committed_epoch (barrier/recovery.rs:353).

TPU re-design: fragments are host-driven pipelines over device state,
so the runtime is a synchronous epoch clock plus an ASYNC checkpoint
lane: at a checkpoint barrier the runtime stages every executor's
delta (the only device-touching step, O(changed rows) and mark flips
happen HERE, on the main thread), then hands SST build + upload +
manifest commit to a background worker that preserves epoch order. A
worker failure is fatal for live state (marks are already flipped):
the next barrier raises and the driver must recover() from the last
durable manifest — the reference's failed-barrier recovery contract.

Partial recovery departs from that contract where it can: an ACTOR
death is attributed to its fragment by the graph supervisor
(runtime/graph.py), and ``_auto_recover`` restores + replays ONLY the
blast radius (failed fragments + transitive subscribers) from a
per-fragment replay buffer of uncommitted inputs — healthy fragments
keep their live state and keep answering queries. Stop-the-world
recovery remains the floor: unattributable failures, whole-runtime
blasts, lost replay windows, and three consecutive failed partials all
fall back to it (and three consecutive fulls raise).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu import blackbox
from risingwave_tpu import utils_sync_point as sync_point
from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.epoch_trace import EpochTrace, chunk_nbytes, dump_stalls
from risingwave_tpu.event_log import EVENT_LOG
from risingwave_tpu.freshness import FRESHNESS, attribute_backpressure
from risingwave_tpu.metrics import REGISTRY
from risingwave_tpu.resilience import (
    STORE_UNAVAILABLE,
    CircuitBreaker,
    DeltaSpill,
    RetryingObjectStore,
    RetryPolicy,
)
from risingwave_tpu.profiler import PROFILER
from risingwave_tpu.trace import span
from risingwave_tpu.storage.object_store import ObjectStore
from risingwave_tpu.storage.state_table import CheckpointManager


class StreamingRuntime:
    """Owns fragments (pipelines), the barrier clock, and checkpoints.

    Args:
      store: object store for checkpoints (None = no persistence).
      barrier_interval_ms: the reference's ``barrier_interval_ms``
        system param (default 1000) — used by ``tick()`` pacing.
      checkpoint_frequency: every Nth barrier is a checkpoint
        (system_param/mod.rs:78).
      async_checkpoint: overlap SST build/upload with the next epochs'
        compute (uploader analogue). ``wait_checkpoints()`` joins.
    """

    @classmethod
    def from_config(cls, cfg, store: Optional[ObjectStore] = None):
        """Build from an RwConfig (config.rs load path): the system
        params drive the barrier clock; storage config drives the
        store root + compaction cadence."""
        from risingwave_tpu.storage.object_store import LocalFsObjectStore

        if store is None:
            store = LocalFsObjectStore(cfg.storage.object_store_root)
        res = getattr(cfg, "resilience", None)
        retry_policy = breaker = None
        if res is not None:
            retry_policy = RetryPolicy.from_env(
                max_attempts=res.retry_max_attempts,
                base_backoff_s=res.retry_base_backoff_ms / 1e3,
                max_backoff_s=res.retry_max_backoff_ms / 1e3,
                deadline_s=res.retry_deadline_s,
            )
            breaker = CircuitBreaker.from_env(
                "object_store",
                failure_threshold=res.breaker_threshold,
                cooldown_s=res.breaker_cooldown_s,
            )
        prof = getattr(cfg, "profiler", None)
        if prof is not None:
            # [profiler] section arms the dispatch-wall profiler for
            # the process (env RW_PROFILE_* wins inside configure)
            PROFILER.configure(prof)
        bb = getattr(cfg, "blackbox", None)
        if bb is not None:
            # [blackbox] section arms the flight recorder's segment
            # persistence and/or the device sentinel (env wins inside)
            blackbox.configure(bb)
        return cls(
            store,
            barrier_interval_ms=cfg.system.barrier_interval_ms,
            checkpoint_frequency=cfg.system.checkpoint_frequency,
            compact_at=cfg.storage.compact_at,
            retry_policy=retry_policy,
            breaker=breaker,
        )

    def __init__(
        self,
        store: Optional[ObjectStore] = None,
        barrier_interval_ms: int = 1000,
        checkpoint_frequency: int = 1,
        async_checkpoint: bool = True,
        compact_at: int = 8,
        memory_budget_bytes: Optional[int] = None,
        auto_recover: bool = False,
        in_flight_barriers: int = 1,
        retry_policy: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
        degraded_dir: Optional[str] = None,
    ):
        # failure detection + self-healing (barrier/mod.rs:676-710 +
        # recovery.rs:353): a poisoned epoch or dead actor surfacing at
        # the barrier triggers recovery WITHOUT caller intervention —
        # rebuild actor graphs, restore state from the last committed
        # epoch, roll source offsets back so the pump replays
        self.auto_recover = auto_recover
        self.auto_recoveries = 0
        # RW_PROFILE env arming must work on EVERY construction path
        # (serve without --config, compute_node, direct construction),
        # not only from_config; a no-op when the env var is unset
        PROFILER.from_env()
        # same contract for the black box (RW_BLACKBOX_*)
        blackbox.from_env()
        # recompile-storm governor (runtime/bucketing.py): per-barrier
        # SignatureWatch hazard deltas vs RW_FUSION_RECOMPILE_BUDGET;
        # over budget (or ANY hazard while the device sentinel reports
        # SLOW) pins the offending executors to their max bucket. Own
        # instance per runtime — pin state never leaks across runtimes.
        from risingwave_tpu.runtime.bucketing import ShapeGovernor

        self.shape_governor = ShapeGovernor()
        # HBM memory governor + overload ladder (runtime/
        # memory_governor.py): global device-state ledger enforcing
        # RW_HBM_BUDGET_BYTES via BucketAllocator grow vetoes + cold-
        # tier spill, credit-based source admission, and the NORMAL ->
        # THROTTLED -> SHEDDING -> DEGRADED ladder. Dormant (one
        # attribute check per barrier) unless a budget or
        # RW_OVERLOAD_LADDER arms it. Own instance per runtime.
        from risingwave_tpu.runtime.memory_governor import MemoryGovernor

        self.memory_governor = MemoryGovernor()
        # the admission controller is the governor's: SourceManager
        # attaches to THIS to have its polls credit-clamped
        self.admission = self.memory_governor.admission
        # RW_SHAPE_WATCH_WARMUP=<N>: arm SignatureWatch from construction
        # and mark it stable after N barriers — the env-only way to run
        # the governor hot in production/soak without code changes
        self._shape_watch_warmup = 0
        try:
            self._shape_watch_warmup = int(
                os.environ.get("RW_SHAPE_WATCH_WARMUP", "0")
            )
        except ValueError:
            pass
        if self._shape_watch_warmup > 0:
            from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES

            if SIGNATURES.enabled:
                # another runtime (or the bench harness) already owns
                # the process-global watch: starting it again would
                # wipe the legitimized shape set mid-run and mint
                # false hazards — this runtime stands down from watch
                # management (its governor still consumes deltas)
                self._shape_watch_warmup = 0
            else:
                # pipelined runtimes admit barrier N while epochs
                # N-k..N-1 are still executing in the closer lane:
                # stretch warmup by the in-flight depth so mark_stable
                # only fires once every warmup epoch has actually run
                # (admission control proves barrier N closed before
                # N+k is admitted)
                self._shape_watch_warmup += max(0, in_flight_barriers - 1)
                SIGNATURES.start()
        # state >> HBM control (the reference's LRU memory controller,
        # src/compute/src/memory/controller.rs role): when accounted
        # device state exceeds the budget, fully-durable groups are
        # evicted to the object store and fold back on next touch
        self.memory_budget_bytes = memory_budget_bytes
        # heap profiling (/heap): this runtime's executors feed the
        # device-state half of the report (utils_heap, jeprof analogue)
        from risingwave_tpu import utils_heap

        utils_heap.attach_runtime(self)
        # shared arrangements (runtime/arrangements.py): the registry
        # of refcounted device indexes serving N structurally-identical
        # MVs off one writer fragment; the barrier publishes a version
        # per arrangement (one attribute check when nothing is shared)
        from risingwave_tpu.runtime.arrangements import ArrangementRegistry

        self.arrangements = ArrangementRegistry(self)
        # monotonic write counter: every chunk entering ANY fragment
        # bumps it, so a published arrangement version can prove the
        # live state still sits at its barrier boundary (lazy snapshot
        # materialization without a torn-read window)
        self._write_gen = 0
        self.fragments: Dict[str, object] = {}
        # upstream -> [(downstream, side)]; side targets one input of a
        # two-input fragment ("left"/"right") or "single"
        self._subs: Dict[str, List[Tuple[str, str]]] = {}
        self._aux_state: List[object] = []
        self.barrier_interval_ms = barrier_interval_ms
        self.checkpoint_frequency = checkpoint_frequency
        # the durability boundary is retry-wrapped and breaker-gated
        # (resilience.py): transient store faults are absorbed by
        # backoff; a hard-down store opens the breaker and the runtime
        # DEGRADES instead of dying — queries keep answering from
        # live/HBM state, checkpoint deltas spill locally, compaction
        # pauses, and the spill replays when the breaker half-opens.
        if store is not None:
            if isinstance(store, RetryingObjectStore):
                if store.breaker is None:
                    # a breaker-less pre-wrapped store (e.g. bare
                    # store.resilient()) would make degraded-mode
                    # restore probes unthrottled — every barrier would
                    # pay the full retry deadline against a down store.
                    # The runtime REQUIRES the cooldown gate: attach one.
                    store.breaker = breaker or CircuitBreaker.from_env(
                        "object_store"
                    )
                self.store_breaker = store.breaker
            else:
                self.store_breaker = breaker or CircuitBreaker.from_env(
                    "object_store"
                )
                store = RetryingObjectStore(
                    store,
                    retry_policy or RetryPolicy.from_env(),
                    self.store_breaker,
                )
        else:
            self.store_breaker = None
        self.mgr = (
            CheckpointManager(store, compact_at=compact_at)
            if store is not None
            else None
        )
        # degraded-mode checkpointing state (guarded by _degraded_lock:
        # the async worker and the barrier thread both touch it)
        self._degraded = False
        self._degraded_lock = threading.Lock()
        self._spill = DeltaSpill(degraded_dir)
        # a persistent RW_DEGRADED_DIR can hold a PREVIOUS incarnation's
        # spill: those epochs rolled back with that process (sources
        # replay their data after recovery) — replaying them here would
        # at best trip the manifest's epoch guard and at worst
        # double-apply. Stale on arrival; discard.
        stale = self._spill.discard_all()
        if stale:
            EVENT_LOG.record("degraded_discard", epochs=stale, at="boot")
        self.async_checkpoint = async_checkpoint
        # -- partial recovery (fragment-scoped failover) ----------------
        # per-fragment replay buffer of UNCOMMITTED inputs: every chunk
        # entering a fragment (driver push, MV-on-MV routed delta,
        # backfill) plus per-fragment barrier markers. A scoped recovery
        # restores only the blast radius's state tables from the last
        # committed checkpoint and replays this log into the rebuilt
        # subtree — healthy fragments never roll back. Pruned as epochs
        # become durable; a fragment whose log overflows re-anchors at
        # the next barrier (replay floor) and is full-recovery-only
        # until the anchor epoch is durable.
        self._replay: Dict[str, List[tuple]] = {}
        # fragment -> lowest epoch the log can replay from (0 = any
        # committed state; None = window lost, re-anchors at the next
        # barrier marker)
        self._replay_floor: Dict[str, Optional[int]] = {}
        # fragment -> last durable epoch whose STAGING included this
        # fragment. Usually the global committed epoch, but a fragment
        # fenced for a deferred recovery is excluded from staging, so
        # healthy-only commits advance the manifest WITHOUT covering it
        # — pruning or replay-skipping by the global epoch would then
        # silently drop its un-durable window
        self._replay_covered: Dict[str, int] = {}
        self._replay_lock = threading.Lock()
        import os as _os

        try:
            self._replay_cap = int(
                _os.environ.get("RW_REPLAY_BUFFER_EVENTS", "4096")
            )
        except ValueError:
            self._replay_cap = 4096
        # deferred partial recovery (store unavailable mid-recovery):
        # the blast radius stays fenced — skipped by barriers, its
        # inputs parked in the replay buffer — until the breaker lets a
        # restore probe through (composes with degraded mode)
        self._pending_partial: Optional[Dict[str, object]] = None
        self._consecutive_partials = 0
        self._consecutive_recoveries = 0
        # "partial" | "full" | None — chaos pumps read this to decide
        # whether the failed epoch's data was replayed (partial) or
        # rolled back with everything else (full: re-feed / re-poll)
        self.last_recovery_mode: Optional[str] = None
        self.partial_recoveries = 0
        self._epoch = self.mgr.max_committed_epoch if self.mgr else 0
        self._barrier_seq = 0
        self._last_barrier_at = 0.0
        self.barrier_latencies_ms: List[float] = []
        self.checkpoint_sync_ms: List[float] = []  # stage->durable, per ckpt
        self._worker: Optional[threading.Thread] = None
        self._work_q: deque = deque()
        self._work_event = threading.Event()
        self._work_err: List[BaseException] = []
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._compactor: Optional[threading.Thread] = None
        self._compact_event = threading.Event()
        self._compact_pause = threading.Event()  # set = paused (recovery)
        self._compact_idle = threading.Event()
        self._compact_idle.set()
        self.compaction_errors: List[BaseException] = []
        self._work_abort = threading.Event()
        # pipelined barriers (barrier/mod.rs:538 in_flight_barrier_nums):
        # barrier() returns at ADMISSION (inject only); a closer thread
        # waits for collection, stages the actor-sealed deltas, and
        # feeds the async commit lane — up to ``in_flight_barriers``
        # epochs overlap. Requires graph-backed fragments and no
        # subscription edges (validated at the first pipelined barrier).
        self.in_flight_barriers = max(1, in_flight_barriers)
        self._closer_q: deque = deque()
        self._closer_cv = threading.Condition()
        self._closer: Optional[threading.Thread] = None
        self._closer_err: List[BaseException] = []
        self._closer_abort = threading.Event()
        self.epoch_close_ms: List[float] = []  # admission -> closed
        # serializes barrier/DDL/DML against a background barrier clock
        # (the CLI's tick thread vs pgwire sessions — the reference
        # serializes via the meta barrier scheduler's command queue)
        self.lock = threading.RLock()
        # -- barrier-lifecycle observability (EpochTrace) ---------------
        # every barrier gets a stage-attributed trace; the ring keeps
        # the recent history for /events-style inspection and bench
        self.epoch_traces: deque = deque(maxlen=256)
        self.last_epoch_trace: Optional[EpochTrace] = None
        self._traces_by_epoch: Dict[int, EpochTrace] = {}
        self._ingest_s = 0.0  # host time in push() since last barrier
        self._ingest_bytes = 0  # chunk bytes moved since last barrier
        self._prev_state_bytes = 0
        # stall watchdog: if a barrier exceeds this deadline, dump every
        # actor's span stack + channel depths BEFORE recovery destroys
        # the evidence (the q7 wedge forensic path). None disables.
        # Default rides just under the barrier deadman
        # (RW_BARRIER_TIMEOUT_S, which device benches raise to cover
        # first-epoch XLA compiles) so a legitimately-compiling barrier
        # never writes a false stall artifact.
        from risingwave_tpu.runtime.graph import _default_barrier_timeout

        try:
            self.stall_dump_after_s: Optional[float] = float(
                os.environ.get(
                    "RW_STALL_DUMP_S",
                    max(60.0, 0.9 * _default_barrier_timeout()),
                )
            )
        except ValueError:
            self.stall_dump_after_s = 0.9 * _default_barrier_timeout()

    # -- fragments -------------------------------------------------------
    def register(
        self,
        name: str,
        pipeline,
        upstream: Optional[str] = None,
        backfill: bool = True,
    ) -> None:
        """Register a fragment. With ``upstream`` (an already-registered
        fragment name), this is MV-on-MV: the upstream's emitted deltas
        are routed into this pipeline after every push/barrier, and —
        unless ``backfill=False`` (recovery re-registration: the state
        is already checkpointed) — the upstream MV's current rows are
        snapshot-backfilled first (no_shuffle_backfill.rs:66; see
        runtime/backfill.py)."""
        if name in self.fragments:
            raise ValueError(f"fragment {name!r} already registered")
        if upstream is not None and upstream not in self.fragments:
            raise KeyError(f"unknown upstream fragment {upstream!r}")
        self.fragments[name] = pipeline
        if self.mgr is not None:
            for ex in pipeline.executors:
                # sinks: delivery is deferred until the epoch's manifest
                # is durable (ADVICE r2: sink commits may never run
                # ahead of durability)
                if hasattr(ex, "deliver_on_durable"):
                    ex.deliver_on_durable = True
                # checkpoint staging will drain pending buffers, so
                # executors skip their own per-barrier compaction
                if hasattr(ex, "checkpoint_enabled"):
                    ex.checkpoint_enabled = True
                # cold tier: evicted durable groups read back through
                # the manager's point-read path (storage get_rows)
                if hasattr(ex, "cold_reader") and hasattr(ex, "table_id"):
                    ex.cold_reader = (
                        lambda keys, _tid=ex.table_id: self.mgr.get_rows(
                            _tid, keys
                        )
                    )
                # multi-table executors (join sides) pick their own
                # table per read
                if hasattr(ex, "cold_get_rows"):
                    ex.cold_get_rows = self.mgr.get_rows
        # mesh observability: instrument sharded chains as they come up
        # (no-op unless MESHPROF is armed AND the chain carries sharded
        # executors — serial fragments stay byte-for-byte untouched;
        # deferred import, same cycle as runtime/__init__'s lazy list)
        from risingwave_tpu.parallel.meshprof import MESHPROF

        if MESHPROF.enabled:
            MESHPROF.watch(pipeline, name=name)
        if upstream is not None:
            self.subscribe(upstream, name, backfill=backfill)

    def subscribe(
        self,
        upstream: str,
        name: str,
        backfill: bool = True,
        side: str = "single",
    ) -> None:
        """Add a delta edge upstream -> name. Multiple subscriptions of
        one fragment realize UNION ALL (the reference's UnionExecutor,
        union.rs: n inputs merged into one stream — here the host
        routes every upstream's chunks into the same pipeline).
        ``side`` targets one input of a two-input fragment ("left" /
        "right"), so joins over two upstream MVs/tables work."""
        if upstream not in self.fragments:
            raise KeyError(f"unknown upstream fragment {upstream!r}")
        if name not in self.fragments:
            raise KeyError(f"unknown fragment {name!r}")
        # UNION schema check (union.rs asserts input schemas match):
        # a second upstream feeding the same (fragment, side) must
        # expose the same lane set, or the mismatch would surface deep
        # inside a kernel long after DDL time
        def _mv_sig(frag):
            try:
                mv = self._fragment_mview(frag)
            except ValueError:
                return None  # no materialize stage: nothing to compare
            dts = getattr(mv, "dtypes", None)  # device MVs
            if not isinstance(dts, dict):
                dts = getattr(mv, "_dtypes", None)  # host MVs (lazy)
            if not isinstance(dts, dict):
                dts = {}
            return {
                n: (str(dts[n]) if n in dts else None)
                for n in tuple(mv.pk) + tuple(mv.columns)
            }

        new_sig = _mv_sig(upstream)
        if new_sig is not None:
            for prev_up, edges in self._subs.items():
                if prev_up == upstream or (name, side) not in edges:
                    continue
                prev_sig = _mv_sig(prev_up)
                if prev_sig is None:
                    continue
                mismatch = set(prev_sig) != set(new_sig) or any(
                    # dtypes compare only where BOTH sides know them
                    # (host MVs learn dtypes from their first chunk)
                    a is not None and b is not None and a != b
                    for a, b in (
                        (new_sig[n], prev_sig[n]) for n in new_sig
                    )
                )
                if mismatch:
                    raise ValueError(
                        f"UNION inputs disagree on schema: {upstream!r} "
                        f"exposes {sorted(new_sig.items())} but "
                        f"{prev_up!r} exposes {sorted(prev_sig.items())}"
                    )
        self._subs.setdefault(upstream, []).append((name, side))
        if backfill:
            from risingwave_tpu.runtime.backfill import snapshot_chunks

            up_mv = self._fragment_mview(upstream)
            for chunk in snapshot_chunks(up_mv):
                self._route(name, self._push_into(name, chunk, side))

    def unregister(self, name: str) -> None:
        """Remove a fragment and every subscription edge touching it —
        the rollback path when CREATE fails mid-registration (the
        reference cleans dirty streaming jobs the same way,
        ddl_controller.rs + barrier/recovery.rs 'clean dirty jobs')."""
        self.fragments.pop(name, None)
        self._subs.pop(name, None)
        FRESHNESS.drop(name)
        with self._replay_lock:
            self._replay.pop(name, None)
            self._replay_floor.pop(name, None)
        for up, edges in list(self._subs.items()):
            kept = [e for e in edges if e[0] != name]
            if kept:
                self._subs[up] = kept
            else:
                del self._subs[up]

    def rename_fragment(self, old: str, new: str) -> None:
        """Re-key a fragment (and every edge/replay record touching
        it) without disturbing its pipeline, state, or the topological
        registration order — the shared-arrangement owner-drop handoff
        (the writer keeps streaming under an internal alias while the
        user-visible name frees up)."""
        if old not in self.fragments:
            raise KeyError(f"unknown fragment {old!r}")
        if new in self.fragments:
            raise ValueError(f"fragment {new!r} already registered")
        # rebuilt in place so the barrier walk's topological order holds
        self.fragments = {
            (new if k == old else k): v for k, v in self.fragments.items()
        }
        if old in self._subs:
            self._subs[new] = self._subs.pop(old)
        for up, edges in self._subs.items():
            self._subs[up] = [
                ((new if n == old else n), s) for n, s in edges
            ]
        with self._replay_lock:
            for m in (self._replay, self._replay_floor, self._replay_covered):
                if old in m:
                    m[new] = m.pop(old)

    def _fragment_mview(self, name: str):
        from risingwave_tpu.executors.materialize import (
            DeviceMaterializeExecutor,
            MaterializeExecutor,
        )

        for ex in reversed(self.fragments[name].executors):
            if isinstance(
                ex, (MaterializeExecutor, DeviceMaterializeExecutor)
            ):
                return ex
        raise ValueError(f"fragment {name!r} has no materialize stage")

    # -- replay buffer (partial recovery's data source) -------------------
    def _record_push(self, name: str, chunk: StreamChunk, side: str) -> None:
        if self.mgr is None:
            return  # no durability boundary -> no recovery -> no log
        with self._replay_lock:
            if self._replay_floor.get(name, 0) is None:
                return  # window lost: re-anchors at the next barrier
            log = self._replay.setdefault(name, [])
            if len(log) >= self._replay_cap:
                # bounded: drop the window rather than grow without
                # limit — this fragment falls back to full recovery
                # until the log re-anchors at a durable barrier
                log.clear()
                self._replay_floor[name] = None
                REGISTRY.counter("replay_buffer_overflows_total").inc(
                    fragment=name
                )
                return
            log.append(("push", chunk, side))

    def _record_barrier(self, name: str, epoch: int, checkpoint: bool) -> None:
        if self.mgr is None:
            return
        with self._replay_lock:
            if self._replay_floor.get(name, 0) is None:
                # re-anchor: state as of THIS barrier is the new replay
                # baseline; the log replays any committed epoch >= it
                self._replay[name] = []
                self._replay_floor[name] = epoch
                return
            self._replay.setdefault(name, []).append(
                ("barrier", epoch, checkpoint)
            )

    def _prune_replay(self, epoch: int) -> None:
        """Epoch is durable: events at or before its barrier marker can
        never be replayed again (restores land at >= this epoch).
        Fragments fenced for a deferred recovery were EXCLUDED from
        this epoch's staging — their durable coverage did not advance,
        so their logs must keep the whole window for the resume."""
        pp = self._pending_partial
        skip = pp["scope"] if pp is not None else ()
        with self._replay_lock:
            for name, log in self._replay.items():
                if name in skip:
                    continue
                self._replay_covered[name] = max(
                    self._replay_covered.get(name, 0), epoch
                )
                cut = 0
                for i, ev in enumerate(log):
                    if ev[0] == "barrier" and ev[1] <= epoch:
                        cut = i + 1
                if cut:
                    del log[:cut]

    def _push_into(self, name: str, chunk: StreamChunk, side: str):
        # failpoint for crash tests: a push that dies mid-fan-out (one
        # subscriber absorbed the chunk, a later one did not) is the
        # half-applied-epoch window the compute node must roll back
        sync_point.hit(f"push_into:{name}:{side}")
        self._write_gen += 1
        self._record_push(name, chunk, side)
        pp = self._pending_partial
        if pp is not None and name in pp["scope"]:
            # fenced for a deferred partial recovery: the input is
            # parked in the replay buffer and applied when the store
            # heals — healthy fragments keep flowing around it
            return []
        p = self.fragments[name]
        if side == "left":
            return p.push_left(chunk)
        if side == "right":
            return p.push_right(chunk)
        if side == "both":
            # self-join: ONE base stream feeds both join inputs (the
            # Nexmark q7 shape — bid joined against its own per-window
            # max); the reference realizes this as two upstream edges
            # from the same fragment
            outs = p.push_left(chunk)
            outs.extend(p.push_right(chunk))
            return outs
        return p.push(chunk)

    def push(self, name: str, chunk: StreamChunk, side: str = "single"):
        """Feed one chunk into a fragment and route its emitted deltas
        into every subscribed downstream fragment (the exchange edge an
        MV-on-MV chain rides)."""
        t0 = time.perf_counter()
        outs = self._push_into(name, chunk, side)
        REGISTRY.counter("chunks_pushed_total").inc(fragment=name)
        self._route(name, outs)
        # ingest attribution: the next barrier's EpochTrace charges this
        # host time + chunk bytes to its "ingest" stage
        self._ingest_s += time.perf_counter() - t0
        self._ingest_bytes += chunk_nbytes(chunk)
        return outs

    def _route(self, upstream: str, chunks) -> None:
        for sub, side in self._subs.get(upstream, ()):
            outs = []
            for c in chunks:
                outs.extend(self._push_into(sub, c, side))
            self._route(sub, outs)

    def register_state(self, obj) -> None:
        """Register a non-pipeline Checkpointable (e.g. a source's
        split offsets) into the checkpoint/recovery cycle."""
        self._aux_state.append(obj)

    def unregister_state(self, obj) -> None:
        """Drop a Checkpointable (DROP SOURCE): a dead executor must
        not keep persisting its state every checkpoint."""
        self._aux_state = [o for o in self._aux_state if o is not obj]

    def executors(self) -> List[object]:
        out = []
        for p in self.fragments.values():
            out.extend(p.executors)
        out.extend(self._aux_state)
        return out

    # -- barrier clock ---------------------------------------------------
    @property
    def epoch(self) -> int:
        return self._epoch

    def next_epoch(self) -> int:
        return max(int(time.time() * 1000) << 16, self._epoch + 1)

    def barrier(self) -> Dict[str, List[StreamChunk]]:
        """Inject one barrier into every fragment; commit a checkpoint
        every ``checkpoint_frequency``-th barrier. Returns each
        fragment's emitted chunks.

        With ``auto_recover``, a failure here (poisoned epoch, dead
        actor, commit-lane error) recovers in place and returns {} —
        the failed epoch is abandoned, offsets roll back, and the
        caller's next pump replays it (no manual recover())."""
        with self.lock:
            watchdog = self._arm_stall_watchdog()
            try:
                outs = self._barrier_locked()
                self._consecutive_recoveries = 0
                self._consecutive_partials = 0
                # a clean barrier clears the pump contract flag: pumps
                # consult it ONLY when a barrier recovered instead of
                # committing, so it must never linger from a past one
                self.last_recovery_mode = None
                if getattr(self, "_grew_last_recovery", False):
                    # the grown replay committed: the growths were
                    # legitimate cures, not a runaway — refund the
                    # per-executor give-up budget
                    self._grew_last_recovery = False
                    for ex in self.executors():
                        if getattr(ex, "_growth_rounds", 0):
                            ex._growth_rounds = 0
                return outs
            except (KeyboardInterrupt, SystemExit):
                raise  # never convert an operator stop into a recovery
            except Exception as e:
                if not self.auto_recover or self.mgr is None:
                    raise
                self._auto_recover(e)
                return {}
            finally:
                if watchdog is not None:
                    watchdog.cancel()

    def _arm_stall_watchdog(self) -> Optional[threading.Timer]:
        """Fire a stall dump if the barrier outlives its deadline — the
        artifact lands while the barrier is STILL stuck, before any
        recovery/abandonment destroys the evidence (q7 wedge case)."""
        if self.stall_dump_after_s is None or self.stall_dump_after_s <= 0:
            return None
        epoch_at_arm = self._epoch

        def _fire() -> None:
            dump_stalls(
                f"barrier after epoch {epoch_at_arm} exceeded "
                f"{self.stall_dump_after_s}s deadline",
                runtime=self,
            )

        # one Timer thread per barrier: ~100µs against a >=100ms barrier
        # cadence (barrier_interval_ms); canceled timers exit promptly.
        # The name is load-bearing for the orphan-timer regression test:
        # every exit path of barrier() (success, recovery, escalation
        # raise) runs the finally-cancel, so no timer with this name may
        # outlive its barrier.
        t = threading.Timer(self.stall_dump_after_s, _fire)
        t.daemon = True
        t.name = "rw-stall-watchdog"
        t.start()
        return t

    def _auto_recover(self, cause: Exception) -> None:
        """Failure routing with the partial→full→raise escalation
        ladder:

        1. If the failure is attributable to one (or a few) graph-backed
           fragments and the blast radius is a strict subset of the
           runtime, run FRAGMENT-SCOPED PARTIAL RECOVERY: restore only
           the affected fragments' state tables, replay their buffered
           inputs, and leave healthy fragments' live state untouched.
        2. Three consecutive partial-recovery failures (the fault keeps
           re-firing) escalate to today's FULL recovery.
        3. Three consecutive full recoveries raise the deterministic-
           fault error (the existing contract)."""
        self.last_failure = cause
        REGISTRY.counter("auto_recoveries_total").inc()
        self.auto_recoveries += 1
        # close any open profiler capture window FIRST: an orphaned
        # jax.profiler session surviving a recovery would hold the
        # device and poison the next capture (watchdog-orphan audit)
        PROFILER.abort_captures()
        # deviceprof re-arms across the rebuild: stale per-barrier
        # telemetry drops, program analyses survive (the rebuilt
        # fragments re-fuse into the SAME compiled programs), and no
        # capture window can orphan — deviceprof never opens one
        from risingwave_tpu.deviceprof import DEVICEPROF

        DEVICEPROF.on_recovery()
        # a DeviceWedged is handled like an actor fault, not a crash:
        # abort the sentinel's capture window and disarm the wedge so
        # the recovered runtime's next barrier proceeds — a device that
        # is STILL wedged re-arms on the next missed heartbeat, and the
        # consecutive-recovery ladder surfaces it as deterministic
        blackbox.SENTINEL.abort_capture()
        if isinstance(cause, blackbox.DeviceWedged):
            blackbox.SENTINEL.clear_wedge()
        # a latched capacity overflow needs the full path's grow-and-
        # replay cure; everything else may be partial-eligible
        latched = any(
            fn()
            for fn in (
                getattr(ex, "capacity_overflow_latched", None)
                for ex in self.executors()
            )
            if fn is not None
        )
        scope = None if latched else self._partial_scope()
        while scope is not None and self._consecutive_partials < 3:
            self._consecutive_partials += 1
            EVENT_LOG.record(
                "recovery",
                mode="partial",
                fragments=sorted(scope),
                scope=len(scope),
                total=len(self.fragments),
                consecutive=self._consecutive_partials,
                cause=repr(cause),
            )
            try:
                # store-free cleanup FIRST — even before draining the
                # async lane, which can itself raise STORE_UNAVAILABLE:
                # a fenced sink's stale held batch must be gone before
                # ANY later epoch can become durable and release it
                self._discard_scope(scope)
                # drain — never abort — the async lane: healthy
                # fragments' staged epochs must still commit; only the
                # blast radius rolls back
                self.wait_checkpoints()
                self._partial_recover(scope, repr(cause))
                self.last_recovery_mode = "partial"
                return
            except (KeyboardInterrupt, SystemExit):
                raise
            except STORE_UNAVAILABLE:
                # degraded-mode composition: the store is down, so the
                # restore DEFERS — the blast radius stays fenced (its
                # inputs park in the replay buffer) and healthy
                # fragments keep serving; the barrier clock retries the
                # restore once the breaker lets a probe through
                self._pending_partial = {
                    "scope": set(scope), "cause": repr(cause)
                }
                REGISTRY.counter("partial_recovery_deferrals_total").inc()
                EVENT_LOG.record(
                    "recovery",
                    mode="partial_deferred",
                    fragments=sorted(scope),
                )
                self.last_recovery_mode = "partial"
                return
            except Exception as e:  # noqa: BLE001 — count + escalate
                cause = e
                scope = self._partial_scope() or scope
        # -- full recovery (the stop-the-world floor) --------------------
        # a DETERMINISTIC failure (e.g. a capacity overflow) would
        # recover-replay-fail forever: after a few consecutive failed
        # epochs, surface the cause instead
        self._consecutive_recoveries += 1
        EVENT_LOG.record(
            "recovery",
            mode="auto",
            cause=repr(cause),
            consecutive=self._consecutive_recoveries,
        )
        # a latched sharded-capacity overflow is DETERMINISTIC at the
        # old shape but curable: grow the overflowed op 2x before the
        # replay (the reference reschedules with more parallelism,
        # scale.rs:453 — here capacity is the per-shard analogue) and
        # refund the deterministic-fault budget so the grown replay
        # gets its attempt. Quiesce FIRST: an in-flight worker step or
        # queued closer commit could otherwise write an old-shape
        # table back over the grown one.
        self._quiesce()
        grew = 0
        for ex in self.executors():
            latched_fn = getattr(ex, "capacity_overflow_latched", None)
            if latched_fn is None or not latched_fn():
                continue
            rounds = getattr(ex, "_growth_rounds", 0)
            if rounds >= 5:
                raise RuntimeError(
                    f"{type(ex).__name__} still overflows after "
                    f"{rounds} capacity doublings — giving up"
                ) from cause
            ex.grow_for_replay()
            ex._growth_rounds = rounds + 1
            REGISTRY.counter("overflow_growths_total").inc()
            grew += 1
        if grew:
            self._grew_last_recovery = True
            self._consecutive_recoveries = min(
                self._consecutive_recoveries, 1
            )
        if self._consecutive_recoveries >= 3:
            raise RuntimeError(
                "auto-recovery failed 3 consecutive epochs — the fault "
                "is deterministic, not transient"
            ) from cause
        self.last_recovery_mode = "full"
        # dead actor threads never come back: rebuild graph-backed
        # fragments (fresh actors/channels around the same executors)
        # BEFORE restoring executor state
        for p in self.fragments.values():
            fn = getattr(p, "rebuild", None)
            if fn is not None:
                fn()
        self.recover()

    # -- partial recovery (fragment-scoped failover) ---------------------
    def _partial_scope(self) -> Optional[set]:
        """The runtime-level blast radius of the current failure: the
        fragments whose actor graphs recorded an actor death, plus
        their transitive subscribers (MV-on-MV closure). None when the
        failure is not scopeable — no graph attributed it, the scope
        covers every fragment, the replay window was lost, or pipelined
        barriers are on (their closer lane owns epoch bookkeeping)."""
        if self.mgr is None or self.in_flight_barriers > 1:
            return None
        failed = set()
        for name, p in self.fragments.items():
            fn = getattr(p, "failure_scope", None)
            if fn is not None and fn():
                failed.add(name)
        if not failed:
            return None
        scope = set(failed)
        frontier = list(failed)
        while frontier:
            up = frontier.pop()
            for sub, _side in self._subs.get(up, ()):
                if sub not in scope:
                    scope.add(sub)
                    frontier.append(sub)
        if scope >= set(self.fragments):
            return None  # whole-runtime blast: full recovery is the floor
        committed = self.mgr.max_committed_epoch
        with self._replay_lock:
            for name in scope:
                floor = self._replay_floor.get(name, 0)
                cov = min(committed, self._replay_covered.get(name, committed))
                if floor is None or floor > cov:
                    return None  # replay window lost for this fragment
        return scope

    def _scoped_plans(self, scope: set) -> Dict[str, tuple]:
        """(graph_fragments_or_None, executors_to_restore) per scoped
        fragment, in registration (topological) order."""
        plans: Dict[str, tuple] = {}
        for name, p in self.fragments.items():
            if name not in scope:
                continue
            fn = getattr(p, "scoped_recovery_plan", None)
            plans[name] = fn() if fn is not None else (None, list(p.executors))
        return plans

    def _discard_scope(self, scope: set) -> None:
        """Store-free cleanup of a blast radius: drop held sink batches
        and captured deltas of every scoped fragment, so no later
        durable epoch can release output whose producing state is about
        to roll back and replay (double delivery). Runs BEFORE any
        store touch — a deferred restore must leave nothing stale."""
        for name, p in self.fragments.items():
            if name not in scope:
                continue
            for ex in p.executors:
                for hook in ("discard_pending", "discard_captured"):
                    fn = getattr(ex, hook, None)
                    if fn is not None:
                        fn()

    def _partial_recover(self, scope: set, cause: str) -> None:
        """Restore + replay ONLY ``scope``: rebuild each affected
        pipeline's actors (scoped inside the graph when sound), restore
        its state tables from the last committed checkpoint, replay its
        buffered inputs, and rejoin at the next barrier boundary.
        Healthy fragments are never touched — their MVs keep answering
        ``query()`` throughout. Raises STORE_UNAVAILABLE (caller defers)
        when the store cannot serve the restore reads."""
        t0 = time.perf_counter()
        committed = self.mgr.max_committed_epoch
        plans = self._scoped_plans(scope)
        self._discard_scope(scope)
        br = self.store_breaker
        if br is not None and not br.allow():
            from risingwave_tpu.resilience import CircuitOpenError

            raise CircuitOpenError(
                "object store breaker open: partial recovery deferred"
            )
        self.partial_recoveries += 1
        REGISTRY.counter("partial_recoveries_total").inc()
        REGISTRY.gauge("recovery_scope_fragments").set(float(len(scope)))
        # quiesce compaction: its GC deletes SSTs the restore reads
        self._compact_pause.set()
        try:
            self._compact_idle.wait()
            for name, (gfrags, exs) in plans.items():
                tf = time.perf_counter()
                p = self.fragments[name]
                rb = getattr(p, "rebuild", None)
                if rb is not None:
                    try:
                        rb(fragments=gfrags)
                    except TypeError:  # a rebuild() without scoping
                        rb()
                self.mgr.recover(exs)
                # this fragment's restore lands at ITS durable coverage
                # — which lags the global committed epoch if healthy-
                # only commits advanced the manifest while it was fenced
                with self._replay_lock:
                    cov = min(
                        committed, self._replay_covered.get(name, committed)
                    )
                p._epoch = cov
                for ex in exs:
                    fn = getattr(ex, "on_recover", None)
                    if fn is not None:
                        fn(cov)
                # test/operator hook: fires INSIDE the recovery window,
                # after the subtree restored and before it rejoins —
                # healthy MVs must answer query() right now
                sync_point.hit(f"partial_recovery:{name}")
                self._replay_fragment(name, p, cov)
                REGISTRY.histogram("recovery_downtime_ms").observe(
                    (time.perf_counter() - tf) * 1e3, fragment=name
                )
        finally:
            self._compact_pause.clear()
        self._work_abort.clear()
        self._closer_abort.clear()
        self._work_err.clear()
        self._closer_err.clear()
        # shared arrangements must not keep serving snapshots that
        # postdate the restored state — republish off the recovery
        self.arrangements.on_recovery(committed)
        EVENT_LOG.record(
            "recovery",
            mode="partial_done",
            fragments=sorted(scope),
            epoch=committed,
            wall_ms=round((time.perf_counter() - t0) * 1e3, 2),
        )

    def _replay_fragment(self, name: str, p, covered: int) -> int:
        """Replay a fragment's buffered inputs on top of its restored
        state: skip everything the fragment's durable coverage already
        holds, re-push the rest in order, re-running barrier boundaries
        as NON-checkpoint barriers (the next real checkpoint stages the
        whole replayed delta). Outputs are discarded — every subscriber
        is inside the scope and replays its OWN recorded inputs, so
        routing them again would double-apply."""
        with self._replay_lock:
            log = list(self._replay.get(name, ()))
        start = 0
        for i, ev in enumerate(log):
            if ev[0] == "barrier" and ev[1] <= covered:
                start = i + 1
        replayed = 0
        # replay re-runs ALREADY-SEEN epochs: recording them would
        # break the black box's monotonic timeline — suppress
        with blackbox.RECORDER.suppress_pipeline_records():
            for ev in log[start:]:
                if ev[0] == "push":
                    _k, chunk, side = ev
                    if side == "left":
                        p.push_left(chunk)
                    elif side == "right":
                        p.push_right(chunk)
                    elif side == "both":
                        p.push_left(chunk)
                        p.push_right(chunk)
                    else:
                        p.push(chunk)
                    replayed += 1
                else:
                    _k, epoch, _ck = ev
                    # mutation-style rejoin boundary: the rebuilt
                    # subtree re-aligns at the SAME epoch fence the
                    # healthy graph already passed
                    p.barrier(checkpoint=False, epoch=epoch)
        if replayed or start < len(log):
            REGISTRY.counter("replay_events_total").inc(
                len(log) - start, fragment=name
            )
        return replayed

    def _maybe_resume_partial(self) -> bool:
        """Deferred partial recovery rides the barrier clock (like the
        degraded-mode restore probe): retry the scoped restore once the
        breaker lets a store touch through. If the replay window was
        lost while deferred, escalate to full recovery instead of
        silently dropping data."""
        pp = self._pending_partial
        if pp is None:
            return False
        br = self.store_breaker
        if br is not None and not br.allow():
            return False
        scope = set(pp["scope"])
        committed = self.mgr.max_committed_epoch if self.mgr else 0
        with self._replay_lock:
            lost = any(
                self._replay_floor.get(n, 0) is None
                or self._replay_floor.get(n, 0)
                > min(committed, self._replay_covered.get(n, committed))
                for n in scope
            )
        if lost:
            self._pending_partial = None
            EVENT_LOG.record(
                "recovery",
                mode="auto",
                cause="deferred partial recovery lost its replay window",
            )
            for p in self.fragments.values():
                fn = getattr(p, "rebuild", None)
                if fn is not None:
                    fn()
            self.last_recovery_mode = "full"
            self.recover()
            return True
        try:
            self._partial_recover(scope, str(pp["cause"]))
        except STORE_UNAVAILABLE:
            return False  # still down: stay deferred, never wedge
        except Exception:
            self._pending_partial = None
            raise  # surfaces through barrier() -> _auto_recover routing
        self._pending_partial = None
        self.last_recovery_mode = "partial"
        return True

    def _staging_executors(self) -> List[object]:
        """Executors eligible for checkpoint staging: while a deferred
        partial recovery has fragments fenced, their (unrestored) state
        must not be staged into a manifest — healthy fragments and aux
        state keep committing around them."""
        pp = self._pending_partial
        if pp is None:
            return self.executors()
        skip = pp["scope"]
        out: List[object] = []
        for name, p in self.fragments.items():
            if name in skip:
                continue
            out.extend(p.executors)
        out.extend(self._aux_state)
        return out

    # -- pipelined barrier path (in_flight_barriers > 1) -----------------
    def _validate_pipelined(self) -> None:
        if self._subs:
            raise ValueError(
                "pipelined barriers do not support subscription edges "
                "(MV-on-MV needs synchronous epoch routing) — use "
                "in_flight_barriers=1"
            )
        for name, p in self.fragments.items():
            if not hasattr(p, "barrier_nowait"):
                raise ValueError(
                    f"fragment {name!r} is not graph-backed; pipelined "
                    "barriers need GraphPipeline fragments"
                )
            if self.mgr is not None:
                p.set_capture(True)

    def _barrier_pipelined(self) -> Dict[str, List[StreamChunk]]:
        t0 = time.perf_counter()
        self._raise_closer_error()
        self._raise_worker_error()
        self._validate_pipelined()
        prev, self._epoch = self._epoch, self.next_epoch()
        self._barrier_seq += 1
        is_ckpt = (
            self.mgr is not None
            and self._barrier_seq % self.checkpoint_frequency == 0
        )
        tr = self._begin_trace(is_ckpt)
        for _name, p in self.fragments.items():
            p._epoch = prev
            p.barrier_nowait(checkpoint=is_ckpt, epoch=self._epoch)
            # pipelined mode never takes the partial path, but the
            # marker keeps the replay buffer's pruning cursor moving
            self._record_barrier(_name, self._epoch, is_ckpt)
        with self._closer_cv:
            self._closer_q.append((self._epoch, is_ckpt, t0))
            self._ensure_closer()
            self._closer_cv.notify_all()
            # admission control: bounded in-flight epochs
            self._closer_cv.wait_for(
                lambda: len(self._closer_q) < self.in_flight_barriers
                or bool(self._closer_err)
            )
        self._raise_closer_error()
        # recompile-storm governor rides the admission clock too
        self._shape_watch_tick()
        self.shape_governor.observe_barrier(self)
        # the trace is NOT finalized here: admission wall time would
        # inflate achieved_bw to nonsense — the closer lane finalizes
        # it once the epoch actually closed (commit stages land later)
        ms = (time.perf_counter() - t0) * 1e3
        self.barrier_latencies_ms.append(ms)  # ADMISSION latency
        REGISTRY.histogram("barrier_latency_ms").observe(ms)
        REGISTRY.counter("barriers_total").inc()
        return {}

    def _ensure_closer(self) -> None:
        if self._closer is None or not self._closer.is_alive():
            self._closer = threading.Thread(
                target=self._closer_loop, daemon=True
            )
            self._closer.start()

    def _closer_loop(self) -> None:
        while True:
            with self._closer_cv:
                if not self._closer_q:
                    self._closer_cv.wait(timeout=0.5)
                    if not self._closer_q:
                        continue
                epoch, is_ckpt, t_adm = self._closer_q[0]
            try:
                if not self._closer_err and not self._closer_abort.is_set():
                    tr = self._traces_by_epoch.get(epoch)
                    t_close = time.perf_counter()
                    for name, p in self.fragments.items():
                        with span("barrier.close", fragment=name):
                            p.wait_barrier(epoch)
                    if tr is not None:
                        tr.add_stage(
                            "close", (time.perf_counter() - t_close) * 1e3
                        )
                    if is_ckpt:
                        # deltas were SEALED by the actors at the
                        # barrier (capture_checkpoint): stage consumes
                        # host buffers, never racing next-epoch compute
                        t_staged = time.perf_counter()
                        with span("checkpoint.stage", epoch=epoch):
                            staged = self.mgr.stage(
                                self._staging_executors()
                            )
                        if tr is not None:
                            tr.add_stage(
                                "checkpoint_stage",
                                (time.perf_counter() - t_staged) * 1e3,
                            )
                        REGISTRY.counter("checkpoints_total").inc()
                        with self._inflight_lock:
                            self._inflight += 1
                        self._work_q.append((epoch, staged, t_staged, tr))
                        self._ensure_worker()
                        self._work_event.set()
                    if tr is not None:
                        # finalize over admission->closed (the epoch's
                        # real span), not admission-only wall time
                        self._end_trace(tr)
                    self.epoch_close_ms.append(
                        (time.perf_counter() - t_adm) * 1e3
                    )
            except BaseException as e:  # surfaced at the next barrier
                self._closer_err.append(e)
            finally:
                with self._closer_cv:
                    if self._closer_q and self._closer_q[0][0] == epoch:
                        self._closer_q.popleft()
                    self._closer_cv.notify_all()

    def _raise_closer_error(self) -> None:
        if self._closer_err:
            raise RuntimeError(
                "pipelined barrier close failed"
            ) from self._closer_err[0]

    def wait_epochs(self) -> None:
        """Join the closer lane: every admitted barrier fully closed
        (collection + staging done; commits may still be in the async
        lane — ``wait_checkpoints`` joins those too)."""
        with self._closer_cv:
            self._closer_cv.wait_for(lambda: not self._closer_q)
        self._raise_closer_error()

    def p99_epoch_close_ms(self) -> float:
        if not self.epoch_close_ms:
            return 0.0
        return float(np.percentile(self.epoch_close_ms, 99))

    def _barrier_locked(self) -> Dict[str, List[StreamChunk]]:
        # device-wedge fail-fast: an armed sentinel wedge raises the
        # structured DeviceWedged HERE instead of letting the barrier
        # walk dispatch into a dead device and hang until an outer
        # alarm (the q7 wedge path); auto_recover routes it like any
        # other barrier fault
        blackbox.SENTINEL.check()
        # degraded-mode probe rides the barrier clock: the breaker's
        # cooldown gates actual store touches, so a down store costs
        # nothing per barrier and a healed one replays the spill here
        self._maybe_restore_degraded()
        # deferred partial recovery probes on the same clock
        self._maybe_resume_partial()
        if self.in_flight_barriers > 1:
            return self._barrier_pipelined()
        t0 = time.perf_counter()
        prev, self._epoch = self._epoch, self.next_epoch()
        self._barrier_seq += 1
        is_ckpt = (
            self.mgr is not None
            and self._barrier_seq % self.checkpoint_frequency == 0
        )
        tr = self._begin_trace(is_ckpt)
        outs = {}
        pending = self._pending_partial
        # registration order is topological (downstreams register after
        # their upstream), so an upstream's barrier-flush deltas reach a
        # subscriber BEFORE the subscriber's own barrier runs.
        # Suppression spans the whole walk: this barrier records ONCE
        # via its EpochTrace in _end_trace, not per fragment pipeline
        with blackbox.RECORDER.suppress_pipeline_records():
            for name, p in self.fragments.items():
                if pending is not None and name in pending["scope"]:
                    continue  # fenced: deferred recovery owns this subtree
                p._epoch = prev  # fragments share the runtime's clock
                # non-checkpoint barriers must NOT commit sinks
                # (exactly-once: sink commits may never run ahead of
                # durability); the runtime's epoch is passed down so
                # held sink batches key by the exact epoch
                # _commit/_on_epoch_durable will use
                tf = time.perf_counter()
                with span(
                    "barrier.fragment", fragment=name, epoch=self._epoch
                ), PROFILER.barrier_window(fragment=name):
                    outs[name] = p.barrier(
                        checkpoint=is_ckpt, epoch=self._epoch
                    )
                self._route(name, outs[name])
                # replay-buffer epoch fence: everything recorded before
                # this marker belongs to epochs <= self._epoch for this
                # fragment
                self._record_barrier(name, self._epoch, is_ckpt)
                tr.add_stage(
                    "dispatch",
                    (time.perf_counter() - tf) * 1e3,
                    fragment=name,
                )
        if is_ckpt:
            self._commit(self._epoch, tr)
        if self.memory_budget_bytes is not None:
            self._enforce_memory_budget()
        # recompile-storm governor: consume this barrier's hazard
        # deltas; over budget (or SLOW device) → pin to max bucket.
        # One attribute check while SignatureWatch is disarmed.
        self._shape_watch_tick()
        self.shape_governor.observe_barrier(self)
        self._end_trace(tr)
        ms = (time.perf_counter() - t0) * 1e3
        self.barrier_latencies_ms.append(ms)
        REGISTRY.histogram("barrier_latency_ms").observe(ms)
        REGISTRY.counter("barriers_total").inc()
        if PROFILER.enabled:
            # slow-barrier auto-capture: a barrier over the profile
            # threshold leaves a PROFILE_* artifact + forensic dump
            PROFILER.observe_barrier(ms, runtime=self)
        return outs

    def _shape_watch_tick(self) -> None:
        """RW_SHAPE_WATCH_WARMUP bookkeeping: after N barriers the
        armed SignatureWatch turns stable — every later novel shape is
        a hazard the governor may act on."""
        if self._shape_watch_warmup <= 0:
            return
        self._shape_watch_warmup -= 1
        if self._shape_watch_warmup == 0:
            from risingwave_tpu.analysis.jax_sanitizer import SIGNATURES

            SIGNATURES.mark_stable()

    # -- EpochTrace plumbing ---------------------------------------------
    def _begin_trace(self, is_ckpt: bool) -> EpochTrace:
        tr = EpochTrace(self._epoch, self._barrier_seq, is_ckpt)
        # commit->visible anchor (freshness.py): wall clock at barrier
        # open; _end_trace measures to the post-publish visible point
        tr.barrier_open_wall = time.time()
        # charge accumulated push() time/bytes to this epoch's ingest
        tr.add_stage("ingest", self._ingest_s * 1e3)
        tr.chunk_bytes = self._ingest_bytes
        self._ingest_s, self._ingest_bytes = 0.0, 0
        self._traces_by_epoch[tr.epoch] = tr
        # bound the pending map (async commits resolve FIFO)
        while len(self._traces_by_epoch) > 512:
            self._traces_by_epoch.pop(next(iter(self._traces_by_epoch)))
        return tr

    def _end_trace(self, tr: EpochTrace) -> None:
        state_bytes = self.state_nbytes()
        tr.finalize(state_bytes, self._prev_state_bytes)
        self._prev_state_bytes = state_bytes
        self.epoch_traces.append(tr)
        self.last_epoch_trace = tr
        # shared arrangements: swap in this barrier's published version
        # (pointer swap; materializes only under active read demand)
        self.arrangements.publish(tr.epoch)
        # freshness + backpressure attribution (ISSUE 16): NOW the
        # epoch's snapshots are what a reader sees — measure to here.
        # Host timestamps and dict folds only; never faults a barrier.
        try:
            self._observe_freshness(tr)
        except Exception:  # noqa: BLE001 — accounting never faults
            pass
        # memory governor + overload ladder: consumes the fresh state
        # bytes and this barrier's backpressure verdict, applies veto/
        # spill/ladder/credit actions. Runs on BOTH barrier paths (the
        # pipelined closer lane finalizes traces here too); dormant =
        # one attribute check. Never faults a barrier (self-guarded).
        self.memory_governor.observe_barrier(self, tr)
        # mesh observability: fold the per-pipeline shard windows closed
        # this barrier into one mesh doc on the trace (per-shard stage
        # lanes + exchange matrix + skew verdict). Dormant = one
        # attribute check; self-guarded, never faults a barrier.
        from risingwave_tpu.parallel.meshprof import MESHPROF

        MESHPROF.observe_barrier(self, tr)
        # flight recorder: the finalized trace is exactly one black-box
        # record (ring always; segment file when a dir is configured)
        blackbox.RECORDER.record_barrier(tr, runtime=self)
        if tr.checkpoint:
            EVENT_LOG.record(
                "barrier_commit",
                epoch=tr.epoch,
                wall_ms=round(tr.wall_ms, 2),
                achieved_bw_frac=tr.achieved_bw_frac,
            )

    def _observe_freshness(self, tr: EpochTrace) -> None:
        """Per-MV freshness deltas at the VISIBLE point + the barrier's
        backpressure verdict (freshness.py). commit->visible runs from
        the barrier-open wall clock to after ``arrangements.publish`` —
        the first instant a lock-free reader can see the epoch; the
        fragments contribute their own ingest wall + watermark frontier
        via FreshnessSurface samples keyed by this epoch."""
        visible = time.time()
        c2v = (
            round((visible - tr.barrier_open_wall) * 1e3, 3)
            if tr.barrier_open_wall
            else None
        )
        fr: Dict[str, dict] = {}
        for name, p in list(self.fragments.items()):
            ent: Dict[str, float] = {}
            if c2v is not None:
                ent["commit_to_visible_ms"] = c2v
            s = getattr(p, "last_freshness", None)
            if s is not None and s.get("epoch") == tr.epoch:
                iw = s.get("ingest_wall")
                if iw:
                    ent["source_to_visible_ms"] = round(
                        (visible - iw) * 1e3, 3
                    )
                lw = s.get("low_watermark")
                if lw is not None:
                    ent["event_time_lag_ms"] = round(
                        visible * 1000.0 - lw, 3
                    )
            FRESHNESS.observe(name, tr.epoch, tr.checkpoint, **ent)
            fr[name] = ent
        # attached shared-arrangement names become visible at the SAME
        # publish: they inherit their backing fragment's deltas
        reg = self.arrangements
        for mv in list(reg._facades):
            if mv in fr:
                continue
            frag = reg.fragment_for(mv)
            base = fr.get(
                frag,
                {"commit_to_visible_ms": c2v} if c2v is not None else {},
            )
            FRESHNESS.observe(mv, tr.epoch, tr.checkpoint, **base)
            fr[mv] = base
        tr.freshness = fr
        verdict = attribute_backpressure(self, tr)
        tr.backpressure_fragment = verdict["fragment"]
        tr.backpressure_ms = verdict["ms"]
        tr.backpressure = verdict["detail"]

    def state_nbytes(self) -> int:
        """Accounted device state across all fragments (host estimate)."""
        return sum(
            ex.state_nbytes()
            for ex in self.executors()
            if hasattr(ex, "state_nbytes")
        )

    def _enforce_memory_budget(self) -> None:
        total = self.state_nbytes()
        REGISTRY.gauge("state_bytes").set(float(total))
        if total <= self.memory_budget_bytes:
            return
        # eviction frees only durable slots; an in-flight async commit
        # has flipped stored marks for state that is not durable YET —
        # join the lane first so evict never races durability
        self.wait_checkpoints()
        evicted = 0
        for ex in self.executors():
            fn = getattr(ex, "evict_cold", None)
            has_reader = (
                getattr(ex, "cold_reader", None) is not None
                or getattr(ex, "cold_get_rows", None) is not None
            )
            if fn is not None and has_reader:
                evicted += fn()
        REGISTRY.counter("cold_evictions_total").inc(evicted)
        REGISTRY.gauge("state_bytes").set(float(self.state_nbytes()))

    def tick(self) -> bool:
        """Barrier iff ``barrier_interval_ms`` elapsed since the last
        one (ScheduledBarriers min-interval tick). Returns whether a
        barrier fired."""
        with self.lock:
            now = time.time()
            if (
                now - self._last_barrier_at
            ) * 1000 < self.barrier_interval_ms:
                return False
            self._last_barrier_at = now
            self.barrier()
            return True

    def p99_barrier_ms(self) -> float:
        if not self.barrier_latencies_ms:
            return 0.0
        return float(np.percentile(self.barrier_latencies_ms, 99))

    # -- degraded mode (store breaker open) ------------------------------
    @property
    def degraded(self) -> bool:
        return self._degraded

    def try_restore_degraded(self) -> bool:
        """Operator/driver surface: force a restore probe NOW (the
        barrier clock does this automatically). True = fully restored."""
        with self.lock:
            return self._maybe_restore_degraded()

    def _enter_degraded(
        self, epoch: int, staged, cause: BaseException
    ) -> None:
        """The store became unavailable mid-epoch (breaker open or
        retry budget exhausted): spill the staged deltas locally, pause
        compaction, keep serving queries from live/HBM state. The
        spilled epochs replay — in order — once the breaker half-opens
        (``_maybe_restore_degraded``)."""
        with self._degraded_lock:
            first = not self._degraded
            self._degraded = True
            self._spill.spill(epoch, staged)
        if first:
            self._compact_pause.set()
            REGISTRY.counter("degraded_entries_total").inc()
            REGISTRY.gauge("degraded_mode").set(1.0)
            EVENT_LOG.record(
                "degraded", epoch=epoch, cause=repr(cause)
            )

    def _commit_or_degrade(self, epoch: int, staged, tr=None) -> bool:
        """The single durable-commit gate for the sync path and the
        async worker: returns True iff the epoch is durable; a store-
        unavailable failure degrades instead of raising (any OTHER
        failure propagates — the failed-barrier recovery contract)."""
        with self._degraded_lock:
            if self._degraded:
                self._spill.spill(epoch, staged)
                return False
        try:
            self.mgr.commit_staged(epoch, staged, trace=tr)
            return True
        except STORE_UNAVAILABLE as e:
            self._enter_degraded(epoch, staged, e)
            return False

    def _maybe_restore_degraded(self) -> bool:
        """Probe the healed store: replay spilled epochs in order
        through the normal commit path. Called at every barrier (the
        breaker's cooldown gates how often the store is actually
        touched). Returns True when the runtime left degraded mode."""
        if not self._degraded:
            return False
        br = self.store_breaker
        if br is not None and not br.allow():
            return False  # still cooling down: no store touch at all
        replayed = []
        restored = False
        with self._degraded_lock:
            if not self._degraded:
                return False
            try:
                for epoch in self._spill.epochs():
                    if epoch <= self.mgr.max_committed_epoch:
                        # already covered by the manifest (e.g. a
                        # replay attempt that committed but failed
                        # later): the spill entry is redundant
                        self._spill.remove(epoch)
                        continue
                    staged = self._spill.load(epoch)
                    # replay is idempotent: a previous half-committed
                    # attempt left orphan SSTs at the same paths which
                    # this put simply overwrites; the manifest is the
                    # only durability authority
                    self.mgr.commit_staged(epoch, staged)
                    self._spill.remove(epoch)
                    replayed.append(epoch)
            except STORE_UNAVAILABLE:
                # breaker re-opened mid-replay; already-replayed epochs
                # ARE durable — only the tail stays spilled
                pass
            else:
                self._degraded = False
                restored = True
        # durable hooks (sink release — arbitrary external work) run
        # OUTSIDE the lock so the async worker never stalls behind them
        if replayed:
            REGISTRY.counter("degraded_epochs_replayed_total").inc(
                len(replayed)
            )
        for epoch in replayed:
            self._on_epoch_durable(epoch)
        if not restored:
            return False
        REGISTRY.gauge("degraded_mode").set(0.0)
        EVENT_LOG.record(
            "restored",
            epochs_replayed=len(replayed),
            epoch=self.mgr.max_committed_epoch,
        )
        self._compact_pause.clear()
        self._kick_compactor()
        return True

    # -- checkpoint lane -------------------------------------------------
    def _commit(self, epoch: int, tr: Optional[EpochTrace] = None) -> None:
        self._raise_worker_error()
        # stage on the main thread (device pull + eager mark flips, with
        # the duplicate-table_id check) — ONE code path with the sync
        # commit (CheckpointManager.stage / commit_staged)
        t_staged = time.perf_counter()
        with span("checkpoint.stage"):
            staged = self.mgr.stage(self._staging_executors())
        if tr is not None:
            tr.add_stage(
                "checkpoint_stage", (time.perf_counter() - t_staged) * 1e3
            )
        REGISTRY.counter("checkpoints_total").inc()
        REGISTRY.gauge("checkpoint_staged_tables").set(len(staged))
        if not self.async_checkpoint:
            if self._commit_or_degrade(epoch, staged, tr):
                self.checkpoint_sync_ms.append(
                    (time.perf_counter() - t_staged) * 1e3
                )
                self._on_epoch_durable(epoch)
                self._kick_compactor()
            return
        with self._inflight_lock:
            self._inflight += 1
        self._work_q.append((epoch, staged, t_staged, tr))
        self._ensure_worker()
        self._work_event.set()

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._worker_loop, daemon=True
            )
            self._worker.start()

    def _worker_loop(self):
        while True:
            self._work_event.wait(timeout=0.5)
            self._work_event.clear()
            while self._work_q:
                epoch, staged, t_staged, tr = self._work_q.popleft()
                try:
                    if self._work_err or self._work_abort.is_set():
                        # a prior epoch failed to commit (or recovery is
                        # aborting the lane): committing later epochs
                        # would persist a manifest covering a hole
                        # (silent data loss on recovery) and release
                        # sink output for unpersisted state — drop
                        # everything until the caller recover()s
                        continue
                    # single-worker FIFO queue -> epoch order holds;
                    # store-unavailable failures degrade (spill) rather
                    # than poisoning the lane — the stream keeps going
                    with span("checkpoint.commit", epoch=epoch):
                        durable = self._commit_or_degrade(
                            epoch, staged, tr
                        )
                    if durable:
                        self.checkpoint_sync_ms.append(
                            (time.perf_counter() - t_staged) * 1e3
                        )
                        self._on_epoch_durable(epoch)
                        self._kick_compactor()
                except BaseException as e:  # surfaced on main thread
                    self._work_err.append(e)
                finally:
                    with self._inflight_lock:
                        self._inflight -= 1

    def _on_epoch_durable(self, epoch: int) -> None:
        """The epoch's manifest is persisted: release deferred sink
        deliveries (exactly-once: sink output never precedes the
        durability of the state that produced it), and prune the
        partial-recovery replay buffer past the durable frontier.
        Fragments fenced for a deferred partial recovery are EXCLUDED:
        their held output belongs to state that is about to roll back
        and replay — releasing it would double-deliver."""
        for ex in self._staging_executors():
            fn = getattr(ex, "on_epoch_durable", None)
            if fn is not None:
                fn(epoch)
        self._prune_replay(epoch)

    # -- compaction lane (off the commit path) ---------------------------
    def _kick_compactor(self):
        if self.mgr is None:
            return
        if not self.mgr.tables_needing_compaction():
            return
        if self._compactor is None or not self._compactor.is_alive():
            self._compactor = threading.Thread(
                target=self._compactor_loop, daemon=True
            )
            self._compactor.start()
        self._compact_event.set()

    def _compactor_loop(self):
        """Dedicated compaction worker (compactor_runner.rs:62 role):
        full-merges long SST runs without ever blocking the commit lane
        or FLUSH."""
        while True:
            self._compact_event.wait(timeout=0.5)
            self._compact_event.clear()
            # clear idle BEFORE checking pause: recover() sets pause
            # then waits for idle, so the reverse order here closes the
            # window where compaction slips past a just-set pause
            self._compact_idle.clear()
            try:
                if self._compact_pause.is_set():
                    continue
                for table_id in self.mgr.tables_needing_compaction():
                    if self._compact_pause.is_set():
                        break
                    self.mgr.compact_once(table_id, self.mgr.max_committed_epoch)
            except Exception as e:
                # best-effort (next commit re-kicks) but never silent:
                # a persistently failing compaction must be visible
                self.compaction_errors.append(e)
                REGISTRY.counter("compaction_errors_total").inc()
            finally:
                self._compact_idle.set()

    def wait_compaction(self) -> None:
        """Block until no table needs compaction (or compaction is
        failing/paused — a doomed compaction must not hang callers)."""
        while (
            self.mgr is not None
            and self.mgr.tables_needing_compaction()
            and not self.compaction_errors
            and not self._compact_pause.is_set()
            and self._compactor is not None
            and self._compactor.is_alive()
        ):
            self._compact_event.set()
            time.sleep(0.002)
        self._compact_idle.wait()

    def wait_checkpoints(self) -> None:
        """Join the async lane (the FLUSH / sync-epoch analogue).
        Compaction intentionally does NOT block this (it runs on its
        own worker — ADVICE r2: inline compaction stalled FLUSH)."""
        if self.in_flight_barriers > 1:
            self.wait_epochs()  # staging happens in the closer lane
        while True:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.002)
        self._raise_worker_error()

    def _raise_worker_error(self):
        if self._work_err:
            raise RuntimeError(
                "async checkpoint failed"
            ) from self._work_err[0]

    def p99_checkpoint_sync_ms(self) -> float:
        """p99 of stage->durable latency (what the reference's <1s
        checkpoint target measures — includes SST build + upload +
        manifest commit, not just staging)."""
        if not self.checkpoint_sync_ms:
            return 0.0
        return float(np.percentile(self.checkpoint_sync_ms, 99))

    # -- recovery --------------------------------------------------------
    def _quiesce(self) -> None:
        """Drain the async commit lane and in-flight worker steps.
        Leaves the abort flags SET — recover() clears them after the
        restore. Idempotent (auto-recovery quiesces before growing
        capacities; recover() quiesces again trivially)."""
        # abort the async lane FIRST: staged epochs still queued refer
        # to pre-recovery state; committing one after the restore would
        # advance the manifest past the epoch we just recovered to
        self._closer_abort.set()
        with self._closer_cv:
            self._closer_cv.notify_all()
            self._closer_cv.wait_for(lambda: not self._closer_q, timeout=150)
        self._work_abort.set()
        while True:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.002)

    def recover(self, fragments: Optional[Sequence[str]] = None) -> None:
        """Rebuild fragment state from the last committed epoch.

        With ``fragments``, the recovery is FRAGMENT-SCOPED: only the
        named fragments' pipelines rebuild, restore their state tables,
        and replay their buffered inputs — every other fragment's live
        state (and the epoch clock) is untouched. Without it, the full
        stop-the-world restore (today's contract)."""
        if not self.mgr:
            raise RuntimeError("no object store configured")
        # manual recovery mirrors the auto path's capture hygiene
        PROFILER.abort_captures()
        blackbox.SENTINEL.abort_capture()
        blackbox.SENTINEL.clear_wedge()
        from risingwave_tpu.deviceprof import DEVICEPROF

        DEVICEPROF.on_recovery()
        if fragments is not None:
            scope = set(fragments)
            unknown = scope - set(self.fragments)
            if unknown:
                raise KeyError(f"unknown fragments {sorted(unknown)}")
            # close the scope over subscribers: _replay_fragment discards
            # replay outputs on the assumption every subscriber replays
            # its OWN log — a half-closed manual scope would starve them
            frontier = list(scope)
            while frontier:
                for sub, _side in self._subs.get(frontier.pop(), ()):
                    if sub not in scope:
                        scope.add(sub)
                        frontier.append(sub)
            # same replay-window guard the auto path enforces: replaying
            # a cleared/late-anchored log would silently drop the
            # un-durable window — refuse and point at full recovery
            committed = self.mgr.max_committed_epoch
            with self._replay_lock:
                lost = sorted(
                    n
                    for n in scope
                    if self._replay_floor.get(n, 0) is None
                    or self._replay_floor.get(n, 0)
                    > min(committed, self._replay_covered.get(n, committed))
                )
            if lost:
                raise RuntimeError(
                    f"replay window lost for {lost} (buffer overflow or "
                    "not yet re-anchored at a durable barrier) — a scoped "
                    "recovery would silently drop their un-durable "
                    "window; use a full recover()"
                )
            # an explicit scoped recovery is a manual store probe too
            if self.store_breaker is not None:
                self.store_breaker.force_probe()
            self.wait_checkpoints()
            self._partial_recover(scope, "manual recover(fragments=...)")
            self._pending_partial = None
            self.last_recovery_mode = "partial"
            return
        # an explicit recovery is a manual store probe: let it through
        # an open breaker (its reads settle the breaker either way)
        if self.store_breaker is not None:
            self.store_breaker.force_probe()
        self._quiesce()
        # quiesce compaction: its GC deletes SSTs that recovery's
        # read_table may be about to read
        self._compact_pause.set()
        try:
            self._compact_idle.wait()
            self.mgr.recover(self.executors())
        finally:
            self._compact_pause.clear()
            self._work_abort.clear()
        # degraded spill of rolled-back epochs is stale: recovery lands
        # on the last DURABLE manifest; sources replay the spilled
        # epochs' data, so replaying the spill too would double-apply
        with self._degraded_lock:
            if self._degraded or self._spill.epochs():
                discarded = self._spill.discard_all()
                if self._degraded:
                    EVENT_LOG.record(
                        "degraded_discard", epochs=discarded
                    )
                self._degraded = False
        REGISTRY.gauge("degraded_mode").set(0.0)
        # rolled-back epochs must not leave stale sink batches behind:
        # replay would re-hold the same rows -> duplicate delivery
        for ex in self.executors():
            fn = getattr(ex, "discard_pending", None)
            if fn is not None:
                fn()
            # captured deltas of rolled-back epochs are stale
            fn = getattr(ex, "discard_captured", None)
            if fn is not None:
                fn()
        self._work_err.clear()
        self._closer_err.clear()
        self._closer_abort.clear()
        # a full restore supersedes any deferred partial recovery and
        # resets the replay window: everything rolls back to the
        # committed epoch and sources replay from their offsets, so the
        # buffered inputs are stale
        self._pending_partial = None
        with self._replay_lock:
            self._replay.clear()
            self._replay_floor.clear()
            self._replay_covered.clear()
        self._epoch = self.mgr.max_committed_epoch
        for p in self.fragments.values():
            p._epoch = self._epoch
        # executors with recovery hooks (e.g. sink log stores dropping
        # rolled-back epochs) learn the recovered frontier
        for ex in self.executors():
            fn = getattr(ex, "on_recover", None)
            if fn is not None:
                fn(self._epoch)
        # stale published snapshots may postdate the restored epoch
        self.arrangements.on_recovery(self._epoch)
        EVENT_LOG.record("recovery", mode="restore", epoch=self._epoch)
