"""NotificationHub — versioned catalog-change pub/sub.

Reference: src/meta/src/manager/notification.rs + the frontend's
ObserverManager (src/frontend/src/observer/observer_manager.rs:40):
meta assigns every catalog mutation a monotonically increasing notify
version and pushes it to subscribed frontends/compute nodes; a late
subscriber first receives a SNAPSHOT at some version and then only
deltas > that version, so no mutation is ever missed or applied twice.

TPU re-design: sessions are in-process frontends sharing one runtime;
the hub carries (version, op, kind, name, payload) tuples where the
payload holds direct object references (schema, mview handle, source
executor) instead of protobuf — the process boundary version of this
rides the cluster wire's DDL broadcast (cluster/multi_node.py).

Ordering: versions are contiguous; each observer holds a reorder
buffer and applies notifications strictly in version order, so a
publish racing a subscription's backlog replay can never deliver v3
before v2 (each mutation applies exactly once, in order).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Tuple


class Notification:
    __slots__ = ("version", "op", "kind", "name", "payload")

    def __init__(self, version, op, kind, name, payload):
        self.version = version  # monotonically increasing, contiguous
        self.op = op  # "add" | "drop"
        self.kind = kind  # "table" | "mv" | "source" | "function"
        self.name = name
        self.payload = payload  # dict of object refs (schema, mview, ...)


class _Observer:
    """Per-observer in-order exactly-once delivery: a reorder buffer
    keyed by version drains contiguously from ``seen``."""

    def __init__(self, cb: Callable[[Notification], None], seen: int):
        self.cb = cb
        self.seen = seen
        self._pending: Dict[int, Notification] = {}
        # RLock: an observer callback may itself publish (re-entrant)
        self._lock = threading.RLock()

    def deliver(self, n: Notification) -> None:
        with self._lock:
            if n.version <= self.seen:
                return  # duplicate
            self._pending[n.version] = n
            while self.seen + 1 in self._pending:
                m = self._pending.pop(self.seen + 1)
                self.seen += 1
                self.cb(m)


class NotificationHub:
    """The meta-side notifier. Thread-safe; callbacks run outside the
    hub lock (an observer may publish), in version order per observer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._version = 0
        self._log: List[Notification] = []
        self._observers: Dict[int, _Observer] = {}
        self._next_obs = 0

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def publish(self, op, kind, name, payload=None) -> int:
        with self._lock:
            self._version += 1
            n = Notification(self._version, op, kind, name, payload or {})
            self._log.append(n)
            if op == "drop":
                # free the dropped relation's object refs held by the
                # log (late subscribers see an empty-payload add that
                # the following drop cancels; observers skip it)
                for old in self._log:
                    if old.name == name and old.kind == kind and old.op == "add":
                        old.payload = {}
            observers = list(self._observers.values())
        for obs in observers:
            obs.deliver(n)
        return n.version

    def subscribe(
        self,
        callback: Callable[[Notification], None],
        from_version: int = 0,
    ) -> int:
        """Register an observer; mutations with version > from_version
        replay IMMEDIATELY (the snapshot-then-deltas contract), then
        live pushes follow — in version order even against concurrent
        publishes. Returns an observer id for unsubscribe."""
        obs = _Observer(callback, from_version)
        with self._lock:
            backlog = [n for n in self._log if n.version > from_version]
            oid = self._next_obs
            self._next_obs += 1
            self._observers[oid] = obs
        for n in backlog:
            obs.deliver(n)
        return oid

    def unsubscribe(self, oid: int) -> None:
        with self._lock:
            self._observers.pop(oid, None)

    def snapshot(self) -> Tuple[int, List[Notification]]:
        """(current version, full mutation log) — net state is the
        log folded add/drop per (kind, name)."""
        with self._lock:
            return self._version, list(self._log)
