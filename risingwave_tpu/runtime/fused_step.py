"""Fused device-resident barrier step — compile a fragment's fusible
executor run into ONE donated jitted program per barrier.

PR 6's profiler pinned the 10x-throughput gap on the host dispatch
wall (~319ms/barrier of Python walking executor chains vs 0.24ms of
device compute), and the fusion analyzer's FUSION_REPORT.json named
the blockers per executor. This module is the engine that cashes the
analysis in (ROADMAP item 1, the TiLT direction from PAPERS.md:
compile whole time-centric queries instead of interpreting
per-operator):

- :func:`fuse_chain` rewrites an actor chain's maximal fusible run —
  ``stateless-pure*  [HashAgg]  stateless-pure*  [DeviceMaterialize]
  stateless-pure*`` — into a :class:`FusedChainExecutor`. Anything
  the run cannot absorb (joins, dedup, host materializers, watermark
  generators, subclasses) passes through untouched and keeps the
  per-executor interpreted path: interpretation IS the automatic
  fallback, per run, not per process.
- :class:`FusedChainExecutor` buffers the epoch's chunks (the
  EpochBatchedAgg discipline: pow2-padded stacked batches, signature
  changes flush) and, at the barrier, runs ONE jitted
  ``fused_step(state_pytree, chunks) -> (state_pytree, deltas,
  scalars)`` with ``donate_argnums`` on the state pytree — keyed agg
  state and the device MV live in HBM across barriers; the host
  touches only ingest and the staged-scalar commit read.
- State ownership never moves: the member executors keep their state
  between programs (the wrapper reads it per barrier and writes the
  donated program's outputs back), so checkpoint/restore, recovery
  rebuilds, cold-tier hooks, snapshots and the shape governor all
  keep working against the original objects.

Compile discipline: the program's statics are value-hashable
(:class:`FusedPlan` hashes the member steps' ``functools.partial``
keys, the ComposedSteps contract), so graph rebuilds and recovery
re-fuse into the SAME compiled program; distinct (flush_rounds, pads,
has_data) combinations are a small closed set in steady state.
"""

from __future__ import annotations

import os
from contextlib import nullcontext
from dataclasses import dataclass, fields as _dc_fields
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.executors.dedup import (
    AppendOnlyDedupExecutor,
    dedup_step_fn,
)
from risingwave_tpu.executors.dynamic_filter import (
    DynamicMaxFilterExecutor,
    filter_step_fn,
)
from risingwave_tpu.executors.epoch_batch import (
    ComposedSteps,
    _compose_lint_infos,
)
from risingwave_tpu.executors.hash_agg import (
    HashAggExecutor,
    _epoch_reduced_fn,
    delta_to_chunk,
)
from risingwave_tpu.executors.hash_join import (
    HashJoinExecutor,
    join_step_fn,
)
from risingwave_tpu.executors.materialize import (
    DeviceMaterializeExecutor,
    mv_step_fn,
)
from risingwave_tpu import integrity
from risingwave_tpu.expr.expr import StaticTree, lift_literals, param_scope
from risingwave_tpu.ops import agg as agg_ops
from risingwave_tpu.parallel.sharded_agg import stack_chunks
from risingwave_tpu.profiler import PROFILER
from risingwave_tpu.runtime.bucketing import flush_pad_schedule

__all__ = [
    "FusedChainExecutor",
    "FusedTwoInputExecutor",
    "expand_fused",
    "fuse_chain",
    "fuse_pipeline",
    "fuse_two_input",
    "fused_cache_stats",
    "fused_enabled",
    "fused_fragments",
    "fusion_refusals",
    "lift_enabled",
    "lift_plan",
    "pipeline_depth",
    "two_input_enabled",
]


def fused_enabled() -> bool:
    """RW_FUSED_STEP=0 is the kill switch: the graph runtime then
    falls back to the per-epoch batched (still interpreted) path."""
    return os.environ.get("RW_FUSED_STEP", "1").strip().lower() not in (
        "0",
        "off",
        "false",
    )


def lift_enabled() -> bool:
    """RW_FUSED_LIFT=0 disables multi-tenant constant lifting: every
    parameter variant then compiles its own fused program (the
    pre-PR-12 behavior)."""
    return os.environ.get("RW_FUSED_LIFT", "1").strip().lower() not in (
        "0",
        "off",
        "false",
    )


def two_input_enabled() -> bool:
    """RW_FUSED_TWO_INPUT=0 disables whole-pipeline two-input fusion:
    two-input pipelines then fall back to the PR 10 per-chain policy
    (epoch-batched agg side, interpreted join, fused-or-interpreted MV
    tail) — the differential-testing twin of the fused path."""
    return os.environ.get(
        "RW_FUSED_TWO_INPUT", "1"
    ).strip().lower() not in ("0", "off", "false")


def pipeline_depth(explicit: Optional[int] = None) -> int:
    """K-barrier device pipelining depth: the fused wrapper defers its
    blocking staged-scalar materialization (and latch checks, telemetry
    decode, input retirement) to every K-th barrier, so K consecutive
    barriers' donated programs sit queued on the device back-to-back
    with ZERO host synchronization between them — the host enqueues
    barrier N+1 while N still runs and leaves the steady state
    entirely. Watermark/checkpoint walks stay at the K-boundary;
    members remain the system of record with per-barrier state
    write-back (the written-back arrays are futures of the in-flight
    program, so recovery/governor/cold-tier contracts see exactly the
    state they always did once they materialize). K=1 (default) is the
    per-barrier fused behavior."""
    if explicit is not None:
        return max(1, int(explicit))
    try:
        return max(1, int(os.environ.get("RW_FUSED_PIPELINE_DEPTH", "1")))
    except ValueError:
        return 1


# ---------------------------------------------------------------------------
# fusion-refusal provenance (the anti-silent-fallback contract)
# ---------------------------------------------------------------------------

_REFUSALS: List[dict] = []
_REFUSALS_CAP = 256  # bounded: graph rebuilds re-refuse per spawn


def _refuse(label: str, reason: str, executor: Optional[str] = None):
    """Record WHY a chain/pipeline was left interpreted (RW-E807):
    fusion policy must never fall back silently — every refusal
    carries fragment + executor provenance, queryable via
    :func:`fusion_refusals` and mirrored into the meta event log."""
    rec = {
        "code": "RW-E807",
        "fragment": label,
        "executor": executor,
        "message": reason,
    }
    if len(_REFUSALS) >= _REFUSALS_CAP:
        del _REFUSALS[: _REFUSALS_CAP // 2]
    _REFUSALS.append(rec)
    try:
        from risingwave_tpu.event_log import EVENT_LOG

        EVENT_LOG.record("fusion_refused", **rec)
    except Exception:  # noqa: BLE001 — provenance is best effort
        pass
    return None


def fusion_refusals(clear: bool = False) -> List[dict]:
    """Every recorded fusion refusal (RW-E807 provenance) since process
    start (or the last ``clear=True`` call)."""
    out = list(_REFUSALS)
    if clear:
        _REFUSALS.clear()
    return out


# ---------------------------------------------------------------------------
# static plan (jit cache key)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AggStatics:
    """The HashAgg member's jit statics (all value-hashable)."""

    calls: tuple
    group_keys: tuple
    nullable: tuple
    out_cap: int
    float_extremes: tuple
    has_minput: bool


@dataclass(frozen=True)
class FusedPlan:
    """The fused program's static shape: pure-step segments around at
    most one HashAgg and at most one DeviceMaterialize (agg strictly
    before mv). ``pre``/``mid``/``post`` are ComposedSteps (value-
    hashable compositions of the members' ``pure_step()`` partials),
    so two plans over equal step sequences share one compiled
    program."""

    pre: Optional[ComposedSteps]
    agg: Optional[AggStatics]
    mid: Optional[ComposedSteps]
    mv_pk: Optional[tuple]
    mv_cols: Optional[tuple]
    post: Optional[ComposedSteps]

    @property
    def has_mv(self) -> bool:
        return self.mv_pk is not None


def _delta_chunk(delta: dict, a: AggStatics, pad: Optional[int]) -> StreamChunk:
    """The flush delta -> chunk decode, shared with the interpreted
    path (hash_agg.delta_to_chunk is the one lane-contract decoder),
    with the host-chosen static pad slice."""
    return delta_to_chunk(delta, a.group_keys, a.nullable, a.calls, pad)


def _fused_barrier_fn(
    states, stacked, params, plan, flush_rounds, pads, has_data
):
    """The whole fragment-barrier as one pure function over
    ``states = (agg_state, mv_state)``:

    data phase  — the epoch's stacked chunks through the pure prefix
                  into the agg's flatten+reduce epoch path (ONE table
                  touch per distinct key), or — agg-less runs —
                  through the steps into the device MV as one
                  flattened batch;
    flush phase — ``flush_rounds`` device flushes of the agg's dirty
                  groups, each delta walking mid-steps -> device MV ->
                  post-steps (the fragment's per-barrier emission);
    scalars     — the members' barrier latches + occupancy counters
                  PLUS the device-computed telemetry lane (rows
                  applied, dirty groups drained, MV rows written) —
                  all packed into one int64 lane for the overlapped
                  finish_barrier read: per-member visibility at zero
                  extra dispatches and zero new host syncs.

    Each phase carries a ``jax.named_scope`` (fused/apply, fused/flush,
    fused/mv_write, fused/scalar_pack) so a ``jax_trace`` capture
    segments the ONE compiled program back into stages
    (deviceprof.parse_fused_stages).
    """
    # lifted-literal parameter vectors (``params``) bind for the whole
    # trace: plan segments containing LiftedLit slots read them as a
    # RUNTIME operand, so K parameter variants of one plan shape share
    # this single compiled program (multi-tenant compile sharing)
    with param_scope(params):
        return _fused_barrier_body(
            states, stacked, plan, flush_rounds, pads, has_data
        )


def _fused_barrier_body(states, stacked, plan, flush_rounds, pads, has_data):
    agg_st, mv_st = states
    outs: List[StreamChunk] = []
    mv_rows = jnp.zeros((), jnp.int32)

    def _through_mv(chunk):
        nonlocal mv_st, mv_rows
        if plan.mid is not None:
            chunk = plan.mid(chunk)
        if plan.has_mv:
            with jax.named_scope("fused/mv_write"):
                mv_rows = mv_rows + jnp.sum(chunk.valid.astype(jnp.int32))
                mtable, mstate = mv_st
                mtable, mstate = mv_step_fn(
                    mtable, mstate, chunk, plan.mv_pk, plan.mv_cols
                )
                mv_st = (mtable, mstate)
        if plan.post is not None:
            chunk = plan.post(chunk)
        return chunk

    rows_in = jnp.zeros((), jnp.int32)
    if has_data:
        rows_in = jnp.sum(stacked.valid.astype(jnp.int32))
        with jax.named_scope("fused/apply"):
            if plan.agg is not None:
                a = plan.agg
                table, st, dropped, minput, mi_bad = agg_st
                if a.has_minput:
                    table, st, dropped, minput, mi_bad = _epoch_reduced_fn(
                        table, st, dropped, stacked, a.calls, a.group_keys,
                        a.nullable, plan.pre, minput, mi_bad,
                    )
                else:
                    table, st, dropped = _epoch_reduced_fn(
                        table, st, dropped, stacked, a.calls, a.group_keys,
                        a.nullable, plan.pre,
                    )
                agg_st = (table, st, dropped, minput, mi_bad)
            else:
                chunks = (
                    jax.vmap(plan.pre)(stacked)
                    if plan.pre is not None
                    else stacked
                )
                # flatten the epoch into one batch: the MV's last-
                # occurrence-per-pk mask makes one flat step equivalent
                # to applying the chunks in order
                flat = jax.tree.map(
                    lambda x: x.reshape((-1,) + x.shape[2:]), chunks
                )
                outs.append(_through_mv(flat))

    # dirty groups pending at the barrier, sampled AFTER the epoch's
    # applies and BEFORE the flush drains them — the device-computed
    # twin of the interpreted agg's jnp.sum(state.dirty) at flush time
    dirty_groups = jnp.zeros((), jnp.int32)
    if plan.agg is not None:
        dirty_groups = jnp.sum(agg_st[1].dirty.astype(jnp.int32))

    if plan.agg is not None and flush_rounds:
        a = plan.agg
        table, st, dropped, minput, mi_bad = agg_st
        with jax.named_scope("fused/flush"):
            for r in range(flush_rounds):
                st, delta = agg_ops.flush(
                    st, table.keys, a.out_cap, a.float_extremes
                )
                outs.append(_through_mv(_delta_chunk(delta, a, pads[r])))
        agg_st = (table, st, dropped, minput, mi_bad)

    with jax.named_scope("fused/scalar_pack"):
        scal = []
        if plan.agg is not None:
            table, st, dropped, minput, mi_bad = agg_st
            scal += [dropped, st.minmax_retracted, mi_bad, table.occupancy()]
        if plan.has_mv:
            mtable, mstate = mv_st
            scal += [mstate.dropped, mtable.occupancy()]
        if scal:
            # telemetry tail rides the same staged read the barrier
            # already pays: rows applied, dirty groups, MV rows
            scal += [rows_in, dirty_groups, mv_rows]
            # state digests ride the SAME lane (integrity layer): the
            # fused twin of each member's host state_digest(), decoded
            # in _on_barrier_scalars — zero extra dispatches
            with jax.named_scope("fused/digest"):
                if plan.agg is not None:
                    table, st = agg_st[0], agg_st[1]
                    scal.append(
                        integrity.device_digest(
                            *integrity.agg_lanes(table, st)
                        )
                    )
                if plan.has_mv:
                    mtable, mstate = mv_st
                    scal.append(
                        integrity.device_digest(
                            *integrity.mv_lanes(mtable, mstate)
                        )
                    )
        packed = (
            jnp.stack([jnp.asarray(x).astype(jnp.int64) for x in scal])
            if scal
            else None
        )
    return (agg_st, mv_st), tuple(outs), packed


_fused_barrier_step = partial(
    jax.jit,
    static_argnames=("plan", "flush_rounds", "pads", "has_data"),
    donate_argnums=(0,),
)(_fused_barrier_fn)


# ---------------------------------------------------------------------------
# multi-tenant compile sharing: lift per-MV constants to runtime operands
# ---------------------------------------------------------------------------

_LIFT_STATS = {"lifted": 0, "rejected": 0}


def lift_plan(plan: FusedPlan):
    """Rewrite the plan's pure segments with numeric literals lifted
    into parameter slots. Returns ``(lifted_plan, params)`` — params
    being the ``{"i": int64[...], "f": float64[...]}`` operand the
    fused program receives at dispatch — or ``(None, None)`` when the
    plan carries no liftable constants. Two plans that differ only in
    literal VALUES produce EQUAL lifted plans (same slot structure),
    so the jit cache serves both from one compiled executable."""
    ints: List[int] = []
    floats: List[float] = []

    def lift_arg(a):
        if isinstance(a, StaticTree):
            return StaticTree(lift_literals(a.value, ints, floats))
        return a

    def lift_steps(cs: Optional[ComposedSteps]) -> Optional[ComposedSteps]:
        if cs is None:
            return None
        return ComposedSteps(
            [
                partial(
                    s.func,
                    *(lift_arg(a) for a in s.args),
                    **{k: lift_arg(v) for k, v in s.keywords.items()},
                )
                for s in cs.steps
            ]
        )

    import dataclasses as _dc

    lifted = _dc.replace(
        plan,
        pre=lift_steps(plan.pre),
        mid=lift_steps(plan.mid),
        post=lift_steps(plan.post),
    )
    if not ints and not floats:
        return None, None
    params = {
        "i": jnp.asarray(ints, jnp.int64),
        "f": jnp.asarray(floats, jnp.float64),
    }
    return lifted, params


def fused_cache_stats() -> dict:
    """The compile-sharing evidence: how many distinct fused programs
    the process actually compiled (jit cache entries) vs how many
    wrappers lifted constants into a shared shape."""
    try:
        compiled = int(_fused_barrier_step._cache_size())
    except Exception:  # noqa: BLE001 — jax-internal surface
        compiled = -1
    return {
        "compiled_programs": compiled,
        "plans_lifted": _LIFT_STATS["lifted"],
        "plans_lift_rejected": _LIFT_STATS["rejected"],
    }


# ---------------------------------------------------------------------------
# the wrapper executor
# ---------------------------------------------------------------------------


def _is_pure(ex: Executor) -> bool:
    """A stateless member the fused program can absorb: pure step, no
    generated watermarks, no barrier behavior (the wrapper never calls
    member.on_barrier for pure members)."""
    return (
        ex.pure_step() is not None
        and type(ex).emit_watermark is Executor.emit_watermark
        and type(ex).on_barrier is Executor.on_barrier
    )


class FusedChainExecutor(Executor):
    """One fusible run ``[pure*, HashAgg?, pure*, DeviceMaterialize?,
    pure*]`` executed as a single donated device program per barrier.

    Drop-in chain element (the EpochBatchedAggExecutor integration
    contract): ``apply`` buffers, ``on_barrier`` runs the program and
    returns the fragment's per-barrier emission, ``finish_barrier``
    materializes the packed member scalars and runs every member's
    latch checks at their original raise points. The member executor
    OBJECTS stay the system of record — checkpoint registries,
    recovery restores, the cold tier and the shape governor all keep
    talking to them; this wrapper is an execution strategy, not a
    state owner.
    """

    def __init__(
        self,
        members: Sequence[Executor],
        label: str = "fragment",
        covers_whole_chain: bool = False,
    ):
        self.members = list(members)
        self.label = label
        self.covers_whole_chain = covers_whole_chain
        self.agg: Optional[HashAggExecutor] = None
        self.mv: Optional[DeviceMaterializeExecutor] = None
        pre: List[Executor] = []
        mid: List[Executor] = []
        post: List[Executor] = []
        for ex in self.members:
            if type(ex) is HashAggExecutor:
                if self.agg is not None or self.mv is not None:
                    raise ValueError(
                        "fused run supports one HashAgg, before the MV"
                    )
                self.agg = ex
            elif type(ex) is DeviceMaterializeExecutor:
                if self.mv is not None:
                    raise ValueError("fused run supports one device MV")
                self.mv = ex
            elif _is_pure(ex):
                (post if self.mv is not None
                 else mid if self.agg is not None
                 else pre).append(ex)
            else:
                raise ValueError(f"{type(ex).__name__} is not fusible")
        steps = lambda exs: (
            ComposedSteps([e.pure_step() for e in exs]) if exs else None
        )
        agg_statics = None
        if self.agg is not None:
            agg_statics = AggStatics(
                calls=self.agg.calls,
                group_keys=self.agg.group_keys,
                nullable=self.agg.nullable,
                out_cap=self.agg.out_cap,
                float_extremes=self.agg._float_extremes,
                has_minput=bool(self.agg.minput),
            )
        self.plan = FusedPlan(
            pre=steps(pre),
            agg=agg_statics,
            mid=steps(mid),
            mv_pk=self.mv.pk if self.mv is not None else None,
            mv_cols=self.mv.columns if self.mv is not None else None,
            post=steps(post),
        )
        # multi-tenant compile sharing: literals lifted to runtime
        # operands, accepted only after a dtype-equivalence proof at
        # the first data barrier (weak-vs-strong scalar promotion can
        # change result dtypes — correctness beats sharing)
        self._exec_plan = self.plan
        self._params = None
        self._lift_state = "off"
        if lift_enabled():
            lifted, params = lift_plan(self.plan)
            if lifted is not None:
                self._lift_candidate = (lifted, params)
                self._lift_state = "pending"
        self._buf: List[StreamChunk] = []
        self._sig = None
        # telemetry bookkeeping: padded lane count of the last staged
        # program's stacked input (masked-lane fill denominator) and
        # the last materialized telemetry dict (deviceprof mirror)
        self._last_lanes = 0
        self._telemetry: Optional[dict] = None
        # device digests decoded at the last barrier (integrity layer):
        # member key -> uint64 fold, the fused twin of state_digest()
        self.last_digests: dict = {}
        # the previous program's consumed inputs, held until the
        # barrier fence: dropping a buffer an in-flight async program
        # still reads BLOCKS the host until the program completes (the
        # deallocation sync) — exactly the dispatch-wall stall the
        # fused step exists to remove. finish_barrier (which awaits the
        # program anyway) retires them instead.
        self._retired = None

    # -- static metadata --------------------------------------------------
    def lint_info(self):
        infos = []
        for m in self.members:
            fn = getattr(m, "lint_info", None)
            info = fn() if fn is not None else None
            if info is None:
                return None  # opacity propagates; never guess
            infos.append(info)
        return _compose_lint_infos(infos)

    # -- data path --------------------------------------------------------
    @staticmethod
    def _signature(c: StreamChunk):
        return (
            c.capacity,
            tuple(sorted((k, str(v.dtype)) for k, v in c.columns.items())),
            tuple(sorted(c.nulls)),
        )

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        outs: List[StreamChunk] = []
        sig = self._signature(chunk)
        if self._sig is not None and sig != self._sig:
            # shape change mid-epoch: flush the homogeneous batch (the
            # stacking discipline); any MV passthrough surfaces here
            outs = self._run(flush=False, stage=False)
        self._sig = sig
        self._buf.append(chunk)
        return outs

    # -- control path -----------------------------------------------------
    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        if self.agg is not None and self.agg._cold_barrier_hook is not None:
            self.agg._cold_barrier_hook()
        outs = self._run(flush=True, stage=True)
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier()
        return outs

    def on_watermark(self, watermark: Watermark):
        # buffered rows precede the watermark in stream order; the
        # watermark itself walks the members interpreted (state lives
        # in the members between programs, so interop is exact)
        from risingwave_tpu.runtime.pipeline import _walk_watermark

        outs: List[StreamChunk] = []
        if self._buf:
            outs = self._run(flush=False, stage=False)
        wm, o = _walk_watermark(self.members, watermark)
        return wm, outs + o

    def finish_barrier(self) -> None:
        super().finish_barrier()
        for m in self.members:
            m.finish_barrier()  # no-op: members never stage under fusion
        # the fence above awaited the program: retiring its inputs is
        # now a plain free, not a hidden synchronization point
        self._retired = None

    def _on_barrier_scalars(self, vals) -> None:
        # telemetry FIRST: a tripped member latch raises below, and the
        # flight recorder must still see what the barrier did
        base = (4 if self.agg is not None else 0) + (
            2 if self.mv is not None else 0
        )
        if len(vals) >= base + 3:
            self._note_telemetry(vals, vals[base:base + 3])
        # digest tail (after the 3 telemetry scalars): the fused twin
        # of each member's state_digest(), in member order agg -> mv
        digs = {}
        j = base + 3
        if self.agg is not None and j < len(vals):
            digs["agg"] = integrity.digest_from_scalar(vals[j])
            j += 1
        if self.mv is not None and j < len(vals):
            digs["mv"] = integrity.digest_from_scalar(vals[j])
        self.last_digests = digs
        self._note_digests(digs)
        i = 0
        if self.agg is not None:
            self.agg._on_barrier_scalars(tuple(vals[0:4]))
            i = 4
        if self.mv is not None:
            self.mv._on_barrier_scalars(tuple(vals[i:i + 2]))

    def _note_digests(self, digs) -> None:
        """Land the per-barrier device digests in the telemetry dict
        (flight recorder + EpochTrace read it from there). Forensic,
        never load-bearing."""
        try:
            if digs and self._telemetry is not None:
                self._telemetry["state_digests"] = {
                    k: f"{v:016x}" for k, v in digs.items()
                }
        except Exception:  # noqa: BLE001
            pass

    def _note_telemetry(self, vals, tail) -> None:
        """Decode the packed telemetry lane into the deviceprof
        registry (host-side bookkeeping over values the barrier read
        anyway — zero extra device IO; never faults the barrier)."""
        try:
            rows_in, dirty_groups, mv_rows = (int(x) for x in tail)
            member_rows = {}
            occupancy = {}
            seen_agg = False
            for idx, m in enumerate(self.members):
                name = f"{idx}:{type(m).__name__}"
                if m is self.agg:
                    member_rows[name] = rows_in
                    occupancy["agg"] = int(vals[3])
                    seen_agg = True
                elif m is self.mv:
                    member_rows[name] = mv_rows
                    occupancy["mv"] = int(
                        vals[5 if self.agg is not None else 1]
                    )
                else:
                    # pure members see the input rows before the agg
                    # collapses them, the flush-delta rows after
                    member_rows[name] = mv_rows if seen_agg else rows_in
            # padded-lane waste over the members' state tables, from
            # the occupancies that rode the packed read (live lanes)
            # weighted by each member's state bytes — the live/capacity
            # accounting runtime/bucketing.padding_stats reads from the
            # device, here for free
            from risingwave_tpu.runtime.bucketing import padding_fraction

            pad_frac = padding_fraction(
                (ex.table.capacity, occupancy[key], ex.state_nbytes())
                for key, ex in (("agg", self.agg), ("mv", self.mv))
                if ex is not None and key in occupancy
            )
            lanes = self._last_lanes
            tel = {
                "rows_in": rows_in,
                "dirty_groups": dirty_groups,
                "mv_rows": mv_rows,
                "member_rows": member_rows,
                "occupancy": occupancy,
                "lanes_total": lanes,
                "lane_fill_frac": (
                    round(rows_in / lanes, 6) if lanes else 0.0
                ),
                "padding_bytes_frac": pad_frac,
            }
            self._telemetry = tel
            from risingwave_tpu.deviceprof import DEVICEPROF

            DEVICEPROF.note_telemetry(self.label, tel)
        except Exception:  # noqa: BLE001 — forensic, never load-bearing
            pass

    def _prove_lift(self, states, stacked, flush_rounds, pads) -> None:
        """Accept the lifted plan only when it is provably
        dtype-equivalent to the baked one over THIS input signature:
        abstract-trace both programs (eval_shape — no XLA) and compare
        every output aval. A weak-typed literal promoting differently
        than its strong int64/float64 parameter slot shows up here as
        a dtype mismatch — fall back to the baked plan for good."""
        lifted, params = self._lift_candidate
        ok = False
        try:
            abstract = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (states, stacked),
            )
            pav = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params
            )
            base = jax.eval_shape(
                lambda s, c: _fused_barrier_fn(
                    s, c, None, self.plan, flush_rounds, pads, True
                ),
                abstract[0],
                abstract[1],
            )
            lift = jax.eval_shape(
                lambda s, c, p: _fused_barrier_fn(
                    s, c, p, lifted, flush_rounds, pads, True
                ),
                abstract[0],
                abstract[1],
                pav,
            )
            ok = jax.tree.structure(base) == jax.tree.structure(
                lift
            ) and all(
                x.shape == y.shape and x.dtype == y.dtype
                for x, y in zip(
                    jax.tree.leaves(base), jax.tree.leaves(lift)
                )
            )
        except Exception:  # noqa: BLE001 — any trace surprise: keep baked
            ok = False
        if ok:
            self._exec_plan, self._params = lifted, params
            self._lift_state = "on"
            _LIFT_STATS["lifted"] += 1
        else:
            self._lift_state = "off"
            _LIFT_STATS["rejected"] += 1

    def _deviceprof_hook(
        self, states, stacked, flush_rounds, pads, has_data
    ) -> None:
        """Compiled-artifact roofline: analyze this (plan, bucket)
        combination ONCE via AOT lower+compile over abstract args —
        FLOPs / bytes-accessed / HBM footprint / compile ms for the
        exact program this barrier dispatches. Gated on the one
        DEVICEPROF.enabled check; never raises."""
        from risingwave_tpu.deviceprof import DEVICEPROF

        if not DEVICEPROF.enabled:
            return
        try:
            shape = (
                "x".join(map(str, stacked.valid.shape[:2]))
                if has_data
                else "-"
            )
            # member table capacities are part of the program's input
            # avals: growth mints a NEW compiled program, so it must
            # mint a new bucket too or the fragment keeps reporting
            # the pre-growth executable's modeled bytes
            caps = ".".join(
                str(ex.table.capacity)
                for ex in (self.agg, self.mv)
                if ex is not None
            )
            bucket = (
                f"fr{flush_rounds}_p{'.'.join(map(str, pads)) or '-'}"
                f"_d{int(has_data)}_n{shape}_c{caps or '-'}"
            )
            abstract = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (states, stacked),
            )
            # the deferred thunk closes over LOCALS only (abstract
            # shapes + the plan AS DISPATCHED): capturing self would
            # pin the whole executor (and its retired device buffers)
            # in the pending queue, and a post-rebuild plan mutation
            # would lower a program that no longer matches this bucket
            plan = self._exec_plan
            pav = (
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    self._params,
                )
                if self._params is not None
                else None
            )
            DEVICEPROF.ensure_program(
                f"fused:{self.label}",
                bucket,
                lambda: _fused_barrier_step.lower(
                    abstract[0],
                    abstract[1],
                    pav,
                    plan,
                    flush_rounds,
                    pads,
                    has_data,
                ),
                fragment=self.label,
            )
        except Exception:  # noqa: BLE001 — observability never faults
            pass

    def capture_checkpoint(self) -> None:
        for m in self.members:
            cap = getattr(m, "capture_checkpoint", None)
            if cap is not None:
                cap()

    # -- the program ------------------------------------------------------
    def _run(self, flush: bool, stage: bool) -> List[StreamChunk]:
        buf, self._buf, self._sig = self._buf, [], None
        has_data = bool(buf)
        stacked = None
        if has_data:
            n = len(buf)
            target = 1 << (n - 1).bit_length() if n > 1 else 1
            if target > n:
                c0 = buf[0]
                empty = StreamChunk(
                    c0.columns, jnp.zeros_like(c0.valid), c0.nulls, c0.ops
                )
                buf = buf + [empty] * (target - n)
            stacked = stack_chunks(buf)
            probe = jax.eval_shape(
                self.plan.pre if self.plan.pre is not None else (lambda c: c),
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype),
                    stacked,
                ),
            )
            incoming = len(buf) * probe.valid.shape[0]
            # host bookkeeping BEFORE the program: growth may rebuild
            # member state, and the program must see the final buffers
            if self.agg is not None:
                if self.agg._cold_stacked_hook is not None:
                    self.agg._cold_stacked_hook()
                self.agg._maybe_grow(incoming)
                self.agg._insert_bound += incoming
                self.agg._dirty_bound += incoming
            elif self.mv is not None:
                self.mv._maybe_grow(incoming)
        # the round count must be derived AFTER the buffered epoch's
        # incoming landed in the dirty bound — deriving it earlier
        # under-flushes any epoch touching more distinct groups than
        # one round drains (silent MV divergence; code-review finding).
        # Rounds and pads come from the PLAN's out_cap (the value the
        # compiled flush actually drains per round), never the agg's
        # live attribute: a post-fuse out_cap mutation must not
        # desynchronize the slice from the program.
        flush_rounds = 0
        pads: Tuple[int, ...] = ()
        if flush and self.agg is not None:
            out_cap = self.plan.agg.out_cap
            bound = min(self.agg._dirty_bound, self.agg.table.capacity)
            flush_rounds = max(1, -(-bound // out_cap))
            # the SAME two-bucket slice quantization the interpreted
            # _flush_all applies, from the same host dirty bound
            full = 2 * out_cap
            small = min(256, full)
            pads = tuple(
                (
                    small
                    if 2 * min(
                        max(bound - r * out_cap, 0), out_cap
                    ) <= small
                    else full
                )
                for r in range(flush_rounds)
            )
            if self.mv is not None:
                for p in pads:
                    self.mv._maybe_grow(p)
        if not has_data and not flush_rounds and (
            not stage or (self.agg is None and self.mv is None)
        ):
            return []  # nothing to run, nothing to stage
        states = (self._agg_state(), self._mv_state())
        if stage:
            self._last_lanes = (
                int(stacked.valid.shape[0] * stacked.valid.shape[1])
                if has_data
                else 0
            )
        if self._lift_state == "pending" and has_data:
            self._prove_lift(states, stacked, flush_rounds, pads)
        self._deviceprof_hook(states, stacked, flush_rounds, pads, has_data)
        # attribution contexts: dispatch counting (PROFILER.attribute)
        # and — under an armed jax_trace capture — a TraceAnnotation so
        # the device trace carries the fragment label next to the
        # program's fused/<stage> named scopes
        attr = ann = nullcontext()
        if PROFILER.enabled:
            attr = PROFILER.attribute(f"fused:{self.label}")
            if PROFILER.jax_trace:
                ann = jax.profiler.TraceAnnotation(f"fused:{self.label}")
        with attr, ann:
            (agg_st, mv_st), outs, packed = _fused_barrier_step(
                states,
                stacked,
                self._params,
                self._exec_plan,
                flush_rounds,
                pads,
                has_data,
            )
        if self.agg is not None:
            (
                self.agg.table,
                self.agg.state,
                self.agg.dropped,
                self.agg.minput,
                self.agg.mi_bad,
            ) = agg_st
            if flush_rounds:
                self.agg._dirty_bound = 0
        if self.mv is not None:
            self.mv.table, self.mv.state = mv_st
        if stage and packed is not None:
            try:
                packed.copy_to_host_async()
            except AttributeError:  # backend without async copies
                pass
            self._staged_scalars = packed
        # keep the program's input refs alive past this frame: their
        # deallocation would synchronize on the still-running program
        self._retired = (buf, stacked, states)
        return list(outs)

    def _agg_state(self):
        if self.agg is None:
            return ()
        return (
            self.agg.table,
            self.agg.state,
            self.agg.dropped,
            self.agg.minput,
            self.agg.mi_bad,
        )

    def _mv_state(self):
        if self.mv is None:
            return ()
        return (self.mv.table, self.mv.state)


# ---------------------------------------------------------------------------
# the two-input fused program (q7/q8: side chains + join + MV, one
# donated device program per barrier)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SidePlan:
    """One input side's statics: a pure prefix (ComposedSteps) feeding
    at most one stateful member — the two-input shapes' side chains:
    q7 ``hop -> DynamicMaxFilter`` (left) / ``hop -> HashAgg`` (right),
    q8 ``hop -> dedup`` (both)."""

    pre: Optional[ComposedSteps]
    kind: Optional[str]  # None | "filter" | "dedup" | "agg"
    keys: tuple = ()  # filter: (group_col, value_col); dedup: key names
    agg: Optional[AggStatics] = None


@dataclass(frozen=True)
class TwoInputPlan:
    """The fused two-input program's static shape (jit cache key):
    two side plans around one hash join, then a pure/mv/pure tail.
    Value-hashable (ComposedSteps contract), so rebuilds and recovery
    re-fuse into the SAME compiled program."""

    left: SidePlan
    right: SidePlan
    j_left_keys: tuple
    j_right_keys: tuple
    j_left_names: tuple
    j_right_names: tuple
    j_out_names: tuple
    j_out_cap: int
    j_type: str
    tail_pre: Optional[ComposedSteps]
    mv_pk: Optional[tuple]
    mv_cols: Optional[tuple]
    tail_post: Optional[ComposedSteps]

    def __hash__(self):
        # hashed as a STATIC jit argument on every barrier dispatch:
        # cache it (frozen dataclasses re-derive the field-tuple hash
        # per call; equality stays field-based for program sharing)
        h = self.__dict__.get("_hash")
        if h is None:
            h = hash(tuple(getattr(self, f.name) for f in _dc_fields(self)))
            object.__setattr__(self, "_hash", h)
        return h


def _two_input_side_scan(st, jl, jr, seg, side_plan, plan, arrival):
    """lax.scan one side's homogeneous stacked batch through the side's
    stateful step (if any) and the join arrival step, chunk by chunk in
    arrival order — the DynamicMaxFilter's pass-iff->=pre-chunk-max
    decision and the join's per-chunk ``out_cap`` emission compaction
    are both order-dependent, so the scan preserves the interpreted
    walk's exact semantics (bit-identity, not just epoch-equivalence).
    Returns ``(st, jl, jr, flat_emission, (saw_delete, dropped),
    em_overflow)`` with the per-chunk emissions flattened in order."""
    own_keys = plan.j_left_keys if arrival == "l" else plan.j_right_keys
    other_keys = plan.j_right_keys if arrival == "l" else plan.j_left_keys
    own_names = plan.j_left_names if arrival == "l" else plan.j_right_names
    other_names = plan.j_right_names if arrival == "l" else plan.j_left_names
    jown, jother = (jl, jr) if arrival == "l" else (jr, jl)
    F = jnp.zeros((), jnp.bool_)

    def body(carry, chunk):
        st, jown, jother, sd, dp, ovf = carry
        if side_plan.pre is not None:
            chunk = side_plan.pre(chunk)
        if side_plan.kind == "filter":
            table, maxes, sdirty = st
            table, maxes, sdirty, chunk, d1, d2 = filter_step_fn(
                table,
                maxes,
                sdirty,
                chunk,
                side_plan.keys[0],
                side_plan.keys[1],
            )
            st = (table, maxes, sdirty)
            sd, dp = sd | d1, dp | d2
        elif side_plan.kind == "dedup":
            table, sdirty = st
            table, sdirty, chunk, d1, d2 = dedup_step_fn(
                table, sdirty, chunk, side_plan.keys
            )
            st = (table, sdirty)
            sd, dp = sd | d1, dp | d2
        jown, jother, cols, nulls, ops, valid, o = join_step_fn(
            jown,
            jother,
            chunk,
            own_keys,
            other_keys,
            own_names,
            other_names,
            plan.j_out_cap,
            plan.j_type,
            arrival,
            plan.j_out_names,
        )
        em = StreamChunk(columns=cols, valid=valid, nulls=nulls, ops=ops)
        return (st, jown, jother, sd, dp, ovf | o), em

    # segments arrive as pow2-padded chunk TUPLES and stack INSIDE the
    # traced program: host-eager jnp.stack cost ~9ms/barrier of pure
    # dispatch overhead on the q7 smoke tier — in-trace it fuses into
    # the compiled program for free
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *seg)
    (st, jown, jother, sd, dp, ovf), ems = jax.lax.scan(
        body, (st, jown, jother, F, F, F), stacked
    )
    jl, jr = (jown, jother) if arrival == "l" else (jother, jown)
    flat = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), ems)
    return st, jl, jr, flat, (sd, dp), ovf


def _fused_two_input_fn(
    states, left_batches, right_batches, params, plan, flush_rounds, pads
):
    """The whole two-input fragment-barrier as one pure function over
    ``states = (left_state, right_state, (join_left, join_right),
    mv_state, latches)``:

    apply phase — the epoch's buffered LEFT batches scan through the
                  left side's step + the join's left-arrival kernel
                  (probe right, fold into left), each batch's per-chunk
                  ``out_cap`` emissions walking tail -> device MV; then
                  the RIGHT batches likewise (or, agg sides, into the
                  agg's flatten+reduce epoch path);
    flush phase — ``flush_rounds`` device flushes of the agg's dirty
                  groups, each delta PADDED TO A LATTICE BUCKET with a
                  validity mask (runtime/bucketing.flush_pad — the
                  "padded flush made the join 80x slower" objection
                  predates masked lanes: the join's probe/build kernels
                  treat masked rows as provably inert, so the pad costs
                  one masked device op instead of an interpreted
                  consumer's compute), probing the join as a
                  right-arrival and walking tail -> MV;
    scalars     — every member's latches + occupancy/survivor counters
                  PLUS the device-computed telemetry lane (left/right
                  rows, join emissions, dirty groups, MV rows) packed
                  into ONE int64 lane for the (possibly K-deferred)
                  overlapped finish read.

    Interpreted-twin equivalence: mid-epoch, left applies touch only
    {left step state, join.left, MV} and right applies only {right
    step state, join.right-or-agg} — disjoint — and the join's
    barrier-time flush deltas probe a left side that already absorbed
    the whole epoch either way, so batching sides in (left, right,
    flush) order reproduces the interpreted walk's emissions exactly
    for the per-barrier MV.
    """
    with param_scope(params):
        return _fused_two_input_body(
            states, left_batches, right_batches, plan, flush_rounds, pads
        )


def _fused_two_input_body(
    states, left_batches, right_batches, plan, flush_rounds, pads
):
    l_st, r_st, (jl, jr), mv_st, latches = states
    l_saw, l_drop, r_saw, r_drop, em_latch = latches
    Z = jnp.zeros((), jnp.int64)
    rows_l = rows_r = join_rows = mv_rows = Z
    em_ovf = em_latch
    outs: List[StreamChunk] = []

    def through_tail(chunk):
        nonlocal mv_st, mv_rows, join_rows
        join_rows = join_rows + jnp.sum(chunk.valid.astype(jnp.int64))
        if plan.tail_pre is not None:
            chunk = plan.tail_pre(chunk)
        if plan.mv_pk is not None:
            with jax.named_scope("fused/mv_write"):
                mv_rows = mv_rows + jnp.sum(chunk.valid.astype(jnp.int64))
                mtable, mstate = mv_st
                mtable, mstate = mv_step_fn(
                    mtable, mstate, chunk, plan.mv_pk, plan.mv_cols
                )
                mv_st = (mtable, mstate)
        if plan.tail_post is not None:
            chunk = plan.tail_post(chunk)
        return chunk

    with jax.named_scope("fused/apply"):
        for seg in left_batches:
            for c in seg:
                rows_l = rows_l + jnp.sum(c.valid.astype(jnp.int64))
            l_st, jl, jr, flat, fl, ovf = _two_input_side_scan(
                l_st, jl, jr, seg, plan.left, plan, "l"
            )
            l_saw, l_drop = l_saw | fl[0], l_drop | fl[1]
            em_ovf = em_ovf | ovf
            outs.append(through_tail(flat))
        for seg in right_batches:
            for c in seg:
                rows_r = rows_r + jnp.sum(c.valid.astype(jnp.int64))
            if plan.right.kind == "agg":
                stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *seg)
                a = plan.right.agg
                table, ast, dropped, minput, mi_bad = r_st
                if a.has_minput:
                    table, ast, dropped, minput, mi_bad = _epoch_reduced_fn(
                        table, ast, dropped, stacked, a.calls,
                        a.group_keys, a.nullable, plan.right.pre,
                        minput, mi_bad,
                    )
                else:
                    table, ast, dropped = _epoch_reduced_fn(
                        table, ast, dropped, stacked, a.calls,
                        a.group_keys, a.nullable, plan.right.pre,
                    )
                r_st = (table, ast, dropped, minput, mi_bad)
            else:
                r_st, jl, jr, flat, fr, ovf = _two_input_side_scan(
                    r_st, jl, jr, seg, plan.right, plan, "r"
                )
                r_saw, r_drop = r_saw | fr[0], r_drop | fr[1]
                em_ovf = em_ovf | ovf
                outs.append(through_tail(flat))

    # dirty groups pending at the barrier, sampled AFTER the epoch's
    # applies and BEFORE the flush drains them (telemetry twin)
    dirty_groups = Z
    if plan.right.kind == "agg":
        dirty_groups = jnp.sum(r_st[1].dirty.astype(jnp.int64))

    if flush_rounds and plan.right.kind == "agg":
        a = plan.right.agg
        table, ast, dropped, minput, mi_bad = r_st
        with jax.named_scope("fused/flush"):
            for r in range(flush_rounds):
                ast, delta = agg_ops.flush(
                    ast, table.keys, a.out_cap, a.float_extremes
                )
                chunk = delta_to_chunk(
                    delta, a.group_keys, a.nullable, a.calls, pads[r]
                )
                jr, jl, cols, nulls, ops, valid, o = join_step_fn(
                    jr,
                    jl,
                    chunk,
                    plan.j_right_keys,
                    plan.j_left_keys,
                    plan.j_right_names,
                    plan.j_left_names,
                    plan.j_out_cap,
                    plan.j_type,
                    "r",
                    plan.j_out_names,
                )
                em_ovf = em_ovf | o
                outs.append(
                    through_tail(
                        StreamChunk(
                            columns=cols, valid=valid, nulls=nulls, ops=ops
                        )
                    )
                )
        r_st = (table, ast, dropped, minput, mi_bad)

    with jax.named_scope("fused/scalar_pack"):
        scal = []

        def side_scal(st, kind, saw, drop):
            if kind in ("filter", "dedup"):
                table = st[0]
                sdirty = st[2] if kind == "filter" else st[1]
                scal.extend(
                    [
                        saw,
                        drop,
                        table.occupancy(),
                        jnp.sum((table.live | sdirty).astype(jnp.int32)),
                    ]
                )
            elif kind == "agg":
                table, ast, dropped, _minput, mi_bad = st
                scal.extend(
                    [dropped, ast.minmax_retracted, mi_bad,
                     table.occupancy()]
                )

        side_scal(l_st, plan.left.kind, l_saw, l_drop)
        side_scal(r_st, plan.right.kind, r_saw, r_drop)
        scal += [
            em_ovf,
            jl.overflow,
            jl.inconsistent,
            jr.overflow,
            jr.inconsistent,
            jl.table.occupancy(),
            jr.table.occupancy(),
            jnp.sum((jl.table.live | jl.sdirty).astype(jnp.int32)),
            jnp.sum((jr.table.live | jr.sdirty).astype(jnp.int32)),
        ]
        if plan.mv_pk is not None:
            mtable, mstate = mv_st
            scal += [mstate.dropped, mtable.occupancy()]
        # telemetry tail rides the same staged read the barrier pays
        # anyway: zero extra lanes dispatched, zero new host syncs
        scal += [rows_l, rows_r, join_rows, dirty_groups, mv_rows]
        # state digests ride the SAME lane (integrity layer): fused
        # twins of the members' state_digest(), decoded per the
        # _scalar_layout "dig" tail — zero extra dispatches
        with jax.named_scope("fused/digest"):
            def side_digest(st, kind):
                if kind == "filter":
                    scal.append(
                        integrity.device_digest(
                            *integrity.filter_lanes(st[0], st[1])
                        )
                    )
                elif kind == "dedup":
                    scal.append(
                        integrity.device_digest(
                            *integrity.dedup_lanes(st[0])
                        )
                    )
                elif kind == "agg":
                    scal.append(
                        integrity.device_digest(
                            *integrity.agg_lanes(st[0], st[1])
                        )
                    )

            side_digest(l_st, plan.left.kind)
            side_digest(r_st, plan.right.kind)
            scal.append(
                integrity.device_digest(
                    *integrity.join_side_lanes(jl, jnp.where)
                )
            )
            scal.append(
                integrity.device_digest(
                    *integrity.join_side_lanes(jr, jnp.where)
                )
            )
            if plan.mv_pk is not None:
                mtable, mstate = mv_st
                scal.append(
                    integrity.device_digest(
                        *integrity.mv_lanes(mtable, mstate)
                    )
                )
        packed = jnp.stack(
            [jnp.asarray(x).astype(jnp.int64) for x in scal]
        )
    latches_out = (l_saw, l_drop, r_saw, r_drop, em_ovf)
    return (l_st, r_st, (jl, jr), mv_st, latches_out), tuple(outs), packed


_fused_two_input_step = partial(
    jax.jit,
    static_argnames=("plan", "flush_rounds", "pads"),
    donate_argnums=(0,),
)(_fused_two_input_fn)


_ZERO_VALID_CACHE: dict = {}


def _zero_valid(shape) -> jnp.ndarray:
    """A cached all-False valid lane for pad chunks: padding is a
    steady-state per-barrier operation and the zero lane is immutable
    and never donated — minting a fresh device buffer per barrier was
    measurable eager-dispatch cost."""
    arr = _ZERO_VALID_CACHE.get(shape)
    if arr is None:
        arr = jnp.zeros(shape, jnp.bool_)
        _ZERO_VALID_CACHE[shape] = arr
    return arr


def _pad_segment(seg: List[StreamChunk]) -> Tuple[StreamChunk, ...]:
    """Pow2-pad a homogeneous chunk list (the epoch-batch compile
    discipline: at most log2(max chunks/epoch) distinct batch shapes
    per chunk signature). The chunks stay a TUPLE — the fused program
    stacks them in-trace, where the stack fuses into the compiled
    program instead of costing host-eager dispatches."""
    n = len(seg)
    target = 1 << (n - 1).bit_length() if n > 1 else 1
    if target > n:
        c0 = seg[0]
        empty = StreamChunk(
            c0.columns, _zero_valid(c0.valid.shape), c0.nulls, c0.ops
        )
        seg = seg + [empty] * (target - n)
    return tuple(seg)


class FusedTwoInputExecutor(Executor):
    """A whole two-input pipeline — ``pure* [filter|dedup|agg]`` per
    side, HashJoin, ``pure* [DeviceMV] pure*`` tail — executed as ONE
    donated device program per barrier (q7/q8's shape; the TiLT
    endgame: compile the query, not the operators).

    Driver contract (TwoInputPipeline routes here when armed):
    ``buffer_left``/``buffer_right`` stage raw source chunks,
    ``on_barrier`` dispatches the barrier program and returns the
    fragment's emission, ``finish_barrier`` materializes the packed
    member scalars and fires every member's latch checks at their
    original raise points — deferred to every K-th barrier under
    ``RW_FUSED_PIPELINE_DEPTH=K`` (K barriers' programs queue on the
    device back-to-back with zero host syncs between them).

    The member executor OBJECTS stay the system of record: state is
    written back after every program (as async futures of the in-flight
    dispatch), so checkpoint/restore, recovery, the shape governor and
    the cold tier keep talking to the originals, and the interpreted
    watermark walk interoperates exactly.
    """

    def __init__(
        self,
        members: Sequence[Executor],
        plan: TwoInputPlan,
        l_stateful: Optional[Executor],
        r_stateful: Optional[Executor],
        join: HashJoinExecutor,
        mv: Optional[DeviceMaterializeExecutor],
        label: str = "fragment",
        depth: Optional[int] = None,
        n_left: Optional[int] = None,
    ):
        self.members = list(members)
        self.plan = plan
        # index boundary between the left and right chains inside
        # ``members`` (telemetry row attribution)
        self._n_left = n_left if n_left is not None else len(members)
        self.l_stateful = l_stateful
        self.r_stateful = r_stateful
        self.agg = r_stateful if type(r_stateful) is HashAggExecutor else None
        self.join = join
        self.mv = mv
        self.label = label
        self.covers_whole_chain = True
        self.depth = pipeline_depth(depth)
        self._segs = {"l": [], "r": []}  # homogeneous chunk segments
        self._sig = {"l": None, "r": None}
        self._probe_caps = {}  # (side, chunk sig) -> post-pre capacity
        self._pending: List = []  # staged packed scalars (K-deferred)
        self._retired: List = []  # program inputs held to the K-fence
        self._barriers = 0
        self._last_lanes = 0
        self._telemetry: Optional[dict] = None
        self.last_digests: dict = {}

    # -- data path --------------------------------------------------------
    def buffer_left(self, chunk: StreamChunk) -> List[StreamChunk]:
        return self._buffer("l", chunk)

    def buffer_right(self, chunk: StreamChunk) -> List[StreamChunk]:
        return self._buffer("r", chunk)

    def _buffer(self, side: str, chunk: StreamChunk) -> List[StreamChunk]:
        sig = FusedChainExecutor._signature(chunk)
        segs = self._segs[side]
        if not segs or self._sig[side] != sig:
            segs.append([])
            self._sig[side] = sig
        segs[-1].append(chunk)
        return []

    def flush_data(self) -> List[StreamChunk]:
        """Apply everything buffered WITHOUT the agg flush (the
        pre-watermark data barrier: buffered rows precede the watermark
        in stream order, and the watermark walk then runs over member
        state interpreted)."""
        if not self._segs["l"] and not self._segs["r"]:
            return []
        return self._run(flush=False, stage=False)

    # -- control path -----------------------------------------------------
    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        if self.agg is not None and self.agg._cold_barrier_hook is not None:
            self.agg._cold_barrier_hook()
        outs = self._run(flush=True, stage=True)
        self._barriers += 1
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier(force=True)
        return outs

    def on_watermark(self, watermark: Watermark):
        # handled at the pipeline level (flush_data + interpreted
        # member walk); kept for Executor-protocol completeness
        outs = self.flush_data()
        return watermark, outs

    def finish_barrier(self, force: bool = False) -> None:
        """Materialize every pending barrier's packed scalars and run
        the member latch checks — at the K-boundary (or forced: direct
        drive, checkpoint staging, close). Between boundaries the host
        never blocks on the device: barrier N+1's program is enqueued
        while N still runs."""
        if not self._pending:
            return
        if not force and (self._barriers % self.depth) != 0:
            return
        import time

        from risingwave_tpu.ops.hash_table import finish_scalars
        from risingwave_tpu.trace import span

        pending, self._pending = self._pending, []
        retired, self._retired = self._retired, []
        try:
            for i, packed in enumerate(pending):
                t0 = time.perf_counter()
                with span(
                    "executor.device_step", executor=type(self).__name__
                ):
                    vals = finish_scalars(packed)
                if PROFILER.enabled:
                    PROFILER.record_device_wait(
                        self, (time.perf_counter() - t0) * 1e3
                    )
                # member scalars decode from the LAST pack only: the
                # latch lanes are monotonic and CARRIED through the
                # chained programs (each barrier's latches_in are the
                # previous write-back), so the final pack subsumes
                # every earlier one — and one K-window must feed the
                # bucket allocators ONE hysteresis observation, not K
                # at once (K stale notes burned the lazy-shrink
                # patience in a single boundary and flapped capacities
                # across the window — the exact oscillation PR 9's
                # hysteresis exists to prevent). Earlier packs still
                # decode their telemetry lanes (per-barrier forensics).
                self._on_barrier_scalars(
                    vals, members=(i == len(pending) - 1)
                )
        finally:
            for m in self.members:
                m.finish_barrier()  # no-op: members never stage here
            del retired  # the fence above ran: retiring is a plain free

    def capture_checkpoint(self) -> None:
        for m in self.members:
            cap = getattr(m, "capture_checkpoint", None)
            if cap is not None:
                cap()

    def lint_info(self):
        return None  # the pipeline's chains stay the lint surface

    # -- scalar decode ----------------------------------------------------
    def _scalar_layout(self):
        layout = []
        if self.l_stateful is not None:
            layout.append(("l", 4))
        if self.r_stateful is not None:
            layout.append(("r", 4))
        layout.append(("join", 9))
        if self.mv is not None:
            layout.append(("mv", 2))
        layout.append(("tel", 5))
        # digest tail mirrors the pack's fused/digest scope exactly:
        # one per stateful side, both join sides, one for the MV
        n_dig = 2
        if self.l_stateful is not None:
            n_dig += 1
        if self.r_stateful is not None:
            n_dig += 1
        if self.mv is not None:
            n_dig += 1
        layout.append(("dig", n_dig))
        return layout

    def _on_barrier_scalars(self, vals, members: bool = True) -> None:
        i = 0
        slices = {}
        for name, width in self._scalar_layout():
            slices[name] = tuple(vals[i : i + width])
            i += width
        # telemetry FIRST: a tripped member latch raises below, and the
        # flight recorder must still see what the barrier did
        self._note_telemetry(slices)
        self._note_digests(slices.get("dig", ()))
        if not members:
            return
        if self.l_stateful is not None:
            self.l_stateful._on_barrier_scalars(slices["l"])
        if self.r_stateful is not None:
            self.r_stateful._on_barrier_scalars(slices["r"])
        self.join._on_barrier_scalars(slices["join"])
        if self.mv is not None:
            self.mv._on_barrier_scalars(slices["mv"])

    def _note_digests(self, dig) -> None:
        """Decode the fused digest tail (integrity layer twins of the
        members' state_digest()) — forensic, never load-bearing."""
        try:
            names = []
            if self.l_stateful is not None:
                names.append("left")
            if self.r_stateful is not None:
                names.append("right")
            names += ["join_left", "join_right"]
            if self.mv is not None:
                names.append("mv")
            digs = {
                n: integrity.digest_from_scalar(v)
                for n, v in zip(names, dig)
            }
            if digs:
                self.last_digests = digs
                if self._telemetry is not None:
                    self._telemetry["state_digests"] = {
                        k: f"{v:016x}" for k, v in digs.items()
                    }
        except Exception:  # noqa: BLE001 — forensic, never load-bearing
            pass

    def _note_telemetry(self, slices) -> None:
        """Decode the packed telemetry lane into the deviceprof
        registry (host bookkeeping over values the barrier read anyway
        — zero extra device IO; never faults the barrier)."""
        try:
            rows_l, rows_r, join_rows, dirty_groups, mv_rows = (
                int(x) for x in slices["tel"]
            )
            member_rows = {}
            occupancy = {}
            for idx, m in enumerate(self.members):
                name = f"{idx}:{type(m).__name__}"
                if m is self.join:
                    member_rows[name] = join_rows
                elif m is self.mv or idx > self.members.index(self.join):
                    member_rows[name] = mv_rows
                elif idx >= self._n_left:
                    member_rows[name] = rows_r
                else:
                    member_rows[name] = rows_l
            occupancy["join_left"] = int(slices["join"][5])
            occupancy["join_right"] = int(slices["join"][6])

            def side_occ(ex, lanes):
                # agg lanes: [dropped, mret, mi_bad, occupancy];
                # filter/dedup: [saw, drop, occupancy, survivors]
                return int(
                    lanes[3] if type(ex) is HashAggExecutor else lanes[2]
                )

            if self.l_stateful is not None:
                occupancy["left"] = side_occ(self.l_stateful, slices["l"])
            if self.r_stateful is not None:
                occupancy["right"] = side_occ(self.r_stateful, slices["r"])
            if self.mv is not None:
                occupancy["mv"] = int(slices["mv"][1])
            from risingwave_tpu.runtime.bucketing import padding_fraction

            def nbytes(ex):
                return sum(
                    leaf.nbytes
                    for leaf in jax.tree.leaves(
                        getattr(ex, "table", None)
                        if type(ex).__name__ not in ("HashJoinExecutor",)
                        else (ex.left, ex.right)
                    )
                    if hasattr(leaf, "nbytes")
                )

            entries = [
                (
                    self.join.left.capacity,
                    occupancy["join_left"],
                    sum(
                        leaf.nbytes
                        for leaf in jax.tree.leaves(self.join.left)
                    ),
                ),
                (
                    self.join.right.capacity,
                    occupancy["join_right"],
                    sum(
                        leaf.nbytes
                        for leaf in jax.tree.leaves(self.join.right)
                    ),
                ),
            ]
            for key, ex in (
                ("left", self.l_stateful),
                ("right", self.r_stateful),
            ):
                if ex is not None and key in occupancy:
                    entries.append(
                        (
                            ex.table.capacity,
                            occupancy[key],
                            nbytes(ex),
                        )
                    )
            if self.mv is not None and "mv" in occupancy:
                entries.append(
                    (
                        self.mv.table.capacity,
                        occupancy["mv"],
                        self.mv.state_nbytes(),
                    )
                )
            pad_frac = padding_fraction(entries)
            lanes = self._last_lanes
            rows_in = rows_l + rows_r
            tel = {
                "rows_in": rows_in,
                "rows_left": rows_l,
                "rows_right": rows_r,
                "join_rows": join_rows,
                "dirty_groups": dirty_groups,
                "mv_rows": mv_rows,
                "member_rows": member_rows,
                "occupancy": occupancy,
                "lanes_total": lanes,
                "lane_fill_frac": (
                    round(rows_in / lanes, 6) if lanes else 0.0
                ),
                "padding_bytes_frac": pad_frac,
            }
            self._telemetry = tel
            from risingwave_tpu.deviceprof import DEVICEPROF

            DEVICEPROF.note_telemetry(self.label, tel)
        except Exception:  # noqa: BLE001 — forensic, never load-bearing
            pass

    # -- member state plumbing --------------------------------------------
    def _side_state(self, ex):
        if ex is None:
            return ()
        if type(ex) is DynamicMaxFilterExecutor:
            return (ex.table, ex.maxes, ex.sdirty)
        if type(ex) is AppendOnlyDedupExecutor:
            return (ex.table, ex.sdirty)
        return (ex.table, ex.state, ex.dropped, ex.minput, ex.mi_bad)

    def _write_side_state(self, ex, st) -> None:
        if ex is None:
            return
        if type(ex) is DynamicMaxFilterExecutor:
            ex.table, ex.maxes, ex.sdirty = st
        elif type(ex) is AppendOnlyDedupExecutor:
            ex.table, ex.sdirty = st
        else:
            ex.table, ex.state, ex.dropped, ex.minput, ex.mi_bad = st

    def _latches(self):
        def pair(ex):
            if ex is None or type(ex) is HashAggExecutor:
                # fresh zero buffers per slot: the states pytree is
                # DONATED whole, and donating one buffer twice is an
                # XLA error
                return (
                    jnp.zeros((), jnp.bool_),
                    jnp.zeros((), jnp.bool_),
                )
            return (ex._saw_delete, ex._dropped)

        return pair(self.l_stateful) + pair(self.r_stateful) + (
            self.join._em_overflow,
        )

    def _write_latches(self, latches) -> None:
        l_saw, l_drop, r_saw, r_drop, em = latches
        for ex, saw, drop in (
            (self.l_stateful, l_saw, l_drop),
            (self.r_stateful, r_saw, r_drop),
        ):
            if ex is not None and type(ex) is not HashAggExecutor:
                ex._saw_delete, ex._dropped = saw, drop
        self.join._em_overflow = em

    # -- the program ------------------------------------------------------
    def _prepare_side(self, side: str, side_plan: SidePlan):
        """Stack the side's buffered segments and run the members' host
        growth bookkeeping (rebuilds must land BEFORE states are read).
        Returns (batches, post_pre_rows)."""
        segs, self._segs[side] = self._segs[side], []
        self._sig[side] = None
        batches = []
        rows = 0
        ex = self.l_stateful if side == "l" else self.r_stateful
        for seg in segs:
            if not seg:
                continue
            padded = _pad_segment(seg)
            key = (side, FusedChainExecutor._signature(seg[0]))
            cap = self._probe_caps.get(key)
            if cap is None:
                # the post-pre row capacity (hop expansion factor),
                # memoized per chunk signature: re-tracing the pure
                # prefix abstractly EVERY barrier was measurable host
                # dispatch cost
                probe = jax.eval_shape(
                    side_plan.pre
                    if side_plan.pre is not None
                    else (lambda c: c),
                    jax.tree.map(
                        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                        seg[0],
                    ),
                )
                cap = probe.valid.shape[0]
                self._probe_caps[key] = cap
            rows += len(padded) * cap
            batches.append(padded)
        if ex is not None and rows:
            if type(ex) is HashAggExecutor:
                if ex._cold_stacked_hook is not None:
                    ex._cold_stacked_hook()
                ex._maybe_grow(rows)
                ex._insert_bound += rows
                ex._dirty_bound += rows
            else:
                ex._grow_hint(rows)
                ex._bound += rows
        return tuple(batches), rows

    def _run(self, flush: bool, stage: bool) -> List[StreamChunk]:
        if self.join._cold_apply_hook is not None:
            # armed cold tier: the program probes the join sides
            # directly, so every evicted bucket must be RESIDENT before
            # dispatch or matches are silently lost — restore them all
            # up front (conservative, the agg _cold_stacked_hook
            # discipline; code-review finding)
            for name in ("left", "right"):
                ev = self.join._evicted[name]
                if ev:
                    self.join._restore_cold_keys(name, sorted(ev))
        left_batches, l_rows = self._prepare_side("l", self.plan.left)
        right_batches, r_rows = self._prepare_side("r", self.plan.right)
        has_data = bool(left_batches or right_batches)

        flush_rounds = 0
        pads: Tuple[int, ...] = ()
        if flush and self.agg is not None:
            # rounds/pads from the PLAN's out_cap (the value the
            # compiled flush drains per round) AFTER the buffered epoch
            # landed in the dirty bound — the single-input lessons
            pads = flush_pad_schedule(
                self.agg._dirty_bound,
                self.agg.table.capacity,
                self.plan.right.agg.out_cap,
            )
            flush_rounds = len(pads)
        if not has_data and not flush_rounds and not stage:
            return []

        # join-side insert bounds: left arrivals fold into the left
        # side; right arrivals (scanned side or flush deltas) into the
        # right
        join = self.join
        if l_rows:
            join.left = join._grow_hint("l", join.left, l_rows)
            join._bound["l"] += l_rows
        r_join_rows = (
            sum(pads) if self.agg is not None else r_rows
        )
        if r_join_rows:
            join.right = join._grow_hint("r", join.right, r_join_rows)
            join._bound["r"] += r_join_rows
        if self.mv is not None:
            # every emission chunk reaching the MV has j_out_cap lanes
            # — INCLUDING flush rounds (a small-pad delta can still
            # match up to out_cap join rows), so the flush contribution
            # is rounds * out_cap, not the delta pad sum: the MV's
            # insert bound must stay a true upper bound or its
            # MAX_PROBE pre-grow guard goes blind (code-review finding)
            em_rows = (
                sum(len(seg) for seg in left_batches)
                + (
                    0
                    if self.agg is not None
                    else sum(len(seg) for seg in right_batches)
                )
                + flush_rounds
            ) * self.plan.j_out_cap
            if em_rows:
                self.mv._maybe_grow(em_rows)

        states = (
            self._side_state(self.l_stateful),
            self._side_state(self.r_stateful),
            (join.left, join.right),
            (self.mv.table, self.mv.state) if self.mv is not None else (),
            self._latches(),
        )
        if stage:
            self._last_lanes = sum(
                len(seg) * int(seg[0].valid.shape[0])
                for seg in left_batches + right_batches
            )
        self._deviceprof_hook(
            states, left_batches, right_batches, flush_rounds, pads
        )
        attr = ann = nullcontext()
        if PROFILER.enabled:
            attr = PROFILER.attribute(f"fused:{self.label}")
            if PROFILER.jax_trace:
                ann = jax.profiler.TraceAnnotation(f"fused:{self.label}")
        with attr, ann:
            (l_st, r_st, (jl, jr), mv_st, latches), outs, packed = (
                _fused_two_input_step(
                    states,
                    left_batches,
                    right_batches,
                    None,
                    self.plan,
                    flush_rounds,
                    pads,
                )
            )
        self._write_side_state(self.l_stateful, l_st)
        self._write_side_state(self.r_stateful, r_st)
        join.left, join.right = jl, jr
        if self.mv is not None:
            self.mv.table, self.mv.state = mv_st
        self._write_latches(latches)
        if self.agg is not None and flush_rounds:
            self.agg._dirty_bound = 0
        if stage:
            try:
                packed.copy_to_host_async()
            except AttributeError:  # backend without async copies
                pass
            self._pending.append(packed)
        # keep the program's input refs alive past this frame: their
        # deallocation would synchronize on the still-running program
        # (held to the K-boundary fence under pipelining)
        self._retired.append((left_batches, right_batches, states, outs))
        return list(outs)

    def _deviceprof_hook(
        self, states, left_batches, right_batches, flush_rounds, pads
    ) -> None:
        """Compiled-artifact roofline for the two-input program:
        analyze each (plan, bucket) combination ONCE via AOT
        lower+compile over abstract args (deferred off the dispatch
        path). Never raises."""
        from risingwave_tpu.deviceprof import DEVICEPROF

        if not DEVICEPROF.enabled:
            return
        try:
            def shapes(batches):
                return ".".join(
                    f"{len(seg)}x{seg[0].valid.shape[0]}"
                    for seg in batches
                ) or "-"

            caps = ".".join(
                str(c)
                for c in (
                    self.join.left.capacity,
                    self.join.right.capacity,
                )
                + (
                    (self.mv.table.capacity,)
                    if self.mv is not None
                    else ()
                )
            )
            bucket = (
                f"fr{flush_rounds}_p{'.'.join(map(str, pads)) or '-'}"
                f"_l{shapes(left_batches)}_r{shapes(right_batches)}"
                f"_c{caps}"
            )
            abstract = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (states, left_batches, right_batches),
            )
            plan = self.plan
            DEVICEPROF.ensure_program(
                f"fused:{self.label}",
                bucket,
                lambda: _fused_two_input_step.lower(
                    abstract[0],
                    abstract[1],
                    abstract[2],
                    None,
                    plan,
                    flush_rounds,
                    pads,
                ),
                fragment=self.label,
            )
        except Exception:  # noqa: BLE001 — observability never faults
            pass


# ---------------------------------------------------------------------------
# chain rewriting
# ---------------------------------------------------------------------------


def _parse_side(chain, label: str, side: str):
    """Split one input-side chain into ``(pure prefix, stateful
    member)`` for the two-input plan, or None (with RW-E807
    provenance) when the side cannot be absorbed."""
    pres: List[Executor] = []
    stateful = None
    for ex in chain:
        if stateful is not None:
            return _refuse(
                f"{label}/{side}",
                "executors after the side's stateful member are not "
                "absorbable by the two-input program",
                executor=type(ex).__name__,
            )
        if _is_pure(ex):
            pres.append(ex)
        elif type(ex) in (
            DynamicMaxFilterExecutor,
            AppendOnlyDedupExecutor,
            HashAggExecutor,
        ):
            stateful = ex
        else:
            return _refuse(
                f"{label}/{side}",
                "not fusible in a two-input side chain",
                executor=type(ex).__name__,
            )
    if stateful is not None and type(stateful) is not HashAggExecutor:
        if stateful._buckets is None:
            return _refuse(
                f"{label}/{side}",
                "side state is not on a bucket lattice (the legacy "
                "unbucketed twin — the RW-E803 wedge class stays "
                "interpreted)",
                executor=type(stateful).__name__,
            )
    return pres, stateful


def _side_plan(pres, stateful) -> SidePlan:
    pre = (
        ComposedSteps([p.pure_step() for p in pres]) if pres else None
    )
    if stateful is None:
        return SidePlan(pre=pre, kind=None)
    if type(stateful) is DynamicMaxFilterExecutor:
        return SidePlan(
            pre=pre,
            kind="filter",
            keys=(stateful.group_col, stateful.value_col),
        )
    if type(stateful) is AppendOnlyDedupExecutor:
        return SidePlan(pre=pre, kind="dedup", keys=stateful.keys)
    return SidePlan(
        pre=pre,
        kind="agg",
        agg=AggStatics(
            calls=stateful.calls,
            group_keys=stateful.group_keys,
            nullable=stateful.nullable,
            out_cap=stateful.out_cap,
            float_extremes=stateful._float_extremes,
            has_minput=bool(stateful.minput),
        ),
    )


def fuse_two_input(
    pipeline, label: str = "mv", depth: Optional[int] = None
) -> Optional[FusedTwoInputExecutor]:
    """Plan whole-pipeline fusion for a TwoInputPipeline — q7's
    ``hop -> maxagg -> [flush] -> DynamicMaxFilter x HashJoin -> mv``
    and q8's ``dedup x join -> mv`` shapes — or None with RW-E807
    provenance (never a silent interpret fallback). Requirements, each
    refused with provenance when unmet:

    - the join is a bucketed HashJoin whose trace contract declares
      ``two_input_fusible`` (both sides' capacities on the declared
      pow2 lattice — flush lanes pad to lattice buckets with masks,
      so the emission shape family is closed);
    - each side is ``pure*`` + at most one of {DynamicMaxFilter,
      AppendOnlyDedup, HashAgg} (bucketed), the agg (at most one, and
      on the right side) flushing INTO the join as lattice-padded
      masked right-arrivals — `_flush_all`'s exact-slicing status read
      never runs on this path;
    - the tail is ``pure* [DeviceMaterialize] pure*``.
    """
    join = getattr(pipeline, "join", None)
    if type(join) is not HashJoinExecutor:
        return _refuse(
            label,
            "two-input executor is not a HashJoin",
            executor=type(join).__name__,
        )
    contract = join.trace_contract()
    if not contract.get("two_input_fusible"):
        return _refuse(
            label,
            "join does not declare bucketed two-input fusibility "
            "(unbucketed sides: lattice-incompatible)",
            executor=type(join).__name__,
        )
    left = _parse_side(pipeline.left, label, "left")
    if left is None:
        return None
    right = _parse_side(pipeline.right, label, "right")
    if right is None:
        return None
    l_pres, l_stateful = left
    r_pres, r_stateful = right
    aggs = [
        e
        for e in (l_stateful, r_stateful)
        if type(e) is HashAggExecutor
    ]
    if len(aggs) > 1:
        return _refuse(label, "two agg sides are not fusible")
    if aggs and type(l_stateful) is HashAggExecutor:
        # one flush phase, ordered after both sides' applies: the agg
        # must sit on the RIGHT side (q7's shape); a left-side agg
        # would need its flush deltas applied as left arrivals BEFORE
        # the right batches to match the interpreted barrier order
        return _refuse(
            label,
            "agg on the left side: flush ordering not supported yet "
            "(swap the inputs)",
            executor="HashAggExecutor",
        )
    # tail: pure* [DeviceMaterialize] pure*
    tail_pre: List[Executor] = []
    tail_post: List[Executor] = []
    mv = None
    for ex in pipeline.tail:
        if type(ex) is DeviceMaterializeExecutor and mv is None:
            mv = ex
        elif _is_pure(ex):
            (tail_post if mv is not None else tail_pre).append(ex)
        else:
            return _refuse(
                f"{label}/tail",
                "not fusible in the two-input tail",
                executor=type(ex).__name__,
            )
    steps = lambda exs: (
        ComposedSteps([e.pure_step() for e in exs]) if exs else None
    )
    plan = TwoInputPlan(
        left=_side_plan(l_pres, l_stateful),
        right=_side_plan(r_pres, r_stateful),
        j_left_keys=join.left_keys,
        j_right_keys=join.right_keys,
        j_left_names=join.left_names,
        j_right_names=join.right_names,
        j_out_names=join.out_names,
        j_out_cap=join.out_cap,
        j_type=join.join_type,
        tail_pre=steps(tail_pre),
        mv_pk=mv.pk if mv is not None else None,
        mv_cols=mv.columns if mv is not None else None,
        tail_post=steps(tail_post),
    )
    members = (
        list(pipeline.left)
        + list(pipeline.right)
        + [join]
        + list(pipeline.tail)
    )
    return FusedTwoInputExecutor(
        members,
        plan,
        l_stateful,
        r_stateful,
        join,
        mv,
        label=label,
        depth=depth,
        n_left=len(pipeline.left),
    )


def fuse_chain(
    chain: Sequence[Executor],
    label: str = "fragment",
    defer_pure: bool = False,
    upstream: Optional[Executor] = None,
) -> List[Executor]:
    """Rewrite every maximal fusible run in an actor chain into a
    FusedChainExecutor; everything else passes through untouched (the
    interpreted fallback, per run, not per process).

    A run fuses when the whole per-barrier data path — agg apply,
    flush-delta extraction AND the device-MV write — lands inside one
    donated program (the q5 shape: ``pure* agg pure* mv pure*``):
    the flush never leaves the device, so its bound-padded delta
    capacity costs one masked device op, not an interpreted
    consumer's compute.

    Everything else keeps today's paths:

    - agg WITHOUT a downstream device MV in the run: the flush chunk
      EXITS to an interpreted consumer (a join) that wants the
      exact-sliced small chunks only the interpreted flush's status
      read can produce — fall back to the per-epoch batched wrapper
      (one fused apply program per epoch, interpreted exact flush).
      (A FUSIBLE two-input consumer absorbs the flush instead — see
      fuse_two_input, which runs before this per-chain pass.)
    - device MV without an agg (join-fed MV tails): fusible IFF the
      feeder's declared emission shape family is CLOSED ("fixed" /
      "bucketed" trace contract — a bucketed join emits one out_cap
      shape, a bucketed dynamic filter a pow2 lattice), so stacking
      its chunks is compile-bounded. The old hard carve-out ("stacking
      heterogeneous join emissions = compile storm") is replaced by
      this lattice-compatibility check; a refusal records RW-E807
      provenance (fusion_refusals) — never a silent fallback. The
      feeder is the nearest unfused upstream in the chain, or the
      caller-passed ``upstream`` executor for chain-head runs.
    - pure-only runs >= 2 fuse only with ``defer_pure`` (they emit
      during ``apply`` interpreted; deferring to the barrier is only
      epoch-equivalent, so it is opt-in)."""
    from risingwave_tpu.executors.epoch_batch import (
        EpochBatchedAggExecutor,
    )

    out: List[Executor] = []
    run: List[Executor] = []
    feeder = upstream

    def _feeder_emission():
        if feeder is None:
            return "unknown"
        fn = getattr(feeder, "trace_contract", None)
        try:
            contract = fn() if fn is not None else None
        except Exception:  # noqa: BLE001 — policy must never crash
            contract = None
        if contract is None:
            return "unknown"
        return contract.get("emission", "unknown")

    def close() -> None:
        nonlocal run
        if not run:
            return
        agg_idx = next(
            (
                i
                for i, m in enumerate(run)
                if type(m) is HashAggExecutor
            ),
            None,
        )
        has_mv = any(
            type(m) is DeviceMaterializeExecutor for m in run
        )
        has_mv_after_agg = agg_idx is not None and any(
            type(m) is DeviceMaterializeExecutor for m in run[agg_idx:]
        )
        if has_mv_after_agg:
            out.append(FusedChainExecutor(run, label=label))
        elif agg_idx is not None:
            # flush exits to an interpreted consumer: epoch-batch the
            # [pure*, agg] head, pass the tail pures through raw
            out.append(
                EpochBatchedAggExecutor(run[:agg_idx], run[agg_idx])
            )
            out.extend(run[agg_idx + 1 :])
        elif has_mv:
            em = _feeder_emission()
            if em in ("fixed", "bucketed"):
                out.append(FusedChainExecutor(run, label=label))
            else:
                _refuse(
                    label,
                    "join-fed MV tail left interpreted: feeder "
                    f"emission shape family is {em!r}, not a closed "
                    "fixed/bucketed lattice (stacking would mint one "
                    "program per distinct batch shape)",
                    executor=(
                        type(feeder).__name__
                        if feeder is not None
                        else None
                    ),
                )
                out.extend(run)
        elif defer_pure and len(run) >= 2:
            out.append(FusedChainExecutor(run, label=label))
        else:
            out.extend(run)
        run = []

    for ex in chain:
        if type(ex) is HashAggExecutor:
            if any(
                type(m) in (HashAggExecutor, DeviceMaterializeExecutor)
                for m in run
            ):
                close()
            run.append(ex)
        elif type(ex) is DeviceMaterializeExecutor:
            if any(type(m) is DeviceMaterializeExecutor for m in run):
                close()
            run.append(ex)
        elif _is_pure(ex):
            run.append(ex)
        else:
            close()
            out.append(ex)
            feeder = ex
    close()
    if (
        len(out) == 1
        and isinstance(out[0], FusedChainExecutor)
        and len(out[0].members) == len(list(chain))
    ):
        out[0].covers_whole_chain = True
    return out


def fuse_pipeline(
    pipeline,
    label: str = "mv",
    defer_pure: bool = False,
    pipeline_depth: Optional[int] = None,
):
    """Arm fusion on a SERIAL Pipeline / TwoInputPipeline in place
    (bench drivers and twin tests; the graph runtime fuses its actor
    chains automatically). Returns the wrappers created.

    Two-input pipelines fuse WHOLE first (fuse_two_input: side chains
    + join + MV tail into one donated program per barrier, with
    ``RW_FUSED_PIPELINE_DEPTH``/``pipeline_depth`` K-barrier device
    pipelining); when that is refused (RW-E807 provenance recorded)
    each chain falls back to the per-chain policy, with the join's
    contract passed as the tail's upstream so a lattice-compatible
    join-fed MV tail still fuses.

    Note: a serial pipeline's ``executors`` enumeration then yields
    wrappers instead of members — use on driver-owned pipelines, not
    runtime-registered ones; a two-input pipeline's chains are NOT
    rewritten under whole fusion (members stay enumerable), the
    wrapper rides ``pipeline._fused``."""
    created: List[Executor] = []

    def rewrite(chain, lbl, upstream=None):
        new = fuse_chain(
            chain, label=lbl, defer_pure=defer_pure, upstream=upstream
        )
        created.extend(
            e for e in new if isinstance(e, FusedChainExecutor)
        )
        return new

    if hasattr(pipeline, "join") and hasattr(pipeline, "left"):
        if two_input_enabled():
            w = fuse_two_input(
                pipeline, label=label, depth=pipeline_depth
            )
            if w is not None:
                pipeline._fused = w
                return [w]
        pipeline.left = rewrite(pipeline.left, f"{label}/left")
        pipeline.right = rewrite(pipeline.right, f"{label}/right")
        pipeline.tail = rewrite(
            pipeline.tail, f"{label}/tail", upstream=pipeline.join
        )
    elif hasattr(pipeline, "executors"):
        pipeline.executors = rewrite(pipeline.executors, label)
    return created


def expand_fused(executors) -> List[Executor]:
    """Flatten fused wrappers back to their member executors (bench
    padding/governor surfaces read per-executor state)."""
    out: List[Executor] = []
    for ex in executors or ():
        if isinstance(ex, (FusedChainExecutor, FusedTwoInputExecutor)):
            out.extend(ex.members)
        else:
            out.append(ex)
    return out


def fused_fragments(pipeline) -> dict:
    """BENCH-JSON evidence: how much of the pipeline actually fused
    (count + whole-chain flag + labels). Accepts serial pipelines,
    two-input pipelines under whole fusion (the ``_fused`` wrapper)
    and GraphPipeline (scans the live actors)."""
    fused = getattr(pipeline, "_fused", None)
    if isinstance(fused, FusedTwoInputExecutor):
        return {
            "count": 1,
            "whole_chain": fused.covers_whole_chain,
            "fragments": [
                f"{fused.label}[{len(fused.members)}]"
            ],
            "pipeline_depth": fused.depth,
        }
    graph = getattr(pipeline, "graph", None)
    exs = graph.executors if graph is not None else (
        list(getattr(pipeline, "executors", []) or [])
    )
    wrappers = [e for e in exs if isinstance(e, FusedChainExecutor)]
    return {
        "count": len(wrappers),
        "whole_chain": bool(wrappers)
        and all(w.covers_whole_chain for w in wrappers),
        "fragments": sorted(
            {f"{w.label}[{len(w.members)}]" for w in wrappers}
        ),
    }
