"""Fused device-resident barrier step — compile a fragment's fusible
executor run into ONE donated jitted program per barrier.

PR 6's profiler pinned the 10x-throughput gap on the host dispatch
wall (~319ms/barrier of Python walking executor chains vs 0.24ms of
device compute), and the fusion analyzer's FUSION_REPORT.json named
the blockers per executor. This module is the engine that cashes the
analysis in (ROADMAP item 1, the TiLT direction from PAPERS.md:
compile whole time-centric queries instead of interpreting
per-operator):

- :func:`fuse_chain` rewrites an actor chain's maximal fusible run —
  ``stateless-pure*  [HashAgg]  stateless-pure*  [DeviceMaterialize]
  stateless-pure*`` — into a :class:`FusedChainExecutor`. Anything
  the run cannot absorb (joins, dedup, host materializers, watermark
  generators, subclasses) passes through untouched and keeps the
  per-executor interpreted path: interpretation IS the automatic
  fallback, per run, not per process.
- :class:`FusedChainExecutor` buffers the epoch's chunks (the
  EpochBatchedAgg discipline: pow2-padded stacked batches, signature
  changes flush) and, at the barrier, runs ONE jitted
  ``fused_step(state_pytree, chunks) -> (state_pytree, deltas,
  scalars)`` with ``donate_argnums`` on the state pytree — keyed agg
  state and the device MV live in HBM across barriers; the host
  touches only ingest and the staged-scalar commit read.
- State ownership never moves: the member executors keep their state
  between programs (the wrapper reads it per barrier and writes the
  donated program's outputs back), so checkpoint/restore, recovery
  rebuilds, cold-tier hooks, snapshots and the shape governor all
  keep working against the original objects.

Compile discipline: the program's statics are value-hashable
(:class:`FusedPlan` hashes the member steps' ``functools.partial``
keys, the ComposedSteps contract), so graph rebuilds and recovery
re-fuse into the SAME compiled program; distinct (flush_rounds, pads,
has_data) combinations are a small closed set in steady state.
"""

from __future__ import annotations

import os
from contextlib import nullcontext
from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.executors.base import Barrier, Executor, Watermark
from risingwave_tpu.executors.epoch_batch import (
    ComposedSteps,
    _compose_lint_infos,
)
from risingwave_tpu.executors.hash_agg import (
    HashAggExecutor,
    _epoch_reduced_fn,
    delta_to_chunk,
)
from risingwave_tpu.executors.materialize import (
    DeviceMaterializeExecutor,
    mv_step_fn,
)
from risingwave_tpu.expr.expr import StaticTree, lift_literals, param_scope
from risingwave_tpu.ops import agg as agg_ops
from risingwave_tpu.parallel.sharded_agg import stack_chunks
from risingwave_tpu.profiler import PROFILER

__all__ = [
    "FusedChainExecutor",
    "expand_fused",
    "fuse_chain",
    "fuse_pipeline",
    "fused_cache_stats",
    "fused_enabled",
    "fused_fragments",
    "lift_enabled",
    "lift_plan",
]


def fused_enabled() -> bool:
    """RW_FUSED_STEP=0 is the kill switch: the graph runtime then
    falls back to the per-epoch batched (still interpreted) path."""
    return os.environ.get("RW_FUSED_STEP", "1").strip().lower() not in (
        "0",
        "off",
        "false",
    )


def lift_enabled() -> bool:
    """RW_FUSED_LIFT=0 disables multi-tenant constant lifting: every
    parameter variant then compiles its own fused program (the
    pre-PR-12 behavior)."""
    return os.environ.get("RW_FUSED_LIFT", "1").strip().lower() not in (
        "0",
        "off",
        "false",
    )


# ---------------------------------------------------------------------------
# static plan (jit cache key)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AggStatics:
    """The HashAgg member's jit statics (all value-hashable)."""

    calls: tuple
    group_keys: tuple
    nullable: tuple
    out_cap: int
    float_extremes: tuple
    has_minput: bool


@dataclass(frozen=True)
class FusedPlan:
    """The fused program's static shape: pure-step segments around at
    most one HashAgg and at most one DeviceMaterialize (agg strictly
    before mv). ``pre``/``mid``/``post`` are ComposedSteps (value-
    hashable compositions of the members' ``pure_step()`` partials),
    so two plans over equal step sequences share one compiled
    program."""

    pre: Optional[ComposedSteps]
    agg: Optional[AggStatics]
    mid: Optional[ComposedSteps]
    mv_pk: Optional[tuple]
    mv_cols: Optional[tuple]
    post: Optional[ComposedSteps]

    @property
    def has_mv(self) -> bool:
        return self.mv_pk is not None


def _delta_chunk(delta: dict, a: AggStatics, pad: Optional[int]) -> StreamChunk:
    """The flush delta -> chunk decode, shared with the interpreted
    path (hash_agg.delta_to_chunk is the one lane-contract decoder),
    with the host-chosen static pad slice."""
    return delta_to_chunk(delta, a.group_keys, a.nullable, a.calls, pad)


def _fused_barrier_fn(
    states, stacked, params, plan, flush_rounds, pads, has_data
):
    """The whole fragment-barrier as one pure function over
    ``states = (agg_state, mv_state)``:

    data phase  — the epoch's stacked chunks through the pure prefix
                  into the agg's flatten+reduce epoch path (ONE table
                  touch per distinct key), or — agg-less runs —
                  through the steps into the device MV as one
                  flattened batch;
    flush phase — ``flush_rounds`` device flushes of the agg's dirty
                  groups, each delta walking mid-steps -> device MV ->
                  post-steps (the fragment's per-barrier emission);
    scalars     — the members' barrier latches + occupancy counters
                  PLUS the device-computed telemetry lane (rows
                  applied, dirty groups drained, MV rows written) —
                  all packed into one int64 lane for the overlapped
                  finish_barrier read: per-member visibility at zero
                  extra dispatches and zero new host syncs.

    Each phase carries a ``jax.named_scope`` (fused/apply, fused/flush,
    fused/mv_write, fused/scalar_pack) so a ``jax_trace`` capture
    segments the ONE compiled program back into stages
    (deviceprof.parse_fused_stages).
    """
    # lifted-literal parameter vectors (``params``) bind for the whole
    # trace: plan segments containing LiftedLit slots read them as a
    # RUNTIME operand, so K parameter variants of one plan shape share
    # this single compiled program (multi-tenant compile sharing)
    with param_scope(params):
        return _fused_barrier_body(
            states, stacked, plan, flush_rounds, pads, has_data
        )


def _fused_barrier_body(states, stacked, plan, flush_rounds, pads, has_data):
    agg_st, mv_st = states
    outs: List[StreamChunk] = []
    mv_rows = jnp.zeros((), jnp.int32)

    def _through_mv(chunk):
        nonlocal mv_st, mv_rows
        if plan.mid is not None:
            chunk = plan.mid(chunk)
        if plan.has_mv:
            with jax.named_scope("fused/mv_write"):
                mv_rows = mv_rows + jnp.sum(chunk.valid.astype(jnp.int32))
                mtable, mstate = mv_st
                mtable, mstate = mv_step_fn(
                    mtable, mstate, chunk, plan.mv_pk, plan.mv_cols
                )
                mv_st = (mtable, mstate)
        if plan.post is not None:
            chunk = plan.post(chunk)
        return chunk

    rows_in = jnp.zeros((), jnp.int32)
    if has_data:
        rows_in = jnp.sum(stacked.valid.astype(jnp.int32))
        with jax.named_scope("fused/apply"):
            if plan.agg is not None:
                a = plan.agg
                table, st, dropped, minput, mi_bad = agg_st
                if a.has_minput:
                    table, st, dropped, minput, mi_bad = _epoch_reduced_fn(
                        table, st, dropped, stacked, a.calls, a.group_keys,
                        a.nullable, plan.pre, minput, mi_bad,
                    )
                else:
                    table, st, dropped = _epoch_reduced_fn(
                        table, st, dropped, stacked, a.calls, a.group_keys,
                        a.nullable, plan.pre,
                    )
                agg_st = (table, st, dropped, minput, mi_bad)
            else:
                chunks = (
                    jax.vmap(plan.pre)(stacked)
                    if plan.pre is not None
                    else stacked
                )
                # flatten the epoch into one batch: the MV's last-
                # occurrence-per-pk mask makes one flat step equivalent
                # to applying the chunks in order
                flat = jax.tree.map(
                    lambda x: x.reshape((-1,) + x.shape[2:]), chunks
                )
                outs.append(_through_mv(flat))

    # dirty groups pending at the barrier, sampled AFTER the epoch's
    # applies and BEFORE the flush drains them — the device-computed
    # twin of the interpreted agg's jnp.sum(state.dirty) at flush time
    dirty_groups = jnp.zeros((), jnp.int32)
    if plan.agg is not None:
        dirty_groups = jnp.sum(agg_st[1].dirty.astype(jnp.int32))

    if plan.agg is not None and flush_rounds:
        a = plan.agg
        table, st, dropped, minput, mi_bad = agg_st
        with jax.named_scope("fused/flush"):
            for r in range(flush_rounds):
                st, delta = agg_ops.flush(
                    st, table.keys, a.out_cap, a.float_extremes
                )
                outs.append(_through_mv(_delta_chunk(delta, a, pads[r])))
        agg_st = (table, st, dropped, minput, mi_bad)

    with jax.named_scope("fused/scalar_pack"):
        scal = []
        if plan.agg is not None:
            table, st, dropped, minput, mi_bad = agg_st
            scal += [dropped, st.minmax_retracted, mi_bad, table.occupancy()]
        if plan.has_mv:
            mtable, mstate = mv_st
            scal += [mstate.dropped, mtable.occupancy()]
        if scal:
            # telemetry tail rides the same staged read the barrier
            # already pays: rows applied, dirty groups, MV rows
            scal += [rows_in, dirty_groups, mv_rows]
        packed = (
            jnp.stack([jnp.asarray(x).astype(jnp.int64) for x in scal])
            if scal
            else None
        )
    return (agg_st, mv_st), tuple(outs), packed


_fused_barrier_step = partial(
    jax.jit,
    static_argnames=("plan", "flush_rounds", "pads", "has_data"),
    donate_argnums=(0,),
)(_fused_barrier_fn)


# ---------------------------------------------------------------------------
# multi-tenant compile sharing: lift per-MV constants to runtime operands
# ---------------------------------------------------------------------------

_LIFT_STATS = {"lifted": 0, "rejected": 0}


def lift_plan(plan: FusedPlan):
    """Rewrite the plan's pure segments with numeric literals lifted
    into parameter slots. Returns ``(lifted_plan, params)`` — params
    being the ``{"i": int64[...], "f": float64[...]}`` operand the
    fused program receives at dispatch — or ``(None, None)`` when the
    plan carries no liftable constants. Two plans that differ only in
    literal VALUES produce EQUAL lifted plans (same slot structure),
    so the jit cache serves both from one compiled executable."""
    ints: List[int] = []
    floats: List[float] = []

    def lift_arg(a):
        if isinstance(a, StaticTree):
            return StaticTree(lift_literals(a.value, ints, floats))
        return a

    def lift_steps(cs: Optional[ComposedSteps]) -> Optional[ComposedSteps]:
        if cs is None:
            return None
        return ComposedSteps(
            [
                partial(
                    s.func,
                    *(lift_arg(a) for a in s.args),
                    **{k: lift_arg(v) for k, v in s.keywords.items()},
                )
                for s in cs.steps
            ]
        )

    import dataclasses as _dc

    lifted = _dc.replace(
        plan,
        pre=lift_steps(plan.pre),
        mid=lift_steps(plan.mid),
        post=lift_steps(plan.post),
    )
    if not ints and not floats:
        return None, None
    params = {
        "i": jnp.asarray(ints, jnp.int64),
        "f": jnp.asarray(floats, jnp.float64),
    }
    return lifted, params


def fused_cache_stats() -> dict:
    """The compile-sharing evidence: how many distinct fused programs
    the process actually compiled (jit cache entries) vs how many
    wrappers lifted constants into a shared shape."""
    try:
        compiled = int(_fused_barrier_step._cache_size())
    except Exception:  # noqa: BLE001 — jax-internal surface
        compiled = -1
    return {
        "compiled_programs": compiled,
        "plans_lifted": _LIFT_STATS["lifted"],
        "plans_lift_rejected": _LIFT_STATS["rejected"],
    }


# ---------------------------------------------------------------------------
# the wrapper executor
# ---------------------------------------------------------------------------


def _is_pure(ex: Executor) -> bool:
    """A stateless member the fused program can absorb: pure step, no
    generated watermarks, no barrier behavior (the wrapper never calls
    member.on_barrier for pure members)."""
    return (
        ex.pure_step() is not None
        and type(ex).emit_watermark is Executor.emit_watermark
        and type(ex).on_barrier is Executor.on_barrier
    )


class FusedChainExecutor(Executor):
    """One fusible run ``[pure*, HashAgg?, pure*, DeviceMaterialize?,
    pure*]`` executed as a single donated device program per barrier.

    Drop-in chain element (the EpochBatchedAggExecutor integration
    contract): ``apply`` buffers, ``on_barrier`` runs the program and
    returns the fragment's per-barrier emission, ``finish_barrier``
    materializes the packed member scalars and runs every member's
    latch checks at their original raise points. The member executor
    OBJECTS stay the system of record — checkpoint registries,
    recovery restores, the cold tier and the shape governor all keep
    talking to them; this wrapper is an execution strategy, not a
    state owner.
    """

    def __init__(
        self,
        members: Sequence[Executor],
        label: str = "fragment",
        covers_whole_chain: bool = False,
    ):
        self.members = list(members)
        self.label = label
        self.covers_whole_chain = covers_whole_chain
        self.agg: Optional[HashAggExecutor] = None
        self.mv: Optional[DeviceMaterializeExecutor] = None
        pre: List[Executor] = []
        mid: List[Executor] = []
        post: List[Executor] = []
        for ex in self.members:
            if type(ex) is HashAggExecutor:
                if self.agg is not None or self.mv is not None:
                    raise ValueError(
                        "fused run supports one HashAgg, before the MV"
                    )
                self.agg = ex
            elif type(ex) is DeviceMaterializeExecutor:
                if self.mv is not None:
                    raise ValueError("fused run supports one device MV")
                self.mv = ex
            elif _is_pure(ex):
                (post if self.mv is not None
                 else mid if self.agg is not None
                 else pre).append(ex)
            else:
                raise ValueError(f"{type(ex).__name__} is not fusible")
        steps = lambda exs: (
            ComposedSteps([e.pure_step() for e in exs]) if exs else None
        )
        agg_statics = None
        if self.agg is not None:
            agg_statics = AggStatics(
                calls=self.agg.calls,
                group_keys=self.agg.group_keys,
                nullable=self.agg.nullable,
                out_cap=self.agg.out_cap,
                float_extremes=self.agg._float_extremes,
                has_minput=bool(self.agg.minput),
            )
        self.plan = FusedPlan(
            pre=steps(pre),
            agg=agg_statics,
            mid=steps(mid),
            mv_pk=self.mv.pk if self.mv is not None else None,
            mv_cols=self.mv.columns if self.mv is not None else None,
            post=steps(post),
        )
        # multi-tenant compile sharing: literals lifted to runtime
        # operands, accepted only after a dtype-equivalence proof at
        # the first data barrier (weak-vs-strong scalar promotion can
        # change result dtypes — correctness beats sharing)
        self._exec_plan = self.plan
        self._params = None
        self._lift_state = "off"
        if lift_enabled():
            lifted, params = lift_plan(self.plan)
            if lifted is not None:
                self._lift_candidate = (lifted, params)
                self._lift_state = "pending"
        self._buf: List[StreamChunk] = []
        self._sig = None
        # telemetry bookkeeping: padded lane count of the last staged
        # program's stacked input (masked-lane fill denominator) and
        # the last materialized telemetry dict (deviceprof mirror)
        self._last_lanes = 0
        self._telemetry: Optional[dict] = None
        # the previous program's consumed inputs, held until the
        # barrier fence: dropping a buffer an in-flight async program
        # still reads BLOCKS the host until the program completes (the
        # deallocation sync) — exactly the dispatch-wall stall the
        # fused step exists to remove. finish_barrier (which awaits the
        # program anyway) retires them instead.
        self._retired = None

    # -- static metadata --------------------------------------------------
    def lint_info(self):
        infos = []
        for m in self.members:
            fn = getattr(m, "lint_info", None)
            info = fn() if fn is not None else None
            if info is None:
                return None  # opacity propagates; never guess
            infos.append(info)
        return _compose_lint_infos(infos)

    # -- data path --------------------------------------------------------
    @staticmethod
    def _signature(c: StreamChunk):
        return (
            c.capacity,
            tuple(sorted((k, str(v.dtype)) for k, v in c.columns.items())),
            tuple(sorted(c.nulls)),
        )

    def apply(self, chunk: StreamChunk) -> List[StreamChunk]:
        outs: List[StreamChunk] = []
        sig = self._signature(chunk)
        if self._sig is not None and sig != self._sig:
            # shape change mid-epoch: flush the homogeneous batch (the
            # stacking discipline); any MV passthrough surfaces here
            outs = self._run(flush=False, stage=False)
        self._sig = sig
        self._buf.append(chunk)
        return outs

    # -- control path -----------------------------------------------------
    def on_barrier(self, barrier: Barrier) -> List[StreamChunk]:
        if self.agg is not None and self.agg._cold_barrier_hook is not None:
            self.agg._cold_barrier_hook()
        outs = self._run(flush=True, stage=True)
        if barrier is None:  # direct drive: checks fire inline
            self.finish_barrier()
        return outs

    def on_watermark(self, watermark: Watermark):
        # buffered rows precede the watermark in stream order; the
        # watermark itself walks the members interpreted (state lives
        # in the members between programs, so interop is exact)
        from risingwave_tpu.runtime.pipeline import _walk_watermark

        outs: List[StreamChunk] = []
        if self._buf:
            outs = self._run(flush=False, stage=False)
        wm, o = _walk_watermark(self.members, watermark)
        return wm, outs + o

    def finish_barrier(self) -> None:
        super().finish_barrier()
        for m in self.members:
            m.finish_barrier()  # no-op: members never stage under fusion
        # the fence above awaited the program: retiring its inputs is
        # now a plain free, not a hidden synchronization point
        self._retired = None

    def _on_barrier_scalars(self, vals) -> None:
        # telemetry FIRST: a tripped member latch raises below, and the
        # flight recorder must still see what the barrier did
        base = (4 if self.agg is not None else 0) + (
            2 if self.mv is not None else 0
        )
        if len(vals) >= base + 3:
            self._note_telemetry(vals, vals[base:base + 3])
        i = 0
        if self.agg is not None:
            self.agg._on_barrier_scalars(tuple(vals[0:4]))
            i = 4
        if self.mv is not None:
            self.mv._on_barrier_scalars(tuple(vals[i:i + 2]))

    def _note_telemetry(self, vals, tail) -> None:
        """Decode the packed telemetry lane into the deviceprof
        registry (host-side bookkeeping over values the barrier read
        anyway — zero extra device IO; never faults the barrier)."""
        try:
            rows_in, dirty_groups, mv_rows = (int(x) for x in tail)
            member_rows = {}
            occupancy = {}
            seen_agg = False
            for idx, m in enumerate(self.members):
                name = f"{idx}:{type(m).__name__}"
                if m is self.agg:
                    member_rows[name] = rows_in
                    occupancy["agg"] = int(vals[3])
                    seen_agg = True
                elif m is self.mv:
                    member_rows[name] = mv_rows
                    occupancy["mv"] = int(
                        vals[5 if self.agg is not None else 1]
                    )
                else:
                    # pure members see the input rows before the agg
                    # collapses them, the flush-delta rows after
                    member_rows[name] = mv_rows if seen_agg else rows_in
            # padded-lane waste over the members' state tables, from
            # the occupancies that rode the packed read (live lanes)
            # weighted by each member's state bytes — the live/capacity
            # accounting runtime/bucketing.padding_stats reads from the
            # device, here for free
            from risingwave_tpu.runtime.bucketing import padding_fraction

            pad_frac = padding_fraction(
                (ex.table.capacity, occupancy[key], ex.state_nbytes())
                for key, ex in (("agg", self.agg), ("mv", self.mv))
                if ex is not None and key in occupancy
            )
            lanes = self._last_lanes
            tel = {
                "rows_in": rows_in,
                "dirty_groups": dirty_groups,
                "mv_rows": mv_rows,
                "member_rows": member_rows,
                "occupancy": occupancy,
                "lanes_total": lanes,
                "lane_fill_frac": (
                    round(rows_in / lanes, 6) if lanes else 0.0
                ),
                "padding_bytes_frac": pad_frac,
            }
            self._telemetry = tel
            from risingwave_tpu.deviceprof import DEVICEPROF

            DEVICEPROF.note_telemetry(self.label, tel)
        except Exception:  # noqa: BLE001 — forensic, never load-bearing
            pass

    def _prove_lift(self, states, stacked, flush_rounds, pads) -> None:
        """Accept the lifted plan only when it is provably
        dtype-equivalent to the baked one over THIS input signature:
        abstract-trace both programs (eval_shape — no XLA) and compare
        every output aval. A weak-typed literal promoting differently
        than its strong int64/float64 parameter slot shows up here as
        a dtype mismatch — fall back to the baked plan for good."""
        lifted, params = self._lift_candidate
        ok = False
        try:
            abstract = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (states, stacked),
            )
            pav = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params
            )
            base = jax.eval_shape(
                lambda s, c: _fused_barrier_fn(
                    s, c, None, self.plan, flush_rounds, pads, True
                ),
                abstract[0],
                abstract[1],
            )
            lift = jax.eval_shape(
                lambda s, c, p: _fused_barrier_fn(
                    s, c, p, lifted, flush_rounds, pads, True
                ),
                abstract[0],
                abstract[1],
                pav,
            )
            ok = jax.tree.structure(base) == jax.tree.structure(
                lift
            ) and all(
                x.shape == y.shape and x.dtype == y.dtype
                for x, y in zip(
                    jax.tree.leaves(base), jax.tree.leaves(lift)
                )
            )
        except Exception:  # noqa: BLE001 — any trace surprise: keep baked
            ok = False
        if ok:
            self._exec_plan, self._params = lifted, params
            self._lift_state = "on"
            _LIFT_STATS["lifted"] += 1
        else:
            self._lift_state = "off"
            _LIFT_STATS["rejected"] += 1

    def _deviceprof_hook(
        self, states, stacked, flush_rounds, pads, has_data
    ) -> None:
        """Compiled-artifact roofline: analyze this (plan, bucket)
        combination ONCE via AOT lower+compile over abstract args —
        FLOPs / bytes-accessed / HBM footprint / compile ms for the
        exact program this barrier dispatches. Gated on the one
        DEVICEPROF.enabled check; never raises."""
        from risingwave_tpu.deviceprof import DEVICEPROF

        if not DEVICEPROF.enabled:
            return
        try:
            shape = (
                "x".join(map(str, stacked.valid.shape[:2]))
                if has_data
                else "-"
            )
            # member table capacities are part of the program's input
            # avals: growth mints a NEW compiled program, so it must
            # mint a new bucket too or the fragment keeps reporting
            # the pre-growth executable's modeled bytes
            caps = ".".join(
                str(ex.table.capacity)
                for ex in (self.agg, self.mv)
                if ex is not None
            )
            bucket = (
                f"fr{flush_rounds}_p{'.'.join(map(str, pads)) or '-'}"
                f"_d{int(has_data)}_n{shape}_c{caps or '-'}"
            )
            abstract = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (states, stacked),
            )
            # the deferred thunk closes over LOCALS only (abstract
            # shapes + the plan AS DISPATCHED): capturing self would
            # pin the whole executor (and its retired device buffers)
            # in the pending queue, and a post-rebuild plan mutation
            # would lower a program that no longer matches this bucket
            plan = self._exec_plan
            pav = (
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    self._params,
                )
                if self._params is not None
                else None
            )
            DEVICEPROF.ensure_program(
                f"fused:{self.label}",
                bucket,
                lambda: _fused_barrier_step.lower(
                    abstract[0],
                    abstract[1],
                    pav,
                    plan,
                    flush_rounds,
                    pads,
                    has_data,
                ),
                fragment=self.label,
            )
        except Exception:  # noqa: BLE001 — observability never faults
            pass

    def capture_checkpoint(self) -> None:
        for m in self.members:
            cap = getattr(m, "capture_checkpoint", None)
            if cap is not None:
                cap()

    # -- the program ------------------------------------------------------
    def _run(self, flush: bool, stage: bool) -> List[StreamChunk]:
        buf, self._buf, self._sig = self._buf, [], None
        has_data = bool(buf)
        stacked = None
        if has_data:
            n = len(buf)
            target = 1 << (n - 1).bit_length() if n > 1 else 1
            if target > n:
                c0 = buf[0]
                empty = StreamChunk(
                    c0.columns, jnp.zeros_like(c0.valid), c0.nulls, c0.ops
                )
                buf = buf + [empty] * (target - n)
            stacked = stack_chunks(buf)
            probe = jax.eval_shape(
                self.plan.pre if self.plan.pre is not None else (lambda c: c),
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype),
                    stacked,
                ),
            )
            incoming = len(buf) * probe.valid.shape[0]
            # host bookkeeping BEFORE the program: growth may rebuild
            # member state, and the program must see the final buffers
            if self.agg is not None:
                if self.agg._cold_stacked_hook is not None:
                    self.agg._cold_stacked_hook()
                self.agg._maybe_grow(incoming)
                self.agg._insert_bound += incoming
                self.agg._dirty_bound += incoming
            elif self.mv is not None:
                self.mv._maybe_grow(incoming)
        # the round count must be derived AFTER the buffered epoch's
        # incoming landed in the dirty bound — deriving it earlier
        # under-flushes any epoch touching more distinct groups than
        # one round drains (silent MV divergence; code-review finding).
        # Rounds and pads come from the PLAN's out_cap (the value the
        # compiled flush actually drains per round), never the agg's
        # live attribute: a post-fuse out_cap mutation must not
        # desynchronize the slice from the program.
        flush_rounds = 0
        pads: Tuple[int, ...] = ()
        if flush and self.agg is not None:
            out_cap = self.plan.agg.out_cap
            bound = min(self.agg._dirty_bound, self.agg.table.capacity)
            flush_rounds = max(1, -(-bound // out_cap))
            # the SAME two-bucket slice quantization the interpreted
            # _flush_all applies, from the same host dirty bound
            full = 2 * out_cap
            small = min(256, full)
            pads = tuple(
                (
                    small
                    if 2 * min(
                        max(bound - r * out_cap, 0), out_cap
                    ) <= small
                    else full
                )
                for r in range(flush_rounds)
            )
            if self.mv is not None:
                for p in pads:
                    self.mv._maybe_grow(p)
        if not has_data and not flush_rounds and (
            not stage or (self.agg is None and self.mv is None)
        ):
            return []  # nothing to run, nothing to stage
        states = (self._agg_state(), self._mv_state())
        if stage:
            self._last_lanes = (
                int(stacked.valid.shape[0] * stacked.valid.shape[1])
                if has_data
                else 0
            )
        if self._lift_state == "pending" and has_data:
            self._prove_lift(states, stacked, flush_rounds, pads)
        self._deviceprof_hook(states, stacked, flush_rounds, pads, has_data)
        # attribution contexts: dispatch counting (PROFILER.attribute)
        # and — under an armed jax_trace capture — a TraceAnnotation so
        # the device trace carries the fragment label next to the
        # program's fused/<stage> named scopes
        attr = ann = nullcontext()
        if PROFILER.enabled:
            attr = PROFILER.attribute(f"fused:{self.label}")
            if PROFILER.jax_trace:
                ann = jax.profiler.TraceAnnotation(f"fused:{self.label}")
        with attr, ann:
            (agg_st, mv_st), outs, packed = _fused_barrier_step(
                states,
                stacked,
                self._params,
                self._exec_plan,
                flush_rounds,
                pads,
                has_data,
            )
        if self.agg is not None:
            (
                self.agg.table,
                self.agg.state,
                self.agg.dropped,
                self.agg.minput,
                self.agg.mi_bad,
            ) = agg_st
            if flush_rounds:
                self.agg._dirty_bound = 0
        if self.mv is not None:
            self.mv.table, self.mv.state = mv_st
        if stage and packed is not None:
            try:
                packed.copy_to_host_async()
            except AttributeError:  # backend without async copies
                pass
            self._staged_scalars = packed
        # keep the program's input refs alive past this frame: their
        # deallocation would synchronize on the still-running program
        self._retired = (buf, stacked, states)
        return list(outs)

    def _agg_state(self):
        if self.agg is None:
            return ()
        return (
            self.agg.table,
            self.agg.state,
            self.agg.dropped,
            self.agg.minput,
            self.agg.mi_bad,
        )

    def _mv_state(self):
        if self.mv is None:
            return ()
        return (self.mv.table, self.mv.state)


# ---------------------------------------------------------------------------
# chain rewriting
# ---------------------------------------------------------------------------


def fuse_chain(
    chain: Sequence[Executor],
    label: str = "fragment",
    defer_pure: bool = False,
) -> List[Executor]:
    """Rewrite every maximal fusible run in an actor chain into a
    FusedChainExecutor; everything else passes through untouched (the
    interpreted fallback, per run, not per process).

    A run fuses when the whole per-barrier data path — agg apply,
    flush-delta extraction AND the device-MV write — lands inside one
    donated program (the q5 shape: ``pure* agg pure* mv pure*``):
    the flush never leaves the device, so its bound-padded delta
    capacity costs one masked device op, not an interpreted
    consumer's compute.

    Everything else keeps today's paths:

    - agg WITHOUT a downstream device MV in the run: the flush chunk
      EXITS to an interpreted consumer (a join) that wants the
      exact-sliced small chunks only the interpreted flush's status
      read can produce — fall back to the per-epoch batched wrapper
      (one fused apply program per epoch, interpreted exact flush).
    - device MV without an agg (join tails): interpreted per chunk.
      Stacking a join's heterogeneous emission chunks (capacities and
      null lanes vary) would mint a fresh compiled program per
      distinct (signature, count) batch — a compile storm, not a win.
    - pure-only runs >= 2 fuse only with ``defer_pure`` (they emit
      during ``apply`` interpreted; deferring to the barrier is only
      epoch-equivalent, so it is opt-in)."""
    from risingwave_tpu.executors.epoch_batch import (
        EpochBatchedAggExecutor,
    )

    out: List[Executor] = []
    run: List[Executor] = []

    def close() -> None:
        nonlocal run
        if not run:
            return
        agg_idx = next(
            (
                i
                for i, m in enumerate(run)
                if type(m) is HashAggExecutor
            ),
            None,
        )
        has_mv_after_agg = agg_idx is not None and any(
            type(m) is DeviceMaterializeExecutor for m in run[agg_idx:]
        )
        if has_mv_after_agg:
            out.append(FusedChainExecutor(run, label=label))
        elif agg_idx is not None:
            # flush exits to an interpreted consumer: epoch-batch the
            # [pure*, agg] head, pass the tail pures through raw
            out.append(
                EpochBatchedAggExecutor(run[:agg_idx], run[agg_idx])
            )
            out.extend(run[agg_idx + 1 :])
        elif (
            defer_pure
            and len(run) >= 2
            and not any(
                type(m) is DeviceMaterializeExecutor for m in run
            )
        ):
            # PURE runs only: a join-fed device MV must stay
            # interpreted per chunk even under defer_pure (see the
            # docstring's compile-storm rule)
            out.append(FusedChainExecutor(run, label=label))
        else:
            out.extend(run)
        run = []

    for ex in chain:
        if type(ex) is HashAggExecutor:
            if any(
                type(m) in (HashAggExecutor, DeviceMaterializeExecutor)
                for m in run
            ):
                close()
            run.append(ex)
        elif type(ex) is DeviceMaterializeExecutor:
            if any(type(m) is DeviceMaterializeExecutor for m in run):
                close()
            run.append(ex)
        elif _is_pure(ex):
            run.append(ex)
        else:
            close()
            out.append(ex)
    close()
    if (
        len(out) == 1
        and isinstance(out[0], FusedChainExecutor)
        and len(out[0].members) == len(list(chain))
    ):
        out[0].covers_whole_chain = True
    return out


def fuse_pipeline(pipeline, label: str = "mv", defer_pure: bool = False):
    """Arm fusion on a SERIAL Pipeline / TwoInputPipeline in place
    (bench drivers and twin tests; the graph runtime fuses its actor
    chains automatically). Returns the wrappers created. Note: the
    pipeline's ``executors`` enumeration then yields wrappers instead
    of members — use on driver-owned pipelines, not runtime-registered
    ones (those fuse through the graph path, which keeps its own
    checkpoint registry of member objects)."""
    created: List[FusedChainExecutor] = []

    def rewrite(chain, lbl):
        new = fuse_chain(chain, label=lbl, defer_pure=defer_pure)
        created.extend(
            e for e in new if isinstance(e, FusedChainExecutor)
        )
        return new

    if hasattr(pipeline, "join") and hasattr(pipeline, "left"):
        pipeline.left = rewrite(pipeline.left, f"{label}/left")
        pipeline.right = rewrite(pipeline.right, f"{label}/right")
        pipeline.tail = rewrite(pipeline.tail, f"{label}/tail")
    elif hasattr(pipeline, "executors"):
        pipeline.executors = rewrite(pipeline.executors, label)
    return created


def expand_fused(executors) -> List[Executor]:
    """Flatten fused wrappers back to their member executors (bench
    padding/governor surfaces read per-executor state)."""
    out: List[Executor] = []
    for ex in executors or ():
        if isinstance(ex, FusedChainExecutor):
            out.extend(ex.members)
        else:
            out.append(ex)
    return out


def fused_fragments(pipeline) -> dict:
    """BENCH-JSON evidence: how much of the pipeline actually fused
    (count + whole-chain flag + labels). Accepts serial pipelines and
    GraphPipeline (scans the live actors)."""
    graph = getattr(pipeline, "graph", None)
    exs = graph.executors if graph is not None else (
        list(getattr(pipeline, "executors", []) or [])
    )
    wrappers = [e for e in exs if isinstance(e, FusedChainExecutor)]
    return {
        "count": len(wrappers),
        "whole_chain": bool(wrappers)
        and all(w.covers_whole_chain for w in wrappers),
        "fragments": sorted(
            {f"{w.label}[{len(w.members)}]" for w in wrappers}
        ),
    }
