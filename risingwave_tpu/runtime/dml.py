"""DML — INSERT INTO routed to the streams' consuming fragments.

Reference: src/frontend/src/handler/dml.rs + src/dml/ (table source
channel: DML rows enter the stream graph through the table's source
executor). Here the host IS the channel: DmlManager turns an
InsertValues statement into one StreamChunk (schema-coerced via the
catalog) and pushes it into every fragment registered as consuming
that stream, with downstream MV deltas routed as usual.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.sql import parser as P


class DmlManager:
    def __init__(self, runtime, catalog):
        self.runtime = runtime
        self.catalog = catalog
        # stream name -> [(fragment, side)]
        self._targets: Dict[str, List[Tuple[str, str]]] = {}

    def attach(self, planned, skip=()) -> None:
        """Register a planned (and runtime-registered) MV's inputs as
        DML-reachable write targets. ``skip`` lists inputs already fed
        through fragment subscriptions (tables/MVs) — adding a direct
        target too would double-deliver every INSERT."""
        for stream, side in planned.inputs.items():
            if stream in skip:
                continue
            if stream in self.catalog.tables and not self.catalog.is_mv(stream):
                self._targets.setdefault(stream, []).append(
                    (planned.name, side)
                )

    def add_target(self, stream: str, fragment: str, side: str) -> None:
        """Route INSERTs on ``stream`` into ``fragment`` (the table's
        own materializing fragment; MVs over it ride subscriptions)."""
        self._targets.setdefault(stream, []).append((fragment, side))

    def execute(self, sql: str) -> int:
        stmt = P.parse(sql)
        if not isinstance(stmt, P.InsertValues):
            raise ValueError("DmlManager executes INSERT statements only")
        schema = self.catalog.tables[stmt.table]
        names = list(stmt.columns or schema.names)
        if set(names) - set(schema.names):
            raise KeyError(
                f"unknown columns {set(names) - set(schema.names)}"
            )
        n = len(stmt.rows)
        cols: Dict[str, np.ndarray] = {}
        nulls: Dict[str, np.ndarray] = {}
        for j, name in enumerate(names):
            field = schema.field(name)
            vals = [r[j] for r in stmt.rows]
            isnull = np.asarray([v is None for v in vals], bool)
            dt = field.dtype.device_dtype
            if field.dtype.value == "varchar":
                raise NotImplementedError(
                    f"DML into VARCHAR column {name!r} not supported yet "
                    "(needs a session string dictionary)"
                )
            filled = np.asarray(
                [0 if v is None else v for v in vals], dt
            )
            cols[name] = filled
            if isnull.any():
                nulls[name] = isnull
        missing = set(schema.names) - set(names)
        if missing:
            raise ValueError(
                f"INSERT must supply all columns (missing {missing}); "
                "column defaults are not implemented"
            )
        cap = max(2, 1 << (max(1, n) - 1).bit_length())
        chunk = StreamChunk.from_numpy(cols, cap, nulls=nulls or None)
        for frag, side in self._targets.get(stmt.table, ()):
            self.runtime.push(frag, chunk, side)
        return n
