"""DML — INSERT INTO routed to the streams' consuming fragments.

Reference: src/frontend/src/handler/dml.rs + src/dml/ (table source
channel: DML rows enter the stream graph through the table's source
executor). Here the host IS the channel: DmlManager turns an
InsertValues statement into one StreamChunk (schema-coerced via the
catalog) and pushes it into every fragment registered as consuming
that stream, with downstream MV deltas routed as usual.
"""

from __future__ import annotations

from typing import Dict, List, Tuple


from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.sql import parser as P


def _coerce_value(field, v):
    """SQL literal -> the python value composite.encode_column expects:
    JSONB string literals parse as JSON text; everything else passes
    through (decimal accepts int/float/str/Decimal natively)."""
    from risingwave_tpu.types import DataType

    if v is None:
        return None
    if field.dtype is DataType.JSONB and isinstance(v, str):
        import json

        return json.loads(v)
    return v


class DmlManager:
    def __init__(self, runtime, catalog, strings=None):
        self.runtime = runtime
        self.catalog = catalog
        # VARCHAR/JSONB dictionary shared with the session result edge
        self.strings = strings
        # stream name -> [(fragment, side)]
        self._targets: Dict[str, List[Tuple[str, str]]] = {}

    def attach(self, planned, skip=()) -> None:
        """Register a planned (and runtime-registered) MV's inputs as
        DML-reachable write targets. ``skip`` lists inputs already fed
        through fragment subscriptions (tables/MVs) — adding a direct
        target too would double-deliver every INSERT."""
        for stream, side in planned.inputs.items():
            if stream in skip:
                continue
            if stream in self.catalog.tables and not self.catalog.is_mv(stream):
                self._targets.setdefault(stream, []).append(
                    (planned.name, side)
                )

    def add_target(self, stream: str, fragment: str, side: str) -> None:
        """Route INSERTs on ``stream`` into ``fragment`` (the table's
        own materializing fragment; MVs over it ride subscriptions)."""
        self._targets.setdefault(stream, []).append((fragment, side))

    def rename_fragment(self, old: str, new: str) -> None:
        """Re-point every DML route at a renamed fragment (the shared-
        arrangement owner-drop handoff: the writer keeps consuming its
        base streams under the internal alias)."""
        for stream, targets in self._targets.items():
            self._targets[stream] = [
                ((new if f == old else f), s) for f, s in targets
            ]

    def detach_fragment(self, fragment: str) -> None:
        """Drop every target routing into ``fragment`` — the rollback
        path when a multi-MV registration fails halfway (a stale target
        would crash later INSERTs on an unregistered fragment)."""
        for stream in list(self._targets):
            kept = [
                (f, s) for f, s in self._targets[stream] if f != fragment
            ]
            if kept:
                self._targets[stream] = kept
            else:
                del self._targets[stream]

    def execute(self, sql: str) -> int:
        stmt = P.parse(sql)
        if not isinstance(stmt, P.InsertValues):
            raise ValueError("DmlManager executes INSERT statements only")
        schema = self.catalog.tables[stmt.table]
        names = list(stmt.columns or schema.names)
        if set(names) - set(schema.names):
            raise KeyError(
                f"unknown columns {set(names) - set(schema.names)}"
            )
        n = len(stmt.rows)
        missing = set(schema.names) - set(names)
        if missing:
            raise ValueError(
                f"INSERT must supply all columns (missing {missing}); "
                "column defaults are not implemented"
            )
        from risingwave_tpu.array.composite import encode_rows
        from risingwave_tpu.types import DataType

        sub = schema.select(names)
        for f in sub.fields:
            if (
                f.dtype in (DataType.VARCHAR, DataType.JSONB)
                and self.strings is None
            ):
                raise ValueError(
                    f"column {f.name!r} needs a session string dictionary"
                )
        rows = [
            tuple(
                _coerce_value(sub.fields[j], r[j]) for j in range(len(names))
            )
            for r in stmt.rows
        ]
        cols, nulls = encode_rows(sub, rows, self.strings)
        cap = max(2, 1 << (max(1, n) - 1).bit_length())
        chunk = StreamChunk.from_numpy(cols, cap, nulls=nulls or None)
        for frag, side in self._targets.get(stmt.table, ()):
            self.runtime.push(frag, chunk, side)
        return n
