"""Runtime — epoch loop, pipelines, barriers (meta-lite, single node)."""

from risingwave_tpu.runtime.pipeline import Pipeline, TwoInputPipeline

__all__ = ["Pipeline", "TwoInputPipeline"]
