"""Runtime — epoch loop, pipelines, barriers (meta-lite, single node)."""

# DeviceWedged is re-exported here because it is part of the runtime's
# failure contract: barrier()/wait_barrier raise it when the blackbox
# sentinel classifies the device WEDGED (drivers catch it next to the
# other barrier faults)
from risingwave_tpu.blackbox import DeviceWedged
from risingwave_tpu.runtime.pipeline import Pipeline, TwoInputPipeline
from risingwave_tpu.runtime.dml import DmlManager
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.runtime.notification import NotificationHub
from risingwave_tpu.runtime.source_manager import SourceManager

__all__ = [
    "DeviceWedged",
    "DmlManager",
    "Pipeline",
    "TwoInputPipeline",
    "StreamingRuntime",
    "SourceManager",
    "NotificationHub",
]
