"""Runtime — epoch loop, pipelines, barriers (meta-lite, single node)."""

from risingwave_tpu.runtime.pipeline import Pipeline, TwoInputPipeline
from risingwave_tpu.runtime.dml import DmlManager
from risingwave_tpu.runtime.runtime import StreamingRuntime
from risingwave_tpu.runtime.notification import NotificationHub
from risingwave_tpu.runtime.source_manager import SourceManager

__all__ = [
    "DmlManager",
    "Pipeline",
    "TwoInputPipeline",
    "StreamingRuntime",
    "SourceManager",
    "NotificationHub",
]
