"""Runtime — epoch loop, pipelines, barriers (meta-lite, single node)."""

# DeviceWedged is re-exported here because it is part of the runtime's
# failure contract: barrier()/wait_barrier raise it when the blackbox
# sentinel classifies the device WEDGED (drivers catch it next to the
# other barrier faults)
from risingwave_tpu.blackbox import DeviceWedged
from risingwave_tpu.runtime.pipeline import Pipeline, TwoInputPipeline
from risingwave_tpu.runtime.runtime import StreamingRuntime

__all__ = [
    "ArrangementRegistry",
    "DeviceWedged",
    "DmlManager",
    "FusedChainExecutor",
    "Pipeline",
    "TwoInputPipeline",
    "StreamingRuntime",
    "SourceManager",
    "NotificationHub",
    "fuse_chain",
    "fuse_pipeline",
]

# Lazy (PEP 562) exports: DmlManager pulls in the SQL planner, which
# imports the executors package — and executors now import
# runtime.bucketing at module level (the shape-stability layer), so an
# eager import here would close a cycle through a partially
# initialized executors package.
_LAZY = {
    "ArrangementRegistry": (
        "risingwave_tpu.runtime.arrangements",
        "ArrangementRegistry",
    ),
    "DmlManager": ("risingwave_tpu.runtime.dml", "DmlManager"),
    # the fused per-barrier step imports the executors package (it
    # composes their pure steps), so it must stay lazy here too
    "FusedChainExecutor": (
        "risingwave_tpu.runtime.fused_step",
        "FusedChainExecutor",
    ),
    "fuse_chain": ("risingwave_tpu.runtime.fused_step", "fuse_chain"),
    "fuse_pipeline": (
        "risingwave_tpu.runtime.fused_step",
        "fuse_pipeline",
    ),
    "SourceManager": (
        "risingwave_tpu.runtime.source_manager",
        "SourceManager",
    ),
    "NotificationHub": (
        "risingwave_tpu.runtime.notification",
        "NotificationHub",
    ),
}


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(name)
    import importlib

    value = getattr(importlib.import_module(entry[0]), entry[1])
    globals()[name] = value
    return value
