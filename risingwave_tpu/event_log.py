"""Meta event log — ring-buffered cluster history + JSONL spill.

Reference: the meta node's event log (src/meta/src/manager/event_log.rs
+ ``risectl meta event-log``) recording DDL, barrier commits,
recoveries, scale events, and connector offset resumes so an operator
can reconstruct *what the cluster did* after the fact. Here: one
process-wide ring (bounded deque — the hot path never grows memory)
plus an optional JSONL spill file for durability across the process,
served at ``/events`` on the metrics HTTP server and rendered on the
dashboard.

Recording sites (grow as subsystems need them):
- ``ddl``            — frontend/session.py, every DDL statement
- ``barrier_commit`` — runtime, each durable checkpoint epoch
- ``recovery``       — runtime recovery, with cause; ``mode`` is one of
                       ``partial`` (fragment-scoped restore started),
                       ``partial_done`` (subtree restored + replayed),
                       ``partial_deferred`` (store unavailable — blast
                       radius stays fenced until the breaker heals),
                       ``auto`` (full stop-the-world recovery), or
                       ``restore`` (explicit/manual full restore)
- ``actor_failure``  — graph supervisor: actor death attributed to its
                       fragment, with the computed blast radius
- ``scale``          — parallel/scale.py reschedules
- ``offset_resume``  — source executors resuming connector offsets
- ``stall_dump``     — epoch_trace.dump_stalls artifacts
- ``stall_dump_fallback`` — RW_STALL_DIR was unwritable; the dump
                       landed in the system temp dir instead
- ``profile_capture`` — profiler.py capture window closed (on-demand
                       or slow-barrier auto-trigger), with the
                       PROFILE_* artifact path
- ``breaker``        — resilience.CircuitBreaker state transitions
                       (closed/open/half_open, with the breaker name)
- ``degraded``       — runtime entered degraded mode: store breaker
                       open mid-epoch, checkpoint deltas spilling
                       locally, compaction paused
- ``restored``       — degraded spill fully replayed, store healthy
- ``degraded_discard`` — recovery discarded a stale degraded spill
                       (sources replay those epochs instead)
- ``device_state``   — blackbox sentinel (or the out-of-process tunnel
                       prober) observed an ALIVE/SLOW/WEDGED transition
- ``wedge_dump``     — blackbox sentinel captured a WEDGE_*.json
                       forensic bundle for a wedged device
- ``recompile_hazard`` — SignatureWatch saw a post-warmup novel
                       abstract input signature (shape escaped the
                       bucket lattice; RW-E403/E803 cross-reference)
- ``shape_governor`` — runtime/bucketing.ShapeGovernor throttled a
                       recompile storm: the named executor class was
                       pinned to its max bucket (reason
                       budget_exceeded | slow_device)
- ``skew``           — parallel/meshprof.py hot-shard verdict: one
                       shard's routed rows exceeded RW_SKEW_RATIO x
                       the per-shard mean this barrier (fields:
                       table_id, shard, ratio, frac, rows)
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from risingwave_tpu.metrics import REGISTRY

_DEFAULT_CAPACITY = 4096


class EventLog:
    def __init__(
        self,
        capacity: int = _DEFAULT_CAPACITY,
        spill_path: Optional[str] = None,
    ):
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        # JSONL spill: the ring forgets, the file does not (best-effort)
        self.spill_path = spill_path or os.environ.get("RW_EVENT_LOG_PATH")

    def set_spill(self, path: Optional[str]) -> None:
        with self._lock:
            self.spill_path = path

    def record(self, kind: str, **fields) -> Dict:
        """Append one event. ``fields`` must be JSON-serializable (the
        spill and the /events endpoint both emit JSON)."""
        with self._lock:
            self._seq += 1
            ev = {"seq": self._seq, "ts": time.time(), "kind": kind}
            ev.update(fields)
            self._events.append(ev)
            spill = self.spill_path
        REGISTRY.counter("events_total").inc(kind=kind)
        if spill:
            try:
                with open(spill, "a") as f:
                    f.write(json.dumps(ev, default=str) + "\n")
            except OSError:
                pass  # spill is forensic, never load-bearing
        return ev

    def events(
        self, kind: Optional[str] = None, limit: Optional[int] = None
    ) -> List[Dict]:
        """Newest-last snapshot, optionally filtered by kind."""
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        if limit is not None:
            out = out[-limit:]
        return out

    def to_json(self, limit: Optional[int] = None) -> str:
        return json.dumps({"events": self.events(limit=limit)}, default=str)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


# the process-default log (reference: the meta node's single event log)
EVENT_LOG = EventLog()
record = EVENT_LOG.record
