"""Out-of-process UDF server.

Reference: src/expr/impl/src/udf/external.rs — an external UDF service
the cluster calls per batch (arrow-flight there). TPU re-design: UDF
bodies never belong on the device path anyway (they are host python),
so the wire is a plain length-prefixed JSON frame over TCP — dependency
-free, batch-at-a-time, with per-row error->NULL semantics matching the
embedded runtime.

Frame: 4-byte big-endian length + UTF-8 JSON.
  request : {"fn": name, "cols": [[...], ...]}    (column-major batch)
  response: {"values": [...], "nulls": [...]}     or {"error": "..."}

Serve functions from a python file:
  python -m risingwave_tpu.udf_server --port 8816 --file my_fns.py
Every top-level callable in the file (not starting with "_") is served
under its name. NULL cells arrive as None; a row raising becomes NULL.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Callable, Dict, Optional


def _json_cell(o):
    """JSON fallback for common UDF return types: numpy scalars carry
    .item(); Decimal and friends cross as str (the client's DECIMAL
    lane parses text)."""
    if hasattr(o, "item"):
        return o.item()
    return str(o)


def read_frame(sock) -> Optional[bytes]:
    hdr = b""
    while len(hdr) < 4:
        part = sock.recv(4 - len(hdr))
        if not part:
            return None
        hdr += part
    (n,) = struct.unpack(">I", hdr)
    buf = b""
    while len(buf) < n:
        part = sock.recv(min(1 << 16, n - len(buf)))
        if not part:
            return None
        buf += part
    return buf


def write_frame(sock, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


class UdfServer:
    """Threaded TCP server hosting a {name: callable} registry."""

    def __init__(self, fns: Dict[str, Callable], host="127.0.0.1", port=0):
        self.fns = dict(fns)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    raw = read_frame(self.request)
                    if raw is None:
                        return
                    try:
                        resp = outer._dispatch(json.loads(raw))
                        # numpy scalars etc. serialize via .item();
                        # anything else unserializable must become an
                        # ERROR FRAME, never a dead socket (the client
                        # would misreport 'service unreachable')
                        payload = json.dumps(resp, default=_json_cell)
                    except Exception as e:  # malformed frame / result
                        payload = json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}
                        )
                    write_frame(self.request, payload.encode("utf-8"))

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = "{}:{}".format(*self._server.server_address)
        self._thread: Optional[threading.Thread] = None

    def _dispatch(self, req: dict) -> dict:
        fn = self.fns.get(req.get("fn"))
        if fn is None:
            return {"error": f"unknown function {req.get('fn')!r}"}
        cols = req.get("cols", [])
        n = len(cols[0]) if cols else 0
        values, nulls = [], []
        for i in range(n):
            args = [c[i] for c in cols]
            if any(a is None for a in args):
                values.append(None)  # NULL-strict, like the kernels
                nulls.append(True)
                continue
            try:
                values.append(fn(*args))
                nulls.append(False)
            except Exception:  # row error -> NULL (non_strict.rs)
                values.append(None)
                nulls.append(True)
        return {"values": values, "nulls": nulls}

    def start(self) -> "UdfServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def call_external(
    address: str,
    fn: str,
    cols,
    timeout: float = 5.0,
    retries: int = 2,
):
    """One batched UDF call with retry-on-fresh-connection (the
    reference client retries flight RPCs). Raises RuntimeError when
    the server stays unreachable or reports an error — a missing UDF
    service is a query error, not silent NULLs."""
    host, _, port = address.rpartition(":")
    last: Optional[Exception] = None
    for _ in range(retries + 1):
        try:
            with socket.create_connection(
                (host or "127.0.0.1", int(port)), timeout=timeout
            ) as sock:
                sock.settimeout(timeout)
                write_frame(
                    sock,
                    json.dumps({"fn": fn, "cols": cols}).encode("utf-8"),
                )
                raw = read_frame(sock)
                if raw is None:
                    raise ConnectionError("server closed mid-response")
                resp = json.loads(raw)
                if "error" in resp:
                    raise RuntimeError(
                        f"external UDF {fn!r}: {resp['error']}"
                    )
                return resp["values"], resp["nulls"]
        except (OSError, ConnectionError, json.JSONDecodeError) as e:
            last = e
    raise RuntimeError(
        f"external UDF service {address} unreachable: {last}"
    ) from last


def _main() -> None:
    import argparse
    import runpy

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8816)
    ap.add_argument(
        "--file", required=True, help="python file defining the UDFs"
    )
    args = ap.parse_args()
    ns = runpy.run_path(args.file)
    fns = {
        k: v
        for k, v in ns.items()
        if callable(v) and not k.startswith("_")
    }
    srv = UdfServer(fns, args.host, args.port)
    print(f"udf server on {srv.address} serving {sorted(fns)}", flush=True)
    srv._server.serve_forever()


if __name__ == "__main__":
    _main()
