"""Type system for the TPU dataflow plane.

Reference: src/common/src/types/ (DataType / ScalarImpl, 20+ SQL types).

The device plane is deliberately narrower than the reference's SQL type
zoo: TPUs want fixed-width vector lanes, so every device column is one of
a small set of JAX dtypes. Wider SQL types are mapped at the host edge:

- INT16/INT32          -> int32
- INT64                -> int64 (real 64-bit lanes; the package enables
                          jax x64 so these never silently truncate)
- FLOAT32              -> float32
- FLOAT64              -> float64 (real f64 — SQL DOUBLE sums must not
                          drift; XLA emulates f64 on TPU, and hot agg
                          payloads may opt into f32/bf16 explicitly)
- BOOLEAN              -> bool_
- TIMESTAMP            -> int64 milliseconds since epoch (Nexmark and the
                          reference both carry ms timestamps)
- VARCHAR              -> int32 dictionary code (dictionary lives host-side,
                          see array/dictionary.py)
- DECIMAL              -> scaled int64 at the host edge

Ops on a StreamChunk follow the reference exactly
(src/common/src/array/stream_chunk.rs:45): Insert / Delete /
UpdateDelete / UpdateInsert. ``Op.sign`` maps these to +1/-1 retraction
signs used by every aggregation kernel.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np


class Op(enum.IntEnum):
    """Row-level change op (reference: stream_chunk.rs:45)."""

    INSERT = 0
    DELETE = 1
    UPDATE_DELETE = 2
    UPDATE_INSERT = 3


def op_sign(ops: jnp.ndarray) -> jnp.ndarray:
    """+1 for Insert/UpdateInsert, -1 for Delete/UpdateDelete."""
    retract = (ops == Op.DELETE) | (ops == Op.UPDATE_DELETE)
    return jnp.where(retract, jnp.int32(-1), jnp.int32(1))


class DataType(enum.Enum):
    """Logical column types at the SQL/host edge.

    Wider SQL types map onto fixed-width device lanes
    (src/common/src/types/ has the same split between logical DataType
    and physical array repr):
    - DECIMAL(p, s) -> scaled int64 (value * 10^s); +,-,sum,compare run
      directly on the scaled lane, exact (Field.scale carries s);
    - INTERVAL -> two lanes, ``name.months`` int32 + ``name.usecs``
      int64 (days folded into usecs; the reference keeps months apart
      for calendar arithmetic, interval months are not a fixed usec
      count);
    - JSONB -> int32 dictionary code over the canonical JSON text
      (sort_keys serialization => equality on codes IS jsonb equality);
    - STRUCT -> one device lane per leaf field, named ``parent.child``
      (columnar decomposition — idiomatic struct-of-arrays);
    - LIST -> ``name.<i>`` element lanes padded to Field.list_cap plus
      a ``name.#`` length lane (static shapes; ragged data is hostile
      to XLA).
    Composite expansion lives in array/composite.py.
    """

    INT32 = "int32"
    INT64 = "int64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    BOOLEAN = "boolean"
    TIMESTAMP = "timestamp"  # ms since epoch, int64 on device
    VARCHAR = "varchar"  # dictionary-encoded int32 on device
    DECIMAL = "decimal"  # scaled int64 on device (Field.scale)
    INTERVAL = "interval"  # composite: months int32 + usecs int64
    JSONB = "jsonb"  # dictionary-encoded canonical JSON, int32
    STRUCT = "struct"  # composite: child lanes (Field.children)
    LIST = "list"  # composite: padded element lanes (Field.elem/cap)
    INT256 = "int256"  # composite: 4 little-endian int64 limbs

    @property
    def device_dtype(self) -> np.dtype:
        d = {
            DataType.INT32: np.dtype(np.int32),
            DataType.INT64: np.dtype(np.int64),
            DataType.FLOAT32: np.dtype(np.float32),
            DataType.FLOAT64: np.dtype(np.float64),
            DataType.BOOLEAN: np.dtype(np.bool_),
            DataType.TIMESTAMP: np.dtype(np.int64),
            DataType.VARCHAR: np.dtype(np.int32),
            DataType.DECIMAL: np.dtype(np.int64),
            DataType.JSONB: np.dtype(np.int32),
        }.get(self)
        if d is None:
            raise TypeError(
                f"{self} is composite: expand via array/composite.py"
            )
        return d

    @property
    def is_composite(self) -> bool:
        return self in (
            DataType.INTERVAL,
            DataType.STRUCT,
            DataType.LIST,
            DataType.INT256,
        )

    @property
    def null_value(self):
        """Padding value used in invalid lanes (never observed by kernels)."""
        if self is DataType.FLOAT32:
            return np.float32(0.0)
        if self is DataType.FLOAT64:
            return np.float64(0.0)
        if self is DataType.BOOLEAN:
            return np.bool_(False)
        return self.device_dtype.type(0)


@dataclass(frozen=True)
class Interval:
    """SQL INTERVAL value (reference: src/common/src/types/interval.rs
    keeps months/days/usecs; days fold into usecs here — no calendar
    DST modelling on the dataflow plane)."""

    months: int = 0
    usecs: int = 0

    @staticmethod
    def of(months=0, days=0, hours=0, minutes=0, seconds=0, usecs=0):
        return Interval(
            months=months,
            usecs=usecs
            + int(seconds * 1_000_000)
            + minutes * 60_000_000
            + hours * 3_600_000_000
            + days * 86_400_000_000,
        )

    def total_usecs(self) -> int:
        """Fixed-usec view; months use the reference's 30-day estimate
        (interval.rs comparison semantics)."""
        return self.months * 30 * 86_400_000_000 + self.usecs


@dataclass(frozen=True)
class Field:
    """A named, typed column in a schema.

    Type parameters ride on the field (the reference puts them inside
    DataType variants): ``scale`` for DECIMAL(p, s); ``children`` (a
    Schema) for STRUCT; ``elem`` + ``list_cap`` for LIST.
    """

    name: str
    dtype: DataType
    scale: "int | None" = None
    children: "Schema | None" = None
    elem: "DataType | None" = None
    list_cap: "int | None" = None

    def __post_init__(self):
        if self.dtype is DataType.DECIMAL and self.scale is None:
            object.__setattr__(self, "scale", 6)  # pg-ish default
        if self.dtype is DataType.STRUCT and self.children is None:
            raise ValueError(f"STRUCT field {self.name!r} needs children")
        if self.dtype is DataType.LIST:
            if self.elem is None:
                raise ValueError(f"LIST field {self.name!r} needs elem")
            if self.list_cap is None:
                object.__setattr__(self, "list_cap", 16)

    def __repr__(self) -> str:  # compact for schema dumps
        return f"{self.name}:{self.dtype.value}"


@dataclass(frozen=True)
class Schema:
    """Ordered list of fields (reference: src/common/src/catalog/schema.rs)."""

    fields: tuple[Field, ...]

    def __init__(self, fields):
        object.__setattr__(
            self,
            "fields",
            tuple(
                f if isinstance(f, Field) else Field(f[0], f[1]) for f in fields
            ),
        )

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.fields)

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def select(self, names) -> "Schema":
        return Schema(tuple(self.field(n) for n in names))

    def concat(self, other: "Schema", prefix: str = "") -> "Schema":
        return Schema(
            self.fields
            + tuple(Field(prefix + f.name, f.dtype) for f in other.fields)
        )


def schema_from_dtypes(dtypes: dict) -> Schema:
    """Device dtypes -> logical Schema (the reverse edge mapping; used
    when registering a planned MV's output as a catalog relation for
    MV-on-MV queries)."""
    rev = {
        np.dtype(np.int32): DataType.INT32,
        np.dtype(np.int64): DataType.INT64,
        np.dtype(np.float32): DataType.FLOAT32,
        np.dtype(np.float64): DataType.FLOAT64,
        np.dtype(np.bool_): DataType.BOOLEAN,
    }
    return Schema(
        tuple(Field(n, rev[np.dtype(d)]) for n, d in dtypes.items())
    )
