"""Meta store (catalog/DDL persistence) + cluster backup/restore.

Reference roles:
- meta store / catalog persistence (src/meta/src/storage/, sea-orm
  model_v2/): DDL survives restarts. Here the meta store is a DDL log
  + the session string dictionary, persisted as JSON blobs in the same
  object store as Hummock state (the reference uses etcd/SQL; ours
  rides the durability boundary that already exists);
- backup/restore (src/storage/backup/, backup_reader.rs): a backup is
  a SELF-CONTAINED prefix holding the meta snapshot, the version
  manifest, and every SST the manifest references — restorable into an
  empty store.

Restart flow (the reference's cluster bootstrap): replay the DDL log
with backfill/barriers suppressed (structure only), then
``runtime.recover()`` restores every executor's state from the last
committed epoch — tables, MVs, source offsets, dictionary.
"""

from __future__ import annotations

import json
from typing import List, Optional


from risingwave_tpu.storage.object_store import ObjectStore

DDL_PATH = "meta/ddl.json"
STRINGS_PATH = "meta/strings.json"
BACKUP_PREFIX = "backup"


class MetaStore:
    """Durable DDL log + dictionary snapshot."""

    def __init__(self, store: ObjectStore):
        self.store = store
        self._ddl: List[str] = []
        if store.exists(DDL_PATH):
            self._ddl = json.loads(store.read(DDL_PATH))

    def append_ddl(self, sql: str) -> None:
        self._ddl.append(sql)
        self.store.put(DDL_PATH, json.dumps(self._ddl).encode())

    def ddl(self) -> List[str]:
        return list(self._ddl)

    def save_strings(self, dump: List[str]) -> None:
        self.store.put(STRINGS_PATH, json.dumps(dump).encode())

    def load_strings(self) -> Optional[List[str]]:
        if not self.store.exists(STRINGS_PATH):
            return None
        return json.loads(self.store.read(STRINGS_PATH))


from risingwave_tpu.storage.state_table import Checkpointable


class DictionaryPersistor(Checkpointable):
    """Aux state object: persists the session dictionary at checkpoint
    STAGE time — strictly BEFORE the manifest that references its codes
    becomes durable (persisting after the commit left a crash window
    where committed state held codes the persisted dictionary lacked).
    A dictionary persisted ahead of a failed commit is harmless: extra
    codes decode nothing."""

    def __init__(self, strings, meta: MetaStore):
        self.strings = strings
        self.meta = meta
        self._persisted_len = 0

    def checkpoint_table_ids(self):
        return ()

    def checkpoint_delta(self):
        if len(self.strings) != self._persisted_len:
            self.meta.save_strings(self.strings.dump())
            self._persisted_len = len(self.strings)
        return []

    def state_digest(self) -> int:
        from risingwave_tpu.integrity import host_obj_digest

        return host_obj_digest(self.strings.dump())

    def restore_state(self, table_id, key_cols, value_cols):
        return None


# ---------------------------------------------------------------------------
# backup / restore
# ---------------------------------------------------------------------------


def create_backup(store: ObjectStore, backup_id: str) -> dict:
    """Copy the meta snapshot + current manifest + every referenced SST
    into ``backup/<id>/`` (self-contained; reference: meta snapshot
    backup, src/storage/backup/).

    Every SST is checksum-VERIFIED on the copy read: a faithfully
    copied corrupt SST makes the backup worthless, so a wrong byte
    fails the backup loudly (StateCorruption naming the artifact,
    which is also quarantined) instead of laundering the corruption
    into the backup prefix."""
    from risingwave_tpu.integrity import decode_manifest
    from risingwave_tpu.storage.state_table import (
        MANIFEST,
        verify_sst_entry,
    )

    manifest_paths = [
        p
        for p in store.list("")
        if p.endswith(MANIFEST)
        and not p.startswith(BACKUP_PREFIX + "/")
        # a backup must not recursively swallow older backups (their
        # manifests reference SSTs the live GC may have deleted)
    ]
    copied = []
    ssts = 0
    for mp in manifest_paths:
        raw = store.read(mp)
        # decode_manifest verifies the envelope crc (and unwraps the
        # format-2 payload); a torn/corrupt manifest fails the backup
        manifest = decode_manifest(raw, artifact=mp)
        dst = f"{BACKUP_PREFIX}/{backup_id}/{mp}"
        store.put(dst, raw)
        copied.append(mp)
        # version["tables"]: table_id -> [{"path", "epoch", "crc"}, ...]
        for entries in manifest.get("tables", {}).values():
            for e in entries:
                store.put(
                    f"{BACKUP_PREFIX}/{backup_id}/{e['path']}",
                    verify_sst_entry(store, e),
                )
                ssts += 1
    for p in (DDL_PATH, STRINGS_PATH):
        if store.exists(p):
            store.put(f"{BACKUP_PREFIX}/{backup_id}/{p}", store.read(p))
            copied.append(p)
    summary = {
        "backup_id": backup_id,
        "manifests": len(manifest_paths),
        "ssts": ssts,
        "meta": [p for p in copied if p.startswith("meta/")],
    }
    store.put(
        f"{BACKUP_PREFIX}/{backup_id}/BACKUP_META",
        json.dumps(summary).encode(),
    )
    return summary


def list_backups(store: ObjectStore) -> List[str]:
    out = []
    for p in store.list(BACKUP_PREFIX + "/"):
        if p.endswith("/BACKUP_META"):
            out.append(p.split("/")[1])
    return sorted(set(out))


def restore_backup(
    src: ObjectStore, backup_id: str, dst: ObjectStore
) -> int:
    """Materialize a backup into ``dst`` (typically an empty store for
    a fresh cluster). Returns blobs restored."""
    prefix = f"{BACKUP_PREFIX}/{backup_id}/"
    blobs = [p for p in src.list(prefix) if not p.endswith("BACKUP_META")]
    if not blobs:
        raise KeyError(f"unknown backup {backup_id!r}")
    for p in blobs:
        dst.put(p[len(prefix):], src.read(p))
    return len(blobs)
