"""Block-granular SSTs — partial reads, range/backward iteration.

Reference: src/storage/src/hummock/sstable/builder.rs:95 (block-based
layout: data blocks + block index + bloom, read via ranged object GETs)
and iterator/ (forward/backward block iterators).

Layout (one immutable object):

    magic  b"RWBSST2\\0"                      (8 bytes)
    header_len  uint64 LE                     (8 bytes)
    header JSON                               (header_len bytes)
      {"meta": {table_id, epoch, n_rows, key_names, value_names},
       "blocks": [{"off", "len", "n",
                   "first": [order-key ints], "last": [...]}, ...],
       "bloom": {"off", "len"}}
    block 0 .. block B-1   (each an npz of its row slice)
    bloom bytes

Blocks are sorted by memcomparable key; ``first``/``last`` are the
block's boundary keys in the order-key (unsigned memcomparable) domain,
so readers prune blocks with pure integer tuple comparisons before any
data IO. Point reads touch the header + at most one block per query;
range scans touch only overlapping blocks; backward iteration walks
blocks (and rows) in reverse.
"""

from __future__ import annotations

import io
import json
import struct
from bisect import bisect_left, bisect_right
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.integrity import (
    crc32_bytes,
    raise_corruption,
)
from risingwave_tpu.storage.sstable import (
    Sst,
    SstMeta,
    _bloom_build,
    _bloom_may_contain,
    _order_key,
    key_hashes,
    sort_order,
)

MAGIC = b"RWBSST2\0"
DEFAULT_BLOCK_ROWS = 4096
_BLOCK_CACHE_CAP = 16  # parsed blocks held per reader (LRU)


def _npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def build_block_sst(
    table_id: str,
    epoch: int,
    key_cols: Dict[str, np.ndarray],
    value_cols: Dict[str, np.ndarray],
    tombstone: np.ndarray,
    key_order: Sequence[str],
    block_rows: int = DEFAULT_BLOCK_ROWS,
) -> bytes:
    """Serialize rows sorted by key into the block layout above."""
    order = sort_order([key_cols[k] for k in key_order])
    n = len(order)
    keys = {k: np.asarray(key_cols[k])[order] for k in key_cols}
    vals = {v: np.asarray(value_cols[v])[order] for v in value_cols}
    tomb = np.asarray(tombstone, bool)[order]
    okeys = [
        _order_key(keys[k]).astype(np.uint64) for k in key_order
    ]

    blocks_meta: List[dict] = []
    blobs: List[bytes] = []
    for at in range(0, max(n, 1), block_rows):
        hi = min(at + block_rows, n)
        if hi <= at and n > 0:
            break
        sl = slice(at, hi)
        payload = {f"k_{k}": a[sl] for k, a in keys.items()}
        payload.update({f"v_{v}": a[sl] for v, a in vals.items()})
        payload["tombstone"] = tomb[sl]
        blob = _npz_bytes(payload)
        blocks_meta.append(
            {
                "len": len(blob),
                "n": hi - at,
                "first": [int(a[at]) for a in okeys] if n else [],
                "last": [int(a[hi - 1]) for a in okeys] if n else [],
                # content checksum, verified on EVERY block read (the
                # reference's per-block xxhash footer, as crc32 here)
                "crc": crc32_bytes(blob),
            }
        )
        blobs.append(blob)
        if n == 0:
            break

    bloom = _bloom_build(
        key_hashes([keys[k] for k in key_order]), n
    ).tobytes()
    meta = {
        "table_id": table_id,
        "epoch": epoch,
        "n_rows": int(n),
        "key_names": list(key_order),
        "value_names": sorted(value_cols),
        # key-lane dtypes ride the header so readers can build order-
        # key bounds for pruning WITHOUT touching any data block
        "key_dtypes": [str(keys[k].dtype) for k in key_order],
    }

    # two passes: offsets depend on the header length, which depends on
    # the offsets' digits — fix by padding the header to its final size
    def render(header: dict) -> bytes:
        return json.dumps(header).encode()

    header = {"meta": meta, "blocks": blocks_meta, "bloom": {}}
    for _ in range(3):
        hl = len(render(header))
        off = 16 + hl
        for bm, blob in zip(blocks_meta, blobs):
            bm["off"] = off
            off += len(blob)
        header["bloom"] = {"off": off, "len": len(bloom), "crc": crc32_bytes(bloom)}
        if len(render(header)) == hl:
            break
    else:  # pad with spaces (valid JSON whitespace) to stabilize
        hl = len(render(header)) + 16
        raw = render(header)
        raw += b" " * (hl - len(raw))
        off = 16 + hl
        for bm, blob in zip(blocks_meta, blobs):
            bm["off"] = off
            off += len(blob)
        header["bloom"] = {"off": off, "len": len(bloom), "crc": crc32_bytes(bloom)}
        raw2 = render(header)
        assert len(raw2) <= hl
        out = [MAGIC, struct.pack("<Q", hl), raw2 + b" " * (hl - len(raw2))]
        out.extend(blobs)
        out.append(bloom)
        return b"".join(out)
    raw = render(header)
    out = [MAGIC, struct.pack("<Q", len(raw)), raw]
    out.extend(blobs)
    out.append(bloom)
    return b"".join(out)


def is_block_sst(head: bytes) -> bool:
    return head[:8] == MAGIC


def verify_block_blob(blob: bytes) -> List[str]:
    """Audit every checksum a block-SST blob carries (scrub / backup
    deep verification): returns a list of human-readable problems,
    empty when the whole artifact verifies."""
    problems: List[str] = []
    if not is_block_sst(blob[:8]):
        return ["not a block SST (bad magic)"]
    try:
        (hl,) = struct.unpack("<Q", blob[8:16])
        hdr = json.loads(blob[16 : 16 + hl].decode())
    except (struct.error, UnicodeDecodeError, ValueError) as e:
        return [f"torn header: {e}"]
    for i, bm in enumerate(hdr.get("blocks", [])):
        want = bm.get("crc")
        if want is None:
            continue
        got = crc32_bytes(blob[bm["off"] : bm["off"] + bm["len"]])
        if got != want:
            problems.append(
                f"block {i} crc mismatch (expected {want}, got {got})"
            )
    bl = hdr.get("bloom", {})
    want = bl.get("crc")
    if want is not None:
        got = crc32_bytes(blob[bl["off"] : bl["off"] + bl["len"]])
        if got != want:
            problems.append(
                f"bloom crc mismatch (expected {want}, got {got})"
            )
    return problems


def header_crc(blob: bytes) -> int:
    """crc32 of a built block-SST's header bytes. The header itself
    cannot carry its own checksum, so the manifest entry records it
    (``hdr_crc``) and readers verify at open — rooting the per-block
    crc chain in the manifest's own crc envelope."""
    (hl,) = struct.unpack("<Q", blob[8:16])
    return crc32_bytes(blob[16 : 16 + hl])


def order_tuple(values: Sequence[object], dtypes) -> Tuple[int, ...]:
    """One key's order-key tuple (for block pruning comparisons)."""
    return tuple(
        int(_order_key(np.asarray([v], dtype=dt))[0])
        for v, dt in zip(values, dtypes)
    )


class BlockSst:
    """Reader over the block layout: header-only open, lazy bloom,
    per-block LRU cache, point/range/backward reads."""

    def __init__(self, store, path: str, expected_hdr_crc: int = None):
        self.store = store
        self.path = path
        head = store.read_range(path, 0, 16)
        if not is_block_sst(head):
            raise ValueError(f"{path} is not a block SST")
        try:
            (hl,) = struct.unpack("<Q", head[8:16])
            raw_hdr = store.read_range(path, 16, hl)
            if (
                expected_hdr_crc is not None
                and crc32_bytes(raw_hdr) != expected_hdr_crc
            ):
                # a WRONG header (vs a torn one, below) is corruption:
                # its offsets/crcs can no longer be trusted to verify
                # anything else, so fail the whole artifact here
                raise_corruption(
                    store, path, "sst-header-crc",
                    expected=expected_hdr_crc,
                    actual=crc32_bytes(raw_hdr),
                )
            hdr = json.loads(raw_hdr.decode())
        except (struct.error, UnicodeDecodeError) as e:
            # a torn/partial header read (flaky ranged GET) must surface
            # in the ValueError domain the storage retry loops classify
            # as a transient decode race — not escape as struct.error
            raise ValueError(f"torn block-SST header at {path}") from e
        m = hdr["meta"]
        self.meta = SstMeta(
            table_id=m["table_id"],
            epoch=m["epoch"],
            n_rows=m["n_rows"],
            key_names=tuple(m["key_names"]),
            value_names=tuple(m["value_names"]),
        )
        self.blocks = hdr["blocks"]
        self.key_dtypes = [
            np.dtype(d) for d in m.get("key_dtypes", [])
        ]
        self._bloom_span = (hdr["bloom"]["off"], hdr["bloom"]["len"])
        self._bloom_crc = hdr["bloom"].get("crc")  # pre-crc files: None
        self._bloom: Optional[np.ndarray] = None
        self._cache: "OrderedDict[int, dict]" = OrderedDict()
        self._firsts = [tuple(b["first"]) for b in self.blocks]
        self._lasts = [tuple(b["last"]) for b in self.blocks]

    # -- pruning ---------------------------------------------------------
    def bloom_bits(self) -> np.ndarray:
        if self._bloom is None:
            off, ln = self._bloom_span
            raw = self.store.read_range(self.path, off, ln)
            want = self._bloom_crc
            if want is not None and crc32_bytes(raw) != want:
                raise_corruption(
                    self.store, self.path, "sst-bloom-crc",
                    expected=want, actual=crc32_bytes(raw),
                )
            self._bloom = np.frombuffer(raw, np.uint8)
        return self._bloom

    def may_contain(self, key_cols: Sequence[np.ndarray]) -> np.ndarray:
        return _bloom_may_contain(self.bloom_bits(), key_hashes(key_cols))

    def key_range(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """(first, last) order-key tuples of the whole file."""
        if not self.blocks or self.meta.n_rows == 0:
            return ((), ())
        return self._firsts[0], self._lasts[-1]

    def _load_block(self, i: int) -> dict:
        blk = self._cache.get(i)
        if blk is not None:
            self._cache.move_to_end(i)
            return blk
        bm = self.blocks[i]
        raw = self.store.read_range(self.path, bm["off"], bm["len"])
        want = bm.get("crc")  # pre-crc files carry no block checksum
        if want is not None and crc32_bytes(raw) != want:
            raise_corruption(
                self.store, self.path, "sst-block-crc",
                detail=f"block {i}", expected=want,
                actual=crc32_bytes(raw),
            )
        z = np.load(io.BytesIO(raw))
        blk = {name: z[name] for name in z.files}
        self._cache[i] = blk
        if len(self._cache) > _BLOCK_CACHE_CAP:
            self._cache.popitem(last=False)
        return blk

    # -- point reads -----------------------------------------------------
    def point_read(
        self, key_cols: Sequence[np.ndarray], mask: np.ndarray
    ):
        """Per masked query: (hit, tomb, row values). Touches at most
        one block per query key (binary search on block bounds)."""
        nq = len(mask)
        hit = np.zeros(nq, bool)
        tomb = np.zeros(nq, bool)
        vals: Dict[str, np.ndarray] = {}
        if self.meta.n_rows == 0:
            return hit, tomb, vals
        qlanes = [np.asarray(c) for c in key_cols]
        okq = [
            _order_key(q).astype(np.uint64) for q in qlanes
        ]
        for i in np.flatnonzero(mask):
            qt = tuple(int(a[i]) for a in okq)
            bi = bisect_left(self._lasts, qt)
            if bi >= len(self.blocks) or self._firsts[bi] > qt:
                continue
            blk = self._load_block(bi)
            rows = np.ones(self.blocks[bi]["n"], bool)
            for name, q in zip(self.meta.key_names, qlanes):
                rows &= blk[f"k_{name}"] == q[i]
            idx = np.flatnonzero(rows)
            if not len(idx):
                continue
            r = int(idx[0])
            hit[i] = True
            tomb[i] = bool(blk["tombstone"][r])
            for vn in self.meta.value_names:
                col = blk[f"v_{vn}"]
                if vn not in vals:
                    vals[vn] = np.zeros((nq,) + col.shape[1:], col.dtype)
                vals[vn][i] = col[r]
        return hit, tomb, vals

    # -- range scans -----------------------------------------------------
    def scan_blocks(
        self,
        lo: Optional[Tuple[int, ...]] = None,
        hi: Optional[Tuple[int, ...]] = None,
        reverse: bool = False,
    ) -> Iterator[dict]:
        """Yield parsed blocks overlapping [lo, hi] (order-key tuple
        prefixes, inclusive), in key order (reverse = backward). A
        bound shorter than the key width compares as a prefix."""
        if self.meta.n_rows == 0:
            return
        b0, b1 = 0, len(self.blocks) - 1
        if lo is not None:
            # first block whose last >= lo
            b0 = bisect_left([t[: len(lo)] for t in self._lasts], lo)
        if hi is not None:
            b1 = (
                bisect_right([t[: len(hi)] for t in self._firsts], hi)
                - 1
            )
        rng = range(b0, b1 + 1)
        for i in reversed(rng) if reverse else rng:
            if 0 <= i < len(self.blocks):
                yield self._load_block(i)

    def materialize(self) -> Sst:
        """Full load (recovery path): equivalent classic Sst."""
        ks = {k: [] for k in self.meta.key_names}
        vs = {v: [] for v in self.meta.value_names}
        ts = []
        for blk in self.scan_blocks():
            for k in self.meta.key_names:
                ks[k].append(blk[f"k_{k}"])
            for v in self.meta.value_names:
                vs[v].append(blk[f"v_{v}"])
            ts.append(blk["tombstone"])
        cat = lambda xs: (
            np.concatenate(xs) if xs else np.zeros(0)
        )
        return Sst(
            self.meta,
            {k: cat(x) for k, x in ks.items()},
            {v: cat(x) for v, x in vs.items()},
            cat(ts) if ts else np.zeros(0, bool),
            self.bloom_bits(),
        )
