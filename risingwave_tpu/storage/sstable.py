"""SSTable — immutable sorted epoch-delta files.

Reference: src/storage/src/hummock/sstable/ (block-based SST with
bloom/xor filters and min-max metadata; full key = user key ‖ epoch,
docs/state-store-overview.md).

TPU-native re-design: state rows are fixed-dtype COLUMNS, not byte
strings — so an SST here is a columnar blob (npz): key lanes + value
lanes sorted by memcomparable key order, a tombstone lane, and
metadata (table id, epoch, row count, min/max key, a split-block bloom
filter over key hashes). Sorting uses the same total-order bit tricks
as the reference's memcomparable encoding (ints offset to unsigned,
floats via the ordered-float transform — ops/agg order keys), so byte
comparison order == SQL ORDER BY order lane by lane.

Merge-on-read recovery: iterate SSTs newest-epoch-first per key,
first hit wins, tombstones drop the key (UserIterator + MergeIterator
semantics, src/storage/src/hummock/iterator/).
"""

from __future__ import annotations

import io
import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

BLOOM_BITS_PER_KEY = 10


def _order_key(col: np.ndarray) -> np.ndarray:
    """Map a lane to unsigned memcomparable order (reference:
    util/memcmp_encoding.rs semantics, vectorized)."""
    if col.dtype == np.bool_:
        return col.astype(np.uint8)
    if np.issubdtype(col.dtype, np.unsignedinteger):
        return col
    if np.issubdtype(col.dtype, np.integer):
        u = col.astype(np.uint64 if col.dtype.itemsize == 8 else np.uint32)
        sign = np.uint64(1) << np.uint64(63) if col.dtype.itemsize == 8 else np.uint32(1) << np.uint32(31)
        return u ^ sign
    if col.dtype == np.float64 or col.dtype == np.float32:
        u_t = np.uint64 if col.dtype == np.float64 else np.uint32
        bits = col.view(u_t)
        sign = u_t(1) << u_t(col.dtype.itemsize * 8 - 1)
        neg = (bits & sign) != 0
        return np.where(neg, ~bits, bits | sign)
    raise TypeError(f"unsupported key dtype {col.dtype}")


def sort_order(key_cols: Sequence[np.ndarray]) -> np.ndarray:
    """Row order by lexicographic memcomparable key (last lane minor)."""
    lanes = [_order_key(np.asarray(c)) for c in key_cols]
    return np.lexsort(tuple(reversed(lanes)))


def _bloom_build(hashes: np.ndarray, n_keys: int) -> np.ndarray:
    nbits = max(64, 1 << int(np.ceil(np.log2(max(1, n_keys) * BLOOM_BITS_PER_KEY))))
    bits = np.zeros(nbits // 8, np.uint8)
    for rot in (0, 21, 42):
        idx = ((hashes >> np.uint64(rot)) % np.uint64(nbits)).astype(np.int64)
        np.bitwise_or.at(bits, idx // 8, (1 << (idx % 8)).astype(np.uint8))
    return bits


def _bloom_may_contain(bits: np.ndarray, hashes: np.ndarray) -> np.ndarray:
    nbits = np.uint64(len(bits) * 8)
    ok = np.ones(len(hashes), bool)
    for rot in (0, 21, 42):
        idx = ((hashes >> np.uint64(rot)) % nbits).astype(np.int64)
        ok &= (bits[idx // 8] & (1 << (idx % 8)).astype(np.uint8)) != 0
    return ok


def key_hashes(key_cols: Sequence[np.ndarray]) -> np.ndarray:
    """64-bit fnv-ish hash per row over all key lanes (host side)."""
    n = len(np.asarray(key_cols[0]))
    h = np.full(n, 0xCBF29CE484222325, np.uint64)
    for c in key_cols:
        u = _order_key(np.asarray(c)).astype(np.uint64)
        h = (h ^ u) * np.uint64(0x100000001B3)
        h ^= h >> np.uint64(29)
    return h


@dataclass
class SstMeta:
    table_id: str
    epoch: int
    n_rows: int
    key_names: Tuple[str, ...]
    value_names: Tuple[str, ...]


def build_sst(
    table_id: str,
    epoch: int,
    key_cols: Dict[str, np.ndarray],
    value_cols: Dict[str, np.ndarray],
    tombstone: np.ndarray,
    key_order: Sequence[str],
) -> bytes:
    """Serialize one epoch delta, sorted by key, with bloom + meta."""
    order = sort_order([key_cols[k] for k in key_order])
    payload = {f"k_{n}": np.asarray(c)[order] for n, c in key_cols.items()}
    payload.update({f"v_{n}": np.asarray(c)[order] for n, c in value_cols.items()})
    payload["tombstone"] = np.asarray(tombstone, bool)[order]
    payload["bloom"] = _bloom_build(
        key_hashes([key_cols[k] for k in key_order])[order], len(order)
    )
    meta = SstMeta(
        table_id=table_id,
        epoch=epoch,
        n_rows=int(len(order)),
        key_names=tuple(key_order),
        value_names=tuple(sorted(value_cols)),
    )
    payload["meta"] = np.frombuffer(
        json.dumps(meta.__dict__).encode(), dtype=np.uint8
    )
    buf = io.BytesIO()
    np.savez_compressed(buf, **payload)
    return buf.getvalue()


@dataclass
class Sst:
    meta: SstMeta
    keys: Dict[str, np.ndarray]
    values: Dict[str, np.ndarray]
    tombstone: np.ndarray
    bloom: np.ndarray
    _index: Optional[dict] = None  # lazy hash -> row indices

    def may_contain(self, key_cols: Sequence[np.ndarray]) -> np.ndarray:
        return _bloom_may_contain(self.bloom, key_hashes(key_cols))

    def lookup_rows(
        self, key_cols: Sequence[np.ndarray], mask: np.ndarray
    ) -> np.ndarray:
        """Point lookup (sstable block-index analogue): row index per
        query, -1 if absent. Only queries with ``mask`` are resolved.
        The lazy hash index plays the role of the reference's block
        index + binary search (sstable/block.rs) on columnar rows."""
        lanes = [np.asarray(self.keys[k]) for k in self.meta.key_names]
        if self._index is None:
            idx: dict = {}
            for i, h in enumerate(key_hashes(lanes)):
                idx.setdefault(int(h), []).append(i)
            self._index = idx
        qh = key_hashes(key_cols)
        out = np.full(len(mask), -1, np.int64)
        qlanes = [np.asarray(c) for c in key_cols]
        for i in np.flatnonzero(mask):
            for row in self._index.get(int(qh[i]), ()):
                if all(l[row] == q[i] for l, q in zip(lanes, qlanes)):
                    out[i] = row
                    break
        return out

    def prefix_mask(self, prefix_cols: Dict[str, object]) -> np.ndarray:
        """Vectorized equality mask over a key-lane prefix (range scan
        within the SST; prefix scans are what backfill/temporal joins
        issue, store.rs:298)."""
        ok = np.ones(self.meta.n_rows, bool)
        for name, v in prefix_cols.items():
            ok &= np.asarray(self.keys[name]) == v
        return ok


def read_sst(blob: bytes) -> Sst:
    z = np.load(io.BytesIO(blob))
    meta_d = json.loads(bytes(z["meta"]).decode())
    meta = SstMeta(
        table_id=meta_d["table_id"],
        epoch=meta_d["epoch"],
        n_rows=meta_d["n_rows"],
        key_names=tuple(meta_d["key_names"]),
        value_names=tuple(meta_d["value_names"]),
    )
    keys = {n: z[f"k_{n}"] for n in meta.key_names}
    values = {n: z[f"v_{n}"] for n in meta.value_names}
    return Sst(meta, keys, values, z["tombstone"], z["bloom"])


def merge_ssts(
    ssts: List[Sst], key_order: Sequence[str]
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """Merge-on-read: newest epoch wins per key; tombstones drop.

    Returns (key_cols, value_cols) of the surviving rows — the analogue
    of a full UserIterator scan at the max committed epoch.
    """
    if not ssts:
        return {}, {}
    ssts = sorted(ssts, key=lambda s: s.meta.epoch)
    key_names = list(key_order)
    value_names = list(ssts[-1].meta.value_names)

    keys = {n: np.concatenate([s.keys[n] for s in ssts]) for n in ssts[-1].keys}

    def _val_lane(s, n):
        # lane-set evolution: a lane absent from an OLDER sst reads as
        # zeros (bool lanes: False). Concretely: a table's NULL
        # companion lanes (materialize vn{j}) appear only once its
        # backend demotes to the nullable python path — rows written
        # before that are by construction non-NULL.
        if n in s.values:
            return s.values[n]
        ref = ssts[-1].values[n]
        return np.zeros(s.meta.n_rows, ref.dtype)

    vals = {
        n: np.concatenate([_val_lane(s, n) for s in ssts])
        for n in value_names
    }
    tomb = np.concatenate([s.tombstone for s in ssts])
    epochs = np.concatenate(
        [np.full(s.meta.n_rows, s.meta.epoch, np.int64) for s in ssts]
    )
    return newest_wins(keys, vals, tomb, epochs, key_names)


def newest_wins(
    keys: Dict[str, np.ndarray],
    vals: Dict[str, np.ndarray],
    tomb: np.ndarray,
    epochs: np.ndarray,
    key_names: Sequence[str],
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """Resolve a multi-epoch row soup: keep each key's newest row,
    dropping tombstoned keys (UserIterator semantics)."""
    order = np.lexsort(
        tuple([epochs] + [_order_key(keys[k]) for k in reversed(list(key_names))])
    )
    k_sorted = {n: a[order] for n, a in keys.items()}
    is_last = np.ones(len(order), bool)
    if len(order) > 1:
        same = np.ones(len(order) - 1, bool)
        for n in key_names:
            same &= k_sorted[n][1:] == k_sorted[n][:-1]
        is_last[:-1] = ~same
    keep = is_last & ~tomb[order]
    sel = order[keep]
    return (
        {n: a[sel] for n, a in keys.items()},
        {n: a[sel] for n, a in vals.items()},
    )
