"""Object store — the durability boundary.

Reference: src/object_store/ (ObjectStore trait; S3 object/s3.rs,
in-mem object/mem.rs, local-fs opendal engine). The streaming state
machine only needs put/read/list/delete of immutable blobs; everything
above (SSTs, manifests) is layered on that, so swapping local-FS for a
cloud store later changes nothing else.

Writes are atomic: LocalFsObjectStore stages to a temp file and
renames, so a crash mid-upload never leaves a half-written SST visible
(the reference gets this from S3 put semantics).
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Dict, List


class ObjectStore:
    def put(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def read_range(self, path: str, off: int, length: int) -> bytes:
        """Partial object read (reference: ObjectStore::read with a
        block range, object/s3.rs ranged GET) — what block-granular
        SST reads ride on. Default: slice a full read (stores with a
        native ranged read override)."""
        return self.read(path)[off : off + length]

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list(self, prefix: str) -> List[str]:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def resilient(self, policy=None, breaker=None) -> "ObjectStore":
        """Wrap this store in the retrying, breaker-gated boundary
        (resilience.RetryingObjectStore) — the production posture for
        any store that can transiently fail. Idempotent: wrapping a
        wrapper returns it unchanged."""
        from risingwave_tpu.resilience import RetryingObjectStore

        if isinstance(self, RetryingObjectStore):
            return self
        return RetryingObjectStore(self, policy, breaker)


class MemObjectStore(ObjectStore):
    """In-memory store (reference: object/mem.rs) — tests & sim."""

    def __init__(self):
        self._blobs: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        self.bytes_read = 0  # test observability: IO actually paid

    def put(self, path: str, data: bytes) -> None:
        with self._lock:
            self._blobs[path] = bytes(data)

    def read(self, path: str) -> bytes:
        with self._lock:
            if path not in self._blobs:
                raise FileNotFoundError(path)
            b = self._blobs[path]
            self.bytes_read += len(b)
            return b

    def read_range(self, path: str, off: int, length: int) -> bytes:
        with self._lock:
            if path not in self._blobs:
                raise FileNotFoundError(path)
            b = self._blobs[path][off : off + length]
            self.bytes_read += len(b)
            return b

    def exists(self, path: str) -> bool:
        with self._lock:
            return path in self._blobs

    def list(self, prefix: str) -> List[str]:
        with self._lock:
            return sorted(p for p in self._blobs if p.startswith(prefix))

    def delete(self, path: str) -> None:
        with self._lock:
            self._blobs.pop(path, None)


class LocalFsObjectStore(ObjectStore):
    """Local filesystem store with atomic rename puts."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _abs(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path))
        if not p.startswith(os.path.normpath(self.root)):
            raise ValueError(f"path escapes store root: {path}")
        return p

    def put(self, path: str, data: bytes) -> None:
        dst = self._abs(path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dst), suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, dst)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def read(self, path: str) -> bytes:
        with open(self._abs(path), "rb") as f:
            return f.read()

    def read_range(self, path: str, off: int, length: int) -> bytes:
        with open(self._abs(path), "rb") as f:
            f.seek(off)
            return f.read(length)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))

    def list(self, prefix: str) -> List[str]:
        out = []
        for dirpath, _, files in os.walk(self.root):
            for fn in files:
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix) and not rel.endswith(".tmp"):
                    out.append(rel)
        return sorted(out)

    def delete(self, path: str) -> None:
        try:
            os.unlink(self._abs(path))
        except FileNotFoundError:
            pass
