"""StateTable checkpoint layer — Hummock-lite version + commit_epoch.

Reference roles replaced:
- ``StateTable::commit`` staging an epoch's memtable into the shared
  buffer for upload (src/stream/src/common/table/state_table.rs:1140,
  src/storage/src/hummock/event_handler/uploader.rs:548);
- ``HummockManager::commit_epoch`` pinning uploaded SSTs into a new
  HummockVersion (src/meta/src/hummock/manager/commit_epoch.rs:93);
- full-merge compaction (src/storage/src/hummock/compactor/).

TPU re-design: executor state lives in HBM as slot-indexed arrays;
``sdirty``/``stored`` lanes on the device state track what changed
since the last checkpoint. At a checkpoint barrier each Checkpointable
executor stages its delta (device→host pull, compacted to the changed
rows), the manager writes one SST per table, then commits the MANIFEST
atomically — the epoch is durable iff the manifest says so (a crash
between SST puts and manifest write recovers to the previous epoch;
orphan SSTs are ignored and reclaimed by compaction GC).

Recovery: ``recover(executors)`` merge-reads each table's SSTs
(newest-epoch-wins, tombstones drop) and hands the surviving rows to
the executor's ``restore_state`` to rebuild device state.
"""

from __future__ import annotations

import json
import threading
from collections import deque as _deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.integrity import (
    StateCorruption,
    crc32_bytes,
    decode_manifest,
    digest_enabled,
    encode_manifest,
    host_rows_digest,
    note_corruption,
    quarantine,
    raise_corruption,
)
from risingwave_tpu.resilience import (
    STORE_UNAVAILABLE,
    CircuitBreaker,
    RetryingObjectStore,
    RetryPolicy,
)
from risingwave_tpu.storage.object_store import ObjectStore
from risingwave_tpu.storage.block_sst import (
    BlockSst,
    build_block_sst,
    header_crc,
    order_tuple,
    verify_block_blob,
)
from risingwave_tpu.storage.sstable import (
    _order_key,
    build_sst,
    merge_ssts,
    newest_wins,
    read_sst,
)

MANIFEST = "MANIFEST"
MANIFEST_HISTORY = "manifests"  # per-epoch manifest copies (walk-back)
MANIFEST_KEEP = 8  # history retention (walk-back depth)
COMPACT_AT = 8  # L0 SSTs per table before a leveled compaction
L1_FILE_ROWS = 1 << 16  # target rows per non-overlapping L1 file


class EpochFloorError(RuntimeError):
    """An MVCC pin below the table's compaction floor: that history
    has been folded away. Deliberately NOT a ValueError — the read
    retry loop treats ValueError as a transient decode race."""


@dataclass
class StateDelta:
    """One table's staged epoch delta (host-side, compacted).

    Staging flips the executor's device sdirty/stored marks EAGERLY —
    slot indices shift on rehash, so a deferred flip would hit wrong
    slots. The durability contract is therefore the reference's
    (barrier/mod.rs:676): if a commit FAILS, in-memory marks are ahead
    of storage and the process MUST recover() from the last durable
    manifest — never retry the commit against live state.
    """

    table_id: str
    key_cols: Dict[str, np.ndarray]
    value_cols: Dict[str, np.ndarray]
    tombstone: np.ndarray
    key_order: Tuple[str, ...]


def stage_marks(
    sdirty: np.ndarray, alive: np.ndarray, stored: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The shared upsert/tombstone classification every Checkpointable
    executor uses: returns (upsert_mask, tombstone_mask, sel_indices)."""
    upsert = sdirty & alive
    tomb = sdirty & stored & ~alive
    return upsert, tomb, np.flatnonzero(upsert | tomb)


def grow_pow2(n: int, cap: int, grow_at: float = 0.5) -> int:
    """Smallest power-of-two capacity >= cap holding n under grow_at."""
    while n > cap * grow_at:
        cap *= 2
    return cap


def host_key_view(a: np.ndarray) -> np.ndarray:
    """Canonical integer view of a key lane for host-side cold-tier
    set membership. Float lanes become their exact bit patterns (the
    cold set needs identity, not numeric comparison), so float-keyed
    state can evict/fault-in without round-tripping through lossy
    python floats."""
    a = np.asarray(a)
    if a.dtype.kind == "f":
        return a.view(np.int32 if a.itemsize == 4 else np.int64)
    if a.dtype.kind == "b":
        return a.astype(np.int64)
    return a


def lanes_from_host_keys(key_tuples, dtypes) -> Dict[str, np.ndarray]:
    """Inverse of host_key_view over a set of canonical key tuples:
    rebuild k{i} lanes in their native dtypes (bit-casting back into
    float lanes)."""
    out = {}
    for i, dt in enumerate(dtypes):
        dt = np.dtype(dt)
        arr = np.asarray([t[i] for t in key_tuples], dtype=np.int64)
        if dt.kind == "f":
            w = arr.astype(np.int32 if dt.itemsize == 4 else np.int64)
            out[f"k{i}"] = w.view(dt)
        else:
            out[f"k{i}"] = arr.astype(dt)
    return out


def pull_rows(device_lanes: Dict[str, object], sel: np.ndarray) -> Dict[str, np.ndarray]:
    """Device->host transfer of SELECTED rows only (checkpoint staging
    must be O(changed rows), not O(capacity)). ``sel`` is padded to a
    power-of-two bucket so jit caches one gather program per bucket
    size instead of recompiling per distinct count."""
    n = len(sel)
    if n == 0:
        return {k: np.asarray(a)[:0] for k, a in device_lanes.items()}
    pad = 1 << (n - 1).bit_length()
    idx = np.zeros(pad, np.int32)
    idx[:n] = sel
    gathered = _gather(dict(device_lanes), jnp.asarray(idx))
    return {k: np.asarray(a)[:n] for k, a in gathered.items()}


@jax.jit
def _gather(lanes, idx):
    return jax.tree.map(lambda a: a[idx], lanes)


class Checkpointable:
    """Executor mixin: stateful executors that persist through the
    checkpoint manager implement these three members."""

    table_id: str = ""

    def checkpoint_table_ids(self) -> List[str]:
        return [self.table_id]

    def checkpoint_delta(self) -> List[StateDelta]:
        """Stage rows changed since the last checkpoint and CLEAR the
        device-side sdirty marks (update stored marks)."""
        raise NotImplementedError

    # -- pipelined barriers: capture-at-barrier (the memtable seal) ----
    # With more than one barrier in flight, the delta for epoch N must
    # be pulled BEFORE any epoch-N+1 row mutates this executor's state.
    # Actor threads call ``capture_checkpoint`` while processing the
    # checkpoint barrier (FIFO channels guarantee nothing from N+1 has
    # been applied yet — the shared-buffer seal point,
    # /root/reference/src/storage/src/hummock/shared_buffer/); the
    # checkpoint manager later consumes captures in epoch order.
    _captured_deltas = None

    def capture_checkpoint(self) -> None:
        if self._captured_deltas is None:
            self._captured_deltas = _deque()
        self._captured_deltas.append(self.checkpoint_delta())

    def staged_or_live_delta(self) -> List[StateDelta]:
        """Oldest captured delta if any (pipelined mode), else a live
        pull (synchronous mode)."""
        if self._captured_deltas:
            return self._captured_deltas.popleft()
        return self.checkpoint_delta()

    def discard_captured(self) -> None:
        """Recovery: captured deltas of rolled-back epochs are stale."""
        if self._captured_deltas is not None:
            self._captured_deltas.clear()

    def restore_state(
        self, table_id: str, key_cols: Dict[str, np.ndarray],
        value_cols: Dict[str, np.ndarray],
    ) -> None:
        raise NotImplementedError

    # -- integrity: the state-digest contract (rwlint RW-E709) ---------
    def state_digest(self) -> int:
        """Order-insensitive fingerprint of this executor's DURABLE
        LOGICAL state (integrity.host_digest over its lanes, or
        integrity.host_obj_digest for host-dict state). Bookkeeping
        lanes (sdirty/stored/latches) are excluded by contract — they
        differ legitimately across a restore. Every Checkpointable
        executor must override this (RW-E709 flags the ones that
        don't); the fused engine computes the same fold on-device so
        fused-vs-interpreted runs cross-check per barrier."""
        raise NotImplementedError(
            f"{type(self).__name__} has no state_digest() — "
            "see rwlint RW-E709"
        )


class CheckpointManager:
    """Version authority + per-epoch committer (meta-lite).

    Thread model (uploader.rs:548 + commit_epoch.rs:93 analogue): the
    version is guarded by one RLock; ``stage`` (validation + device
    pull) and ``commit_staged`` (SST build + manifest) are the single
    commit path shared by the sync caller and the runtime's async lane.
    Compaction never runs inside a commit — it is scheduled separately
    (``compact_once``) and swaps the version CAS-style under the lock,
    so a racing commit can never be lost.
    """

    def __init__(
        self,
        store: ObjectStore,
        prefix: str = "hummock",
        compact_at: int = COMPACT_AT,
        retry_policy: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
        read_retry: Optional[RetryPolicy] = None,
    ):
        # the durability boundary: EVERY store touch (SST upload,
        # manifest commit, compaction IO, block reads) goes through the
        # retrying, monitored wrapper (reference: src/object_store/'s
        # RetryCondition around each op). Transient classification is
        # narrow (TransientStoreError/ConnectionError/Timeout), so
        # in-mem and local-fs stores behave exactly as before; chaos
        # CrashPoints are BaseExceptions and always propagate.
        if not isinstance(store, RetryingObjectStore):
            store = RetryingObjectStore(
                store, retry_policy or RetryPolicy.from_env(), breaker
            )
        self.store = store
        # read-closure retries (GC race / torn decode) reload the
        # manifest between attempts; deadline + backoff bound what was
        # previously an ad-hoc fixed-count spin
        self._read_policy = read_retry or RetryPolicy.from_env(
            max_attempts=8, base_backoff_s=0.002, max_backoff_s=0.05
        )
        self.prefix = prefix
        self.compact_at = compact_at
        self._lock = threading.RLock()
        self.version = {"max_committed_epoch": 0, "tables": {}}
        self._sst_cache: Dict[str, object] = {}  # path -> parsed Sst
        # stage()-buffered cleaning watermarks: durable only WITH the
        # epoch that staged them (commit_staged applies + persists)
        self._pending_watermarks: Dict[str, Tuple[str, int]] = {}
        self._load()

    # -- table watermarks (state cleaning) --------------------------------
    def update_table_watermark(
        self, table_id: str, key_name: str, value: int
    ) -> None:
        """Advance a table's cleaning watermark: rows whose ``key_name``
        falls BELOW it are expired and may be dropped by compaction
        (reference: StateTable::update_watermark -> Hummock table
        watermarks -> iterator/skip_watermark.rs dropping expired keys
        during compaction). Monotonic; persisted with the manifest so
        a restart keeps cleaning."""
        with self._lock:
            wms = self.version.setdefault("watermarks", {})
            cur = wms.get(table_id)
            if cur is not None and cur[0] == key_name and cur[1] >= value:
                return
            wms[table_id] = [key_name, int(value)]
            self._persist_version()

    def table_watermark(self, table_id: str):
        with self._lock:
            wm = self.version.get("watermarks", {}).get(table_id)
            return tuple(wm) if wm else None

    # -- version ---------------------------------------------------------
    def _manifest_path(self) -> str:
        return f"{self.prefix}/{MANIFEST}"

    def _history_path(self, epoch: int) -> str:
        return f"{self.prefix}/{MANIFEST_HISTORY}/{epoch:020d}"

    def _load(self):
        """Read + verify the manifest pointer. A torn tail (the crash-
        mid-write window) or a crc mismatch quarantines the pointer and
        walks back through the per-epoch manifest history to the newest
        copy that fully verifies — recovery lands on the previous
        durable epoch instead of crashing on a half-written JSON."""
        path = self._manifest_path()
        if not self.store.exists(path):
            return
        raw = self.store.read(path)
        try:
            self.version = decode_manifest(raw, artifact=path)
            return
        except StateCorruption as exc:
            exc.quarantined = quarantine(self.store, path, raw)
            note_corruption(exc)
            v = self._walk_back()
            if v is None:
                raise  # no verifying history: surface, never guess
            self.version = v
            self._persist_version()  # heal the pointer

    def _walk_back(
        self, bad_paths=frozenset(), deep: bool = False
    ) -> Optional[dict]:
        """Newest manifest-history copy whose checksum chain fully
        verifies: the envelope crc, no reference to a known-bad
        artifact, every referenced SST present (and, when ``deep``,
        content-crc-verified). Returns the decoded version or None."""
        try:
            cands = sorted(
                self.store.list(f"{self.prefix}/{MANIFEST_HISTORY}/"),
                reverse=True,
            )
        except Exception:  # noqa: BLE001 — a dead store ends the walk
            return None
        for p in cands:
            try:
                v = decode_manifest(self.store.read(p), artifact=p)
            except (StateCorruption, OSError, ValueError):
                continue
            entries = [
                e
                for es in v.get("tables", {}).values()
                for e in es
            ]
            if any(e["path"] in bad_paths for e in entries):
                continue
            try:
                ok = all(
                    self._entry_verifies(e, deep=deep) for e in entries
                )
            except Exception:  # noqa: BLE001
                ok = False
            if ok:
                return v
        return None

    def _entry_verifies(self, e: dict, deep: bool = False) -> bool:
        if not self.store.exists(e["path"]):
            return False
        if not deep:
            return True
        data = self.store.read(e["path"])
        want = e.get("crc")
        if want is not None and crc32_bytes(data) != want:
            return False
        if e.get("format") == "block":
            want_h = e.get("hdr_crc")
            if want_h is not None and header_crc(data) != want_h:
                return False
            if verify_block_blob(data):
                return False
        return True

    def _persist_version(self):
        blob = encode_manifest(self.version)
        self.store.put(self._manifest_path(), blob)
        # a per-epoch history copy makes walk-back possible: the
        # pointer alone is one overwritten object — a torn write there
        # would otherwise erase the only path back to durable state
        ep = int(self.version["max_committed_epoch"])
        self.store.put(self._history_path(ep), blob)
        self._gc_history(ep)

    def _gc_history(self, newest_epoch: int) -> None:
        """Bounded retention: keep the newest MANIFEST_KEEP history
        copies (best-effort — retention never fails a commit)."""
        try:
            hist = sorted(
                self.store.list(f"{self.prefix}/{MANIFEST_HISTORY}/")
            )
            for p in hist[:-MANIFEST_KEEP]:
                self.store.delete(p)
        except Exception:  # noqa: BLE001
            pass

    @property
    def max_committed_epoch(self) -> int:
        with self._lock:
            return int(self.version["max_committed_epoch"])

    # -- commit path -----------------------------------------------------
    def stage(self, executors: Sequence[object]) -> List[StateDelta]:
        """Pull every Checkpointable executor's delta (the only device-
        touching step) with the duplicate-table_id check. Mark flips are
        eager (see StateDelta): a later commit failure requires
        recover(), never a retry against live state."""
        staged: List[StateDelta] = []
        seen_ids = set()
        for ex in executors:
            if not isinstance(ex, Checkpointable):
                continue
            # executors with watermark-driven cleaning advance their
            # table's skip-watermark here — BUFFERED: it becomes
            # durable with this epoch's manifest commit, never before
            # (compaction acting on an early watermark could drop
            # state whose downstream emissions were not yet durable)
            wm_fn = getattr(ex, "cleaning_watermarks", None)
            if wm_fn is not None:
                for tid, key, val in wm_fn():
                    cur = self._pending_watermarks.get(tid)
                    if cur is None or cur[0] != key or cur[1] < val:
                        self._pending_watermarks[tid] = (key, int(val))
            for delta in ex.staged_or_live_delta():
                if delta.table_id in seen_ids:
                    raise ValueError(
                        f"duplicate table_id {delta.table_id!r} in one "
                        "commit — give each executor a unique table_id"
                    )
                seen_ids.add(delta.table_id)
                staged.append(delta)
        return staged

    def commit_staged(
        self,
        epoch: int,
        staged: Sequence[StateDelta],
        trace=None,
    ) -> int:
        """Build + upload SSTs for a staged epoch, then commit the
        manifest. The single commit implementation behind both the sync
        path and the runtime's async worker. Returns SSTs written.
        ``trace`` (an EpochTrace) receives the upload / manifest_commit
        stage attribution; without one the stages still land in the
        ``barrier_stage_ms`` histogram."""
        import time as _time

        with self._lock:
            if epoch <= int(self.version["max_committed_epoch"]):
                raise ValueError(
                    f"epoch {epoch} <= committed "
                    f"{self.version['max_committed_epoch']}"
                )
        t_upload = _time.perf_counter()
        n = 0
        new_entries = []  # (table_id, entry) — registered under lock below
        for delta in staged:
            if len(delta.tombstone) == 0:
                continue
            blob = build_sst(
                delta.table_id,
                epoch,
                delta.key_cols,
                delta.value_cols,
                delta.tombstone,
                delta.key_order,
            )
            path = f"{self.prefix}/sst/{delta.table_id}/{epoch:020d}.sst"
            self.store.put(path, blob)
            new_entries.append(
                (
                    delta.table_id,
                    # content crc written AT BUILD, verified on every
                    # read path (_open_entry / scrub / backup)
                    {"path": path, "epoch": epoch,
                     "crc": crc32_bytes(blob)},
                )
            )
            n += 1
        from risingwave_tpu import utils_sync_point as sync_point

        upload_ms = (_time.perf_counter() - t_upload) * 1e3
        # SSTs are uploaded but the manifest is NOT yet written: the
        # classic crash window (recovery must land on the previous
        # epoch); tests inject crashes here (utils_sync_point)
        sync_point.hit("before_manifest_commit")
        t_manifest = _time.perf_counter()
        with self._lock:
            # re-validate under the lock: a concurrent commit may have
            # advanced the epoch while our SSTs uploaded; publishing
            # unconditionally could regress max_committed_epoch
            if epoch <= int(self.version["max_committed_epoch"]):
                for _, entry in new_entries:
                    self.store.delete(entry["path"])
                raise ValueError(
                    f"epoch {epoch} <= committed "
                    f"{self.version['max_committed_epoch']} (lost race)"
                )
            for table_id, entry in new_entries:
                self.version["tables"].setdefault(table_id, []).append(entry)
            self.version["max_committed_epoch"] = epoch
            # cleaning watermarks become durable WITH this epoch: the
            # emissions they license compaction to destroy are durable
            # in the same manifest write
            if self._pending_watermarks:
                wms = self.version.setdefault("watermarks", {})
                for tid, (key, val) in self._pending_watermarks.items():
                    cur = wms.get(tid)
                    if cur is None or cur[0] != key or cur[1] < val:
                        wms[tid] = [key, val]
                self._pending_watermarks = {}
            if digest_enabled():
                # per-table epoch digest over the post-commit row image
                # (order-insensitive; merge-on-read applied) — recovery
                # verifies restored state against these
                digs = self.version.setdefault("digests", {})
                for table_id, _entry in new_entries:
                    digs[table_id] = host_rows_digest(
                        *self._read_table_once(table_id)
                    )
            self._persist_version()
        sync_point.hit("after_manifest_commit")
        manifest_ms = (_time.perf_counter() - t_manifest) * 1e3
        if trace is not None:
            trace.add_stage("upload", upload_ms)
            trace.add_stage("manifest_commit", manifest_ms)
        else:
            from risingwave_tpu.epoch_trace import record_stage

            record_stage("upload", upload_ms)
            record_stage("manifest_commit", manifest_ms)
        return n

    def commit_epoch(self, epoch: int, executors: Sequence[object]) -> int:
        """stage + commit_staged in one call (the standalone sync path;
        compacts inline afterwards — the runtime's async lane instead
        defers compaction to its dedicated worker)."""
        # early epoch check so a stale epoch fails before mark flips
        with self._lock:
            if epoch <= int(self.version["max_committed_epoch"]):
                raise ValueError(
                    f"epoch {epoch} <= committed "
                    f"{self.version['max_committed_epoch']}"
                )
        n = self.commit_staged(epoch, self.stage(executors))
        self._maybe_compact(epoch)
        return n

    # -- compaction ------------------------------------------------------
    def tables_needing_compaction(self) -> List[str]:
        with self._lock:
            return [
                t
                for t, entries in self.version["tables"].items()
                if sum(1 for e in entries if e.get("level", 0) == 0)
                >= self.compact_at
            ]

    def compact_once(self, table_id: str, epoch: int) -> bool:
        """Leveled compaction (two-level picker, the write-amplification
        bound of compaction/picker/): merge the table's L0 epoch deltas
        with ONLY the L1 files whose key ranges overlap the L0 span,
        and rewrite that span as non-overlapping block-format L1 files.
        L1 files outside the span are untouched — repeated compactions
        rewrite each key's neighborhood, not the whole table.

        OFF the commit path: the merge runs without the lock; the
        version swap is CAS-style — concurrent commits append L0
        entries which are preserved as the new run's suffix."""
        with self._lock:
            entries = list(self.version["tables"].get(table_id, []))
        l0 = [e for e in entries if e.get("level", 0) == 0]
        l1 = [e for e in entries if e.get("level", 0) == 1]
        if len(l0) < self.compact_at:
            return False
        l0_ssts = [self._materialized(e, cache=False) for e in l0]
        key_order = l0_ssts[-1].meta.key_names

        # the L0 span in the order-key domain — SSTs are key-sorted, so
        # each file's span is exactly its first and last row
        span_lo = span_hi = None
        for s in l0_ssts:
            if s.meta.n_rows == 0:
                continue
            ok = [
                _order_key(np.asarray(s.keys[k])).astype(np.uint64)
                for k in key_order
            ]
            lo = tuple(int(a[0]) for a in ok)
            hi = tuple(int(a[-1]) for a in ok)
            span_lo = lo if span_lo is None else min(span_lo, lo)
            span_hi = hi if span_hi is None else max(span_hi, hi)
        overlapping = [
            e
            for e in l1
            if span_lo is not None
            and not (
                tuple(e["last"]) < span_lo or tuple(e["first"]) > span_hi
            )
        ]
        src = l0 + overlapping
        ssts = l0_ssts + [
            self._materialized(e, cache=False) for e in overlapping
        ]
        keys, values = merge_ssts(ssts, key_order)
        n_rows = len(next(iter(keys.values()))) if keys else 0
        # skip-watermark cleaning: expired keys drop during the merge
        # (iterator/skip_watermark.rs) — tombstone-free state cleaning
        wm = self.table_watermark(table_id)
        if wm is not None and n_rows:
            kname, wval = wm
            if kname in keys:
                keep = np.asarray(keys[kname]) >= wval
                if not keep.all():
                    keys = {k: np.asarray(a)[keep] for k, a in keys.items()}
                    values = {
                        v: np.asarray(a)[keep] for v, a in values.items()
                    }
                    n_rows = int(keep.sum())
        # L1 file epoch = newest SOURCE epoch: stays below any
        # concurrently-committed L0 so newest-wins ordering holds
        src_epoch = max(e["epoch"] for e in src)
        new_entries: List[dict] = []
        new_paths: List[str] = []
        if n_rows:
            from risingwave_tpu.storage.sstable import sort_order

            order = sort_order([keys[k] for k in key_order])
            keys = {k: np.asarray(a)[order] for k, a in keys.items()}
            values = {v: np.asarray(a)[order] for v, a in values.items()}
            okeys = [
                _order_key(keys[k]).astype(np.uint64) for k in key_order
            ]
            for part, at in enumerate(range(0, n_rows, L1_FILE_ROWS)):
                hi_i = min(at + L1_FILE_ROWS, n_rows)
                sl = slice(at, hi_i)
                blob = build_block_sst(
                    table_id,
                    src_epoch,
                    {k: a[sl] for k, a in keys.items()},
                    {v: a[sl] for v, a in values.items()},
                    np.zeros(hi_i - at, bool),
                    key_order,
                )
                path = (
                    f"{self.prefix}/sst/{table_id}/"
                    f"{epoch:020d}.l1.{part:04d}.sst"
                )
                self.store.put(path, blob)
                new_paths.append(path)
                new_entries.append(
                    {
                        "path": path,
                        "epoch": src_epoch,
                        "level": 1,
                        "format": "block",
                        "first": [int(a[at]) for a in okeys],
                        "last": [int(a[hi_i - 1]) for a in okeys],
                        # whole-blob crc for scrub/backup; header crc
                        # for the lazy read path (blocks carry their
                        # own crcs inside the header)
                        "crc": crc32_bytes(blob),
                        "hdr_crc": header_crc(blob),
                    }
                )
        untouched = [e for e in l1 if e not in overlapping]
        merged_l1 = sorted(
            untouched + new_entries, key=lambda e: tuple(e["first"])
        )
        with self._lock:
            cur = self.version["tables"].get(table_id, [])
            if cur[: len(entries)] != entries:
                # someone else rewrote the run (another compactor);
                # abandon ours — the orphan SSTs are unreferenced
                for p in new_paths:
                    self.store.delete(p)
                return False
            # L1 files lead (oldest layer; newest-first reads walk the
            # list reversed), surviving + concurrent L0s follow
            self.version["tables"][table_id] = merged_l1 + cur[
                len(entries):
            ]
            # epoch-pinned reads below this floor would silently see a
            # partial table (the folded layer is excluded): record the
            # newest epoch this compaction folded so readers can raise
            floors = self.version.setdefault("history_floor", {})
            floors[table_id] = max(floors.get(table_id, 0), src_epoch)
            if digest_enabled():
                # skip-watermark cleaning DROPS expired rows during the
                # merge, so the table's row image (and hence its epoch
                # digest) changes at compaction: refresh it in the same
                # manifest write that publishes the folded run
                self.version.setdefault("digests", {})[table_id] = (
                    host_rows_digest(*self._read_table_once(table_id))
                )
            self._persist_version()
        from risingwave_tpu import utils_sync_point as sync_point

        sync_point.hit("before_compaction_gc")
        for e in src:  # GC after the new version is durable
            self.store.delete(e["path"])
            self._sst_cache.pop(e["path"], None)
        return True

    def _maybe_compact(self, epoch: int):
        """Compact every over-long table run (synchronous helper for
        tests and for runtimes without a compaction thread)."""
        for table_id in self.tables_needing_compaction():
            self.compact_once(table_id, epoch)

    # -- recovery --------------------------------------------------------
    def read_table(
        self, table_id: str
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
        return self._read_retry(lambda: self._read_table_once(table_id))

    def _read_table_once(self, table_id: str):
        # full-table restores bypass the SST cache: pinning every
        # restored SST would hold the whole committed store in host RAM
        # (the cache exists for the point-read working set)
        readers = list(
            reversed(self._readers_newest_first(table_id, cache=False))
        )
        if not readers:
            return {}, {}
        ssts = [
            r.materialize() if isinstance(r, BlockSst) else r
            for r in readers
        ]
        return merge_ssts(ssts, ssts[-1].meta.key_names)

    @staticmethod
    def _read_transient(exc: Exception) -> bool:
        # in READ context a missing file IS transient (a compaction's
        # GC deleted it mid-read; the reloaded manifest never references
        # GC'd files) and ValueError is a torn-decode race. NOT
        # KeyError: that is how user errors (bad prefix / range column)
        # surface from the read closures.
        return isinstance(exc, (OSError, ValueError)) and not isinstance(
            exc, EpochFloorError
        )

    def _read_retry(self, fn):
        """Run a read closure that may lazily touch SST bytes (block
        reads happen AFTER the entry snapshot); a concurrent
        compaction's GC can delete a file mid-read, so retry the WHOLE
        closure against a reloaded manifest — bounded by the read
        policy's deadline + backoff (a wedged manifest race can no
        longer spin), with attempts visible in the retry metrics."""

        def _reload(exc, attempt):
            with self._lock:
                self._load()

        return self._read_policy.run(
            fn,
            op="storage.read",
            classify=self._read_transient,
            on_retry=_reload,
        )

    def _open_entry(self, e: dict, cache: bool):
        r = self._sst_cache.get(e["path"])
        if r is None:
            if e.get("format") == "block":
                # header crc verified eagerly; per-block crcs verify
                # lazily as blocks load (BlockSst._load_block)
                r = BlockSst(
                    self.store, e["path"],
                    expected_hdr_crc=e.get("hdr_crc"),
                )
            else:
                blob = self.store.read(e["path"])
                exp = e.get("crc")
                if exp is not None and crc32_bytes(blob) != exp:
                    raise_corruption(
                        self.store, e["path"], "sst-crc", data=blob,
                        expected=exp, actual=crc32_bytes(blob),
                    )
                r = read_sst(blob)
            if cache:
                self._sst_cache[e["path"]] = r
        return r

    def _materialized(self, e: dict, cache: bool = True):
        r = self._open_entry(e, cache)
        return r.materialize() if isinstance(r, BlockSst) else r

    def _readers_newest_first(
        self, table_id: str, cache: bool = True,
        at_epoch: "Optional[int]" = None,
    ):
        # blob reads run OUTSIDE the lock; a compactor — this manager's
        # off-path thread, or another node still draining after a
        # "kill" — may GC an SST between the version snapshot and the
        # read. Retry after RELOADING the manifest: the durable version
        # never references GC'd files (GC runs only after the new
        # manifest persists, compact_once). Bounded by the read
        # policy's attempt budget (shared with _read_retry).
        for attempt in range(self._read_policy.max_attempts):
            with self._lock:
                if attempt:
                    self._load()
                entries = list(self.version["tables"].get(table_id, []))
            if at_epoch is not None:
                # MVCC snapshot pin (StateStore epoch-pinned reads,
                # store.rs read options): ignore SSTs committed after
                # the pinned epoch — L1 files carry their newest SOURCE
                # epoch, so a compaction never hides history newer than
                # its inputs. Below the compaction floor the folded
                # layer would be EXCLUDED and the read silently
                # partial: refuse (the reference pins epochs against
                # compaction via hummock version pinning).
                floor = self.version.get("history_floor", {}).get(
                    table_id, 0
                )
                if at_epoch < floor:
                    raise EpochFloorError(
                        f"epoch {at_epoch} is below {table_id!r}'s "
                        f"compaction floor {floor}: that history has "
                        "been folded"
                    )
                entries = [e for e in entries if e["epoch"] <= at_epoch]
            out = []
            try:
                for e in reversed(entries):
                    out.append(self._open_entry(e, cache))
                return out
            except (KeyError, FileNotFoundError, OSError, ValueError):
                continue
        raise RuntimeError(
            f"SST run for {table_id!r} kept vanishing mid-read "
            "(compaction livelock?)"
        )

    def get_rows(
        self, table_id: str, key_cols: Dict[str, np.ndarray],
        at_epoch: Optional[int] = None,
    ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """MVCC-style point reads at the committed version
        (StateStore::get, store.rs:218): per queried key, newest SST
        containing it wins; tombstones resolve to absent. Blooms prune
        whole SSTs per query batch — no full-table materialization.

        Returns ``(found_mask, value_cols)``; value lanes are only
        meaningful where ``found_mask``. ``at_epoch`` pins an MVCC
        snapshot: the read sees exactly the state committed at that
        epoch (epoch-pinned batch reads, store.rs read options) —
        subject to compaction having not yet folded those epochs."""
        return self._read_retry(
            lambda: self._get_rows_once(table_id, key_cols, at_epoch)
        )

    def _get_rows_once(self, table_id, key_cols, at_epoch=None):
        readers = self._readers_newest_first(table_id, at_epoch=at_epoch)
        n = len(next(iter(key_cols.values()))) if key_cols else 0
        found = np.zeros(n, bool)
        unresolved = np.ones(n, bool)
        values: Dict[str, np.ndarray] = {}
        for sst in readers:
            if not unresolved.any():
                break
            lanes = [np.asarray(key_cols[k]) for k in sst.meta.key_names]
            if isinstance(sst, BlockSst):
                # block-granular: prune by the header's key range (no
                # IO — already resident), then at most one ~block read
                # per query. The bloom is skipped on purpose: for a
                # non-overlapping leveled file its bits outweigh a
                # single block, so range + in-block binary search is
                # strictly cheaper.
                fr, la = sst.key_range()
                if not fr:
                    continue
                qts = [
                    _order_key(np.asarray(l)).astype(np.uint64)
                    for l in lanes
                ]
                in_rng = np.ones(n, bool)
                for qi in range(n):
                    t = tuple(int(a[qi]) for a in qts)
                    in_rng[qi] = fr <= t <= la
                cand = unresolved & in_rng
                if not cand.any():
                    continue
                hit, tombs, vals = sst.point_read(lanes, cand)
                if not hit.any():
                    continue
                live = hit & ~tombs
                for name, col in vals.items():
                    if name not in values:
                        values[name] = np.zeros(
                            (n,) + col.shape[1:], col.dtype
                        )
                    values[name][live] = col[live]
                found |= live
                unresolved &= ~hit
                continue
            cand = unresolved & sst.may_contain(lanes)
            if not cand.any():
                continue
            rows = sst.lookup_rows(lanes, cand)
            hit = cand & (rows >= 0)
            if not hit.any():
                continue
            live = hit & ~sst.tombstone[np.where(hit, rows, 0)]
            for name, col in sst.values.items():
                if name not in values:
                    # 2D bucket lanes (join rv/deg/r_*) read back whole
                    values[name] = np.zeros(
                        (n,) + col.shape[1:], col.dtype
                    )
                values[name][live] = col[rows[live]]
            found |= live
            unresolved &= ~hit  # tombstone = resolved absent
        return found, values

    def scan_prefix(
        self, table_id: str, prefix_cols: Dict[str, object],
        at_epoch: Optional[int] = None,
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
        """Prefix range scan at the committed version (StateStore::iter,
        store.rs:298): touches only rows matching the key-lane prefix in
        each SST — and only the overlapping BLOCKS of leveled files —
        then resolves newest-wins; the read path backfill and lookup
        joins build on. ``at_epoch`` pins the same MVCC snapshot the
        other read paths honor."""
        return self.scan_range(
            table_id, prefix_cols=prefix_cols, at_epoch=at_epoch
        )

    def scan_range(
        self,
        table_id: str,
        prefix_cols: Optional[Dict[str, object]] = None,
        range_col: Optional[str] = None,
        lo: Optional[object] = None,
        hi: Optional[object] = None,
        reverse: bool = False,
        at_epoch: Optional[int] = None,
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
        """Ordered range scan at the committed version (the forward /
        backward UserIterator, src/storage/src/hummock/iterator/):
        equality over a key-lane prefix, optional [lo, hi] bounds
        (inclusive) on the NEXT key lane, rows returned in key order
        (``reverse`` = backward). Leveled (block-format) files read
        only their overlapping blocks; L0 epoch deltas mask in place;
        newest epoch wins per key and tombstones drop."""
        return self._read_retry(
            lambda: self._scan_range_once(
                table_id, prefix_cols, range_col, lo, hi, reverse,
                at_epoch,
            )
        )

    def _scan_range_once(
        self, table_id, prefix_cols, range_col, lo, hi, reverse,
        at_epoch=None,
    ):
        readers = self._readers_newest_first(table_id, at_epoch=at_epoch)
        if not readers:
            return {}, {}
        key_names = readers[0].meta.key_names
        value_names = readers[0].meta.value_names
        prefix_cols = dict(prefix_cols or {})
        for kn in prefix_cols:
            if kn not in key_names:
                raise KeyError(f"{kn!r} is not a key lane of {key_names}")
        if range_col is not None and range_col not in key_names:
            raise KeyError(
                f"range column {range_col!r} is not a key lane"
            )
        # equality filters apply to ANY key-lane subset (the historical
        # scan_prefix contract); BLOCK pruning only uses the longest
        # LEADING run of equality lanes (+ a range on the next lane)
        plen = 0
        while plen < len(key_names) and key_names[plen] in prefix_cols:
            plen += 1

        k_parts: Dict[str, list] = {k: [] for k in key_names}
        v_parts: Dict[str, list] = {v: [] for v in value_names}
        t_parts, e_parts = [], []

        def collect(blk_keys, blk_vals, blk_tomb, epoch):
            m = np.ones(len(blk_tomb), bool)
            for name, v in prefix_cols.items():
                m &= blk_keys[name] == v
            if range_col is not None:
                lane = blk_keys[range_col]
                if lo is not None:
                    m &= lane >= lo
                if hi is not None:
                    m &= lane <= hi
            if not m.any():
                return
            for k in key_names:
                k_parts[k].append(np.asarray(blk_keys[k])[m])
            for v in value_names:
                v_parts[v].append(np.asarray(blk_vals[v])[m])
            t_parts.append(np.asarray(blk_tomb)[m])
            e_parts.append(np.full(int(m.sum()), epoch, np.int64))

        # order-key bounds for block pruning in leveled files
        def bound(extreme) -> Optional[tuple]:
            vals = []
            for kn in key_names:
                if kn in prefix_cols:
                    vals.append(prefix_cols[kn])
                elif kn == range_col and extreme is not None:
                    vals.append(extreme)
                else:
                    break
            return tuple(vals) if vals else None

        for sst in readers:
            if isinstance(sst, BlockSst):
                blo = bhi = None
                if (
                    prefix_cols or lo is not None or hi is not None
                ) and sst.key_dtypes:
                    # lane dtypes ride the header: whole-file pruning
                    # costs no data IO
                    lane_dt = dict(zip(key_names, sst.key_dtypes))
                    lov = bound(lo)
                    hiv = bound(hi)
                    if lov is not None:
                        blo = order_tuple(
                            lov, [lane_dt[k] for k in key_names[: len(lov)]]
                        )
                    if hiv is not None:
                        bhi = order_tuple(
                            hiv, [lane_dt[k] for k in key_names[: len(hiv)]]
                        )
                    elif prefix_cols:
                        pv = tuple(
                            prefix_cols[k] for k in key_names[:plen]
                        )
                        bhi = order_tuple(
                            pv, [lane_dt[k] for k in key_names[:plen]]
                        )
                for blk in sst.scan_blocks(blo, bhi):
                    collect(
                        {k: blk[f"k_{k}"] for k in key_names},
                        {v: blk[f"v_{v}"] for v in value_names},
                        blk["tombstone"],
                        sst.meta.epoch,
                    )
            else:
                collect(
                    sst.keys, sst.values, sst.tombstone, sst.meta.epoch
                )
        if not t_parts:
            return {k: np.zeros(0) for k in key_names}, {}
        keys = {k: np.concatenate(p) for k, p in k_parts.items()}
        vals = {v: np.concatenate(p) for v, p in v_parts.items()}
        keys, vals = newest_wins(
            keys,
            vals,
            np.concatenate(t_parts),
            np.concatenate(e_parts),
            key_names,
        )
        if reverse:
            keys = {k: a[::-1] for k, a in keys.items()}
            vals = {v: a[::-1] for v, a in vals.items()}
        return keys, vals

    def recover(self, executors: Sequence[object]) -> None:
        """Rebuild every Checkpointable executor's device state from
        the last committed version (recovery from max_committed_epoch,
        barrier/recovery.rs:353).

        Corruption-aware: a ``StateCorruption`` raised while reading
        (crc/digest mismatch — the artifact is already quarantined)
        walks the manifest history back to the NEWEST version whose
        checksum chain deep-verifies without referencing the bad
        artifact, adopts it, and retries — recovery lands on the newest
        fully-verifying epoch instead of restoring a wrong byte."""
        bad: set = set()
        for _attempt in range(MANIFEST_KEEP + 1):
            try:
                self._recover_once(executors)
                return
            except StateCorruption as exc:
                if exc.artifact:
                    bad.add(exc.artifact)
                v = self._walk_back(bad_paths=frozenset(bad), deep=True)
                if v is None:
                    raise  # nothing verifies: surface, never guess
                with self._lock:
                    self.version = v
                    self._sst_cache.clear()
                    self._persist_version()  # heal the pointer
        raise RuntimeError(
            "recovery exhausted the manifest history without finding a "
            f"fully-verifying version (known-bad: {sorted(bad)!r})"
        )

    def _recover_once(self, executors: Sequence[object]) -> None:
        for ex in executors:
            if not isinstance(ex, Checkpointable):
                continue
            for table_id in ex.checkpoint_table_ids():
                keys, values = self.read_table(table_id)
                self._verify_table_digest(table_id, keys, values)
                ex.restore_state(table_id, keys, values)

    def _verify_table_digest(self, table_id, keys, values) -> None:
        """Compare the restored row image against the epoch digest the
        manifest captured at commit (RW_STATE_DIGEST): catches a wrong
        byte that still crc-verifies — e.g. corruption that happened
        BEFORE the SST build, or a crc-less legacy entry."""
        if not digest_enabled():
            return
        with self._lock:
            want = self.version.get("digests", {}).get(table_id)
            entries = list(self.version["tables"].get(table_id, []))
        if want is None:
            return
        got = host_rows_digest(keys, values)
        if got != want:
            artifact = entries[-1]["path"] if entries else table_id
            raise_corruption(
                self.store, artifact, "table-digest",
                detail=f"table {table_id!r} row-image digest mismatch",
                expected=want, actual=got,
            )

    # -- scrub -----------------------------------------------------------
    def scrub(self, deep: bool = False) -> List[dict]:
        """On-demand audit of every artifact the current manifest
        references (plus the manifest pointer itself). Returns one row
        per artifact — ``status`` in {ok, corrupt, unverified,
        unavailable} — suitable for the ``rw_integrity`` system table
        and the ``ctl scrub`` CLI. Detection quarantines + records the
        event but NEVER raises: a scrub is reconnaissance, not a fault.
        ``deep`` additionally parses block SSTs and verifies every
        per-block crc (not just the whole-blob one)."""
        with self._lock:
            version = json.loads(json.dumps(self.version))
        rows: List[dict] = []
        mpath = self._manifest_path()
        mrow = {
            "artifact": mpath, "table_id": "", "level": -1,
            "epoch": int(version.get("max_committed_epoch", 0)),
            "status": "ok", "detail": "",
        }
        try:
            decode_manifest(self.store.read(mpath), artifact=mpath)
        except StateCorruption as exc:
            exc.quarantined = quarantine(self.store, mpath)
            note_corruption(exc)
            mrow.update(status="corrupt", detail=str(exc))
        except STORE_UNAVAILABLE as exc:
            mrow.update(status="unavailable", detail=str(exc))
        except OSError as exc:
            mrow.update(status="unavailable", detail=str(exc))
        rows.append(mrow)
        for table_id in sorted(version.get("tables", {})):
            for e in version["tables"][table_id]:
                rows.append(self._scrub_entry(table_id, e, deep))
        return rows

    def _scrub_entry(self, table_id: str, e: dict, deep: bool) -> dict:
        row = {
            "artifact": e["path"], "table_id": table_id,
            "level": int(e.get("level", 0)), "epoch": int(e["epoch"]),
            "status": "ok", "detail": "",
        }
        try:
            blob = self.store.read(e["path"])
        except STORE_UNAVAILABLE as exc:
            row.update(status="unavailable", detail=str(exc))
            return row
        except OSError as exc:
            row.update(status="unavailable", detail=str(exc))
            return row
        problems: List[str] = []
        want = e.get("crc")
        if want is None:
            row["status"] = "unverified"
            row["detail"] = "no checksum recorded (pre-integrity entry)"
        elif crc32_bytes(blob) != want:
            problems.append(
                f"blob crc mismatch expected={want} "
                f"actual={crc32_bytes(blob)}"
            )
        if e.get("format") == "block":
            want_h = e.get("hdr_crc")
            if want_h is not None and header_crc(blob) != want_h:
                problems.append("header crc mismatch")
            if deep:
                problems.extend(verify_block_blob(blob))
        if problems:
            exc = StateCorruption(
                e["path"], "scrub", detail="; ".join(problems),
            )
            exc.quarantined = quarantine(self.store, e["path"], blob)
            note_corruption(exc)
            row.update(status="corrupt", detail="; ".join(problems))
        return row

def verify_sst_entry(store: ObjectStore, e: dict) -> bytes:
    """Read + verify one manifest SST entry, returning the VERIFIED
    bytes. The backup tool's chokepoint (``meta_backup``): a faithfully
    copied corrupt SST makes the backup worthless, so verification and
    the copy read are the same read. Raises StateCorruption (and
    quarantines) on a wrong byte."""
    blob = store.read(e["path"])
    want = e.get("crc")
    if want is not None and crc32_bytes(blob) != want:
        raise_corruption(
            store, e["path"], "sst-crc", data=blob,
            expected=want, actual=crc32_bytes(blob),
        )
    if e.get("format") == "block":
        want_h = e.get("hdr_crc")
        if want_h is not None and header_crc(blob) != want_h:
            raise_corruption(
                store, e["path"], "sst-header-crc", data=blob,
                expected=want_h, actual=header_crc(blob),
            )
        problems = verify_block_blob(blob)
        if problems:
            raise_corruption(
                store, e["path"], "sst-block-crc", data=blob,
                detail="; ".join(problems),
            )
    return blob
