"""StateTable checkpoint layer — Hummock-lite version + commit_epoch.

Reference roles replaced:
- ``StateTable::commit`` staging an epoch's memtable into the shared
  buffer for upload (src/stream/src/common/table/state_table.rs:1140,
  src/storage/src/hummock/event_handler/uploader.rs:548);
- ``HummockManager::commit_epoch`` pinning uploaded SSTs into a new
  HummockVersion (src/meta/src/hummock/manager/commit_epoch.rs:93);
- full-merge compaction (src/storage/src/hummock/compactor/).

TPU re-design: executor state lives in HBM as slot-indexed arrays;
``sdirty``/``stored`` lanes on the device state track what changed
since the last checkpoint. At a checkpoint barrier each Checkpointable
executor stages its delta (device→host pull, compacted to the changed
rows), the manager writes one SST per table, then commits the MANIFEST
atomically — the epoch is durable iff the manifest says so (a crash
between SST puts and manifest write recovers to the previous epoch;
orphan SSTs are ignored and reclaimed by compaction GC).

Recovery: ``recover(executors)`` merge-reads each table's SSTs
(newest-epoch-wins, tombstones drop) and hands the surviving rows to
the executor's ``restore_state`` to rebuild device state.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.storage.object_store import ObjectStore
from risingwave_tpu.storage.sstable import build_sst, merge_ssts, read_sst

MANIFEST = "MANIFEST"
COMPACT_AT = 8  # SSTs per table before a full-merge compaction


@dataclass
class StateDelta:
    """One table's staged epoch delta (host-side, compacted).

    Staging flips the executor's device sdirty/stored marks EAGERLY —
    slot indices shift on rehash, so a deferred flip would hit wrong
    slots. The durability contract is therefore the reference's
    (barrier/mod.rs:676): if a commit FAILS, in-memory marks are ahead
    of storage and the process MUST recover() from the last durable
    manifest — never retry the commit against live state.
    """

    table_id: str
    key_cols: Dict[str, np.ndarray]
    value_cols: Dict[str, np.ndarray]
    tombstone: np.ndarray
    key_order: Tuple[str, ...]


def stage_marks(
    sdirty: np.ndarray, alive: np.ndarray, stored: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The shared upsert/tombstone classification every Checkpointable
    executor uses: returns (upsert_mask, tombstone_mask, sel_indices)."""
    upsert = sdirty & alive
    tomb = sdirty & stored & ~alive
    return upsert, tomb, np.flatnonzero(upsert | tomb)


def grow_pow2(n: int, cap: int, grow_at: float = 0.5) -> int:
    """Smallest power-of-two capacity >= cap holding n under grow_at."""
    while n > cap * grow_at:
        cap *= 2
    return cap


def pull_rows(device_lanes: Dict[str, object], sel: np.ndarray) -> Dict[str, np.ndarray]:
    """Device->host transfer of SELECTED rows only (checkpoint staging
    must be O(changed rows), not O(capacity)). ``sel`` is padded to a
    power-of-two bucket so jit caches one gather program per bucket
    size instead of recompiling per distinct count."""
    n = len(sel)
    if n == 0:
        return {k: np.asarray(a)[:0] for k, a in device_lanes.items()}
    pad = 1 << (n - 1).bit_length()
    idx = np.zeros(pad, np.int32)
    idx[:n] = sel
    gathered = _gather(dict(device_lanes), jnp.asarray(idx))
    return {k: np.asarray(a)[:n] for k, a in gathered.items()}


@jax.jit
def _gather(lanes, idx):
    return jax.tree.map(lambda a: a[idx], lanes)


class Checkpointable:
    """Executor mixin: stateful executors that persist through the
    checkpoint manager implement these three members."""

    table_id: str = ""

    def checkpoint_table_ids(self) -> List[str]:
        return [self.table_id]

    def checkpoint_delta(self) -> List[StateDelta]:
        """Stage rows changed since the last checkpoint and CLEAR the
        device-side sdirty marks (update stored marks)."""
        raise NotImplementedError

    def restore_state(
        self, table_id: str, key_cols: Dict[str, np.ndarray],
        value_cols: Dict[str, np.ndarray],
    ) -> None:
        raise NotImplementedError


class CheckpointManager:
    """Version authority + per-epoch committer (meta-lite)."""

    def __init__(
        self,
        store: ObjectStore,
        prefix: str = "hummock",
        compact_at: int = COMPACT_AT,
    ):
        self.store = store
        self.prefix = prefix
        self.compact_at = compact_at
        self.version = {"max_committed_epoch": 0, "tables": {}}
        self._load()

    # -- version ---------------------------------------------------------
    def _manifest_path(self) -> str:
        return f"{self.prefix}/{MANIFEST}"

    def _load(self):
        if self.store.exists(self._manifest_path()):
            self.version = json.loads(self.store.read(self._manifest_path()))

    def _persist_version(self):
        self.store.put(
            self._manifest_path(), json.dumps(self.version).encode()
        )

    @property
    def max_committed_epoch(self) -> int:
        return int(self.version["max_committed_epoch"])

    # -- commit path -----------------------------------------------------
    def commit_epoch(self, epoch: int, executors: Sequence[object]) -> int:
        """Stage every Checkpointable executor's delta, upload SSTs,
        then commit the manifest. Staging flips device marks eagerly
        (see StateDelta), so if this raises, the caller must recover()
        from the last durable manifest before continuing — matching the
        reference's failed-barrier -> global recovery contract.
        Returns the number of SSTs written."""
        if epoch <= self.max_committed_epoch:
            raise ValueError(
                f"epoch {epoch} <= committed {self.max_committed_epoch}"
            )
        staged: List[StateDelta] = []
        seen_ids = set()
        for ex in executors:
            if not isinstance(ex, Checkpointable):
                continue
            for delta in ex.checkpoint_delta():
                if delta.table_id in seen_ids:
                    raise ValueError(
                        f"duplicate table_id {delta.table_id!r} in one "
                        "commit — give each executor a unique table_id"
                    )
                seen_ids.add(delta.table_id)
                staged.append(delta)

        n = 0
        tables = self.version["tables"]
        for delta in staged:
            if len(delta.tombstone) == 0:
                continue
            blob = build_sst(
                delta.table_id,
                epoch,
                delta.key_cols,
                delta.value_cols,
                delta.tombstone,
                delta.key_order,
            )
            path = f"{self.prefix}/sst/{delta.table_id}/{epoch:020d}.sst"
            self.store.put(path, blob)
            tables.setdefault(delta.table_id, []).append(
                {"path": path, "epoch": epoch}
            )
            n += 1
        self.version["max_committed_epoch"] = epoch
        self._persist_version()
        self._maybe_compact(epoch)
        return n

    # -- compaction ------------------------------------------------------
    def _maybe_compact(self, epoch: int):
        """Full-merge compaction per table once its L0 run gets long
        (fast_compactor_runner analogue, synchronous v0): merge every
        SST into one at the current epoch; tombstones drop entirely
        (nothing older survives a full merge)."""
        for table_id, entries in self.version["tables"].items():
            if len(entries) < self.compact_at:
                continue
            ssts = [read_sst(self.store.read(e["path"])) for e in entries]
            key_order = ssts[-1].meta.key_names
            keys, values = merge_ssts(ssts, key_order)
            n_rows = len(next(iter(keys.values()))) if keys else 0
            blob = build_sst(
                table_id,
                epoch,
                keys,
                values,
                np.zeros(n_rows, bool),
                key_order,
            )
            path = f"{self.prefix}/sst/{table_id}/{epoch:020d}.compact.sst"
            self.store.put(path, blob)
            old = list(entries)
            self.version["tables"][table_id] = [
                {"path": path, "epoch": epoch}
            ]
            self._persist_version()
            for e in old:  # GC after the new version is durable
                self.store.delete(e["path"])

    # -- recovery --------------------------------------------------------
    def read_table(
        self, table_id: str
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
        entries = self.version["tables"].get(table_id, [])
        ssts = [read_sst(self.store.read(e["path"])) for e in entries]
        if not ssts:
            return {}, {}
        return merge_ssts(ssts, ssts[-1].meta.key_names)

    def recover(self, executors: Sequence[object]) -> None:
        """Rebuild every Checkpointable executor's device state from
        the last committed version (recovery from max_committed_epoch,
        barrier/recovery.rs:353)."""
        for ex in executors:
            if not isinstance(ex, Checkpointable):
                continue
            for table_id in ex.checkpoint_table_ids():
                keys, values = self.read_table(table_id)
                ex.restore_state(table_id, keys, values)
