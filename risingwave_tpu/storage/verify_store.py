"""Differential read verification — the VerifyStateStore analogue.

Reference: src/storage/src/store_impl.rs VerifyStateStore (debug-mode
dispatch wrapper running every operation against two stores and
asserting agreement). Here the two independent implementations are the
OPTIMIZED read paths (bloom/block-pruned point reads, block-pruned
range scans) vs the ORACLE path (full materialization + newest-wins
merge): wrap a CheckpointManager and every get_rows/scan_range runs
both, raising on any divergence. Used by the chaos/e2e tiers to catch
pruning bugs (a wrong bloom bit or block bound silently drops rows —
exactly the class of bug assertions in the hot path can't see).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from risingwave_tpu.storage.sstable import merge_ssts
from risingwave_tpu.storage.block_sst import BlockSst


class VerifyReadStore:
    """Wraps a CheckpointManager; reads run BOTH paths and must agree."""

    def __init__(self, mgr):
        self.mgr = mgr
        self.verified_reads = 0
        # oracle results cached per (table, version paths, pin): the
        # differential tier must not re-download the whole table per
        # read; a version change produces a different key
        self._oracle_cache: Dict[tuple, tuple] = {}

    def __getattr__(self, name):  # everything else passes through
        return getattr(self.mgr, name)

    # -- oracle path -----------------------------------------------------
    def _oracle_rows(self, table_id: str, at_epoch: Optional[int] = None):
        with self.mgr._lock:
            paths = tuple(
                e["path"]
                for e in self.mgr.version["tables"].get(table_id, ())
            )
        ck = (table_id, paths, at_epoch)
        hit = self._oracle_cache.get(ck)
        if hit is not None:
            return hit
        readers = list(
            reversed(
                self.mgr._readers_newest_first(
                    table_id, cache=False, at_epoch=at_epoch
                )
            )
        )
        if not readers:
            out = ({}, {}, ())
        else:
            ssts = [
                r.materialize() if isinstance(r, BlockSst) else r
                for r in readers
            ]
            keys, vals = merge_ssts(ssts, ssts[-1].meta.key_names)
            out = (keys, vals, ssts[-1].meta.key_names)
        if len(self._oracle_cache) > 8:
            self._oracle_cache.pop(next(iter(self._oracle_cache)))
        self._oracle_cache[ck] = out
        return out

    # -- verified reads --------------------------------------------------
    def get_rows(self, table_id, key_cols, at_epoch=None):
        found, vals = self.mgr.get_rows(
            table_id, key_cols, at_epoch=at_epoch
        )
        okeys, ovals, key_names = self._oracle_rows(table_id, at_epoch)
        n = len(next(iter(key_cols.values()))) if key_cols else 0
        table = {}
        if okeys:
            rows = list(
                zip(*(np.asarray(okeys[k]).tolist() for k in key_names))
            )
            for i, kt in enumerate(rows):
                table[kt] = i
        for i in range(n):
            kt = tuple(
                np.asarray(key_cols[k])[i].item() for k in key_names
            )
            want = kt in table
            if bool(found[i]) != want:
                raise AssertionError(
                    f"differential store: key {kt} found={bool(found[i])}"
                    f" but oracle says {want} ({table_id})"
                )
            if want:
                j = table[kt]
                for vn, lane in vals.items():
                    ov = np.asarray(ovals[vn])[j]
                    if not np.array_equal(np.asarray(lane[i]), ov):
                        raise AssertionError(
                            f"differential store: {table_id} key {kt} "
                            f"lane {vn}: fast={lane[i]} oracle={ov}"
                        )
        self.verified_reads += 1
        return found, vals

    def scan_range(
        self, table_id, prefix_cols=None, range_col=None, lo=None,
        hi=None, reverse=False, at_epoch=None,
    ):
        keys, vals = self.mgr.scan_range(
            table_id, prefix_cols, range_col, lo, hi, reverse, at_epoch
        )
        okeys, ovals, key_names = self._oracle_rows(table_id, at_epoch)

        def rowset(ks, vs):
            if not ks:
                return {}
            n = len(next(iter(ks.values())))
            vns = sorted(vs)
            return {
                tuple(np.asarray(ks[k])[i].item() for k in key_names): tuple(
                    np.asarray(np.asarray(vs[v])[i]).tolist()
                    if np.asarray(vs[v])[i].ndim
                    else np.asarray(vs[v])[i].item()
                    for v in vns
                )
                for i in range(n)
            }

        want = {}
        if okeys:
            mask = np.ones(len(next(iter(okeys.values()))), bool)
            for kn, v in (prefix_cols or {}).items():
                mask &= np.asarray(okeys[kn]) == v
            if range_col is not None:
                lane = np.asarray(okeys[range_col])
                if lo is not None:
                    mask &= lane >= lo
                if hi is not None:
                    mask &= lane <= hi
            sel = np.flatnonzero(mask)
            fk = {k: np.asarray(a)[sel] for k, a in okeys.items()}
            fv = {k: np.asarray(a)[sel] for k, a in ovals.items()}
            want = rowset(fk, fv)
        got = rowset(keys, vals)
        if got != want:
            raise AssertionError(
                f"differential store: scan of {table_id} diverges — "
                f"{len(got)} rows vs oracle {len(want)} (or values "
                "differ)"
            )
        self.verified_reads += 1
        return keys, vals

    def scan_prefix(self, table_id, prefix_cols, at_epoch=None):
        return self.scan_range(
            table_id, prefix_cols=prefix_cols, at_epoch=at_epoch
        )
