"""Storage — object store, SSTs, checkpoint/recovery (Hummock-lite).

Reference: src/object_store/, src/storage/ (Hummock). See module docs.
"""

from risingwave_tpu.storage.object_store import (
    LocalFsObjectStore,
    MemObjectStore,
    ObjectStore,
)
from risingwave_tpu.storage.state_table import (
    Checkpointable,
    CheckpointManager,
    StateDelta,
)

__all__ = [
    "ObjectStore",
    "MemObjectStore",
    "LocalFsObjectStore",
    "Checkpointable",
    "CheckpointManager",
    "StateDelta",
]
