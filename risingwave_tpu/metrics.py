"""Metrics kernel — counters/histograms with labels.

Reference: src/common/metrics/ (prometheus registry + label-guarded
metrics, guarded_metrics.rs) and the per-executor ``StreamingMetrics``
struct (src/stream/src/executor/monitor/streaming_stats.rs:44).

v0: an in-process registry with the prometheus text exposition format
(``render()``), no HTTP endpoint yet. Counters are plain floats on the
host — metric updates must NEVER force a device sync, so executors
record shapes/capacities and host-side timings only.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Tuple

import numpy as np

_Labels = Tuple[Tuple[str, str], ...]


def _labels(kv: Dict[str, str]) -> _Labels:
    return tuple(sorted(kv.items()))


class Counter:
    def __init__(self, registry, name: str):
        self.name = name
        self._values: Dict[_Labels, float] = defaultdict(float)
        self._lock = registry._lock

    def inc(self, value: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._values[_labels(labels)] += value

    def get(self, **labels: str) -> float:
        return self._values.get(_labels(labels), 0.0)


class Histogram:
    def __init__(self, registry, name: str):
        self.name = name
        self._obs: Dict[_Labels, List[float]] = defaultdict(list)
        self._lock = registry._lock

    def observe(self, value: float, **labels: str) -> None:
        with self._lock:
            self._obs[_labels(labels)].append(value)

    def percentile(self, q: float, **labels: str) -> float:
        obs = self._obs.get(_labels(labels))
        return float(np.percentile(obs, q)) if obs else 0.0

    def count(self, **labels: str) -> int:
        return len(self._obs.get(_labels(labels), ()))


class Gauge:
    def __init__(self, registry, name: str):
        self.name = name
        self._values: Dict[_Labels, float] = defaultdict(float)
        self._lock = registry._lock

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_labels(labels)] = value

    def get(self, **labels: str) -> float:
        return self._values.get(_labels(labels), 0.0)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, Counter] = {}
        self.histograms: Dict[str, Histogram] = {}
        self.gauges: Dict[str, Gauge] = {}
        self._server = None

    def counter(self, name: str) -> Counter:
        if name not in self.counters:
            self.counters[name] = Counter(self, name)
        return self.counters[name]

    def histogram(self, name: str) -> Histogram:
        if name not in self.histograms:
            self.histograms[name] = Histogram(self, name)
        return self.histograms[name]

    def gauge(self, name: str) -> Gauge:
        if name not in self.gauges:
            self.gauges[name] = Gauge(self, name)
        return self.gauges[name]

    def render(self) -> str:
        """Prometheus text exposition."""
        lines = []
        for name, c in sorted(self.counters.items()):
            lines.append(f"# TYPE {name} counter")
            for labels, v in sorted(c._values.items()):
                lbl = ",".join(f'{k}="{val}"' for k, val in labels)
                lines.append(f"{name}{{{lbl}}} {v}" if lbl else f"{name} {v}")
        for name, g in sorted(self.gauges.items()):
            lines.append(f"# TYPE {name} gauge")
            for labels, v in sorted(g._values.items()):
                lbl = ",".join(f'{k}="{val}"' for k, val in labels)
                lines.append(f"{name}{{{lbl}}} {v}" if lbl else f"{name} {v}")
        for name, h in sorted(self.histograms.items()):
            lines.append(f"# TYPE {name} summary")
            for labels, obs in sorted(h._obs.items()):
                lbl = ",".join(f'{k}="{val}"' for k, val in labels)
                base = f"{name}{{{lbl}}}" if lbl else name
                for q in (0.5, 0.9, 0.99):
                    ql = (
                        f'{{{lbl},quantile="{q}"}}'
                        if lbl
                        else f'{{quantile="{q}"}}'
                    )
                    lines.append(
                        f"{name}{ql} {float(np.percentile(obs, q * 100))}"
                    )
                lines.append(f"{base}_count {len(obs)}")
                lines.append(f"{base}_sum {sum(obs)}")
        return "\n".join(lines) + "\n"

    def render_dashboard(self) -> str:
        """One self-contained HTML ops page (the reference ships a
        React dashboard from the meta node; this collapses the same
        surfaces — fragments, state sizes, barrier health, recovery
        counters — into a static render per request)."""
        from html import escape

        from risingwave_tpu import utils_heap

        rows = []
        rt = utils_heap._runtime_ref() if utils_heap._runtime_ref else None
        frag_rows = ""
        if rt is not None:
            for name in sorted(getattr(rt, "fragments", {})):
                subs = [
                    f"{d}({s})"
                    for d, s in getattr(rt, "_subs", {}).get(name, ())
                ]
                frag_rows += (
                    f"<tr><td>{escape(name)}</td>"
                    f"<td>{escape(', '.join(subs) or '-')}</td></tr>"
                )
            stats = [
                ("epoch", getattr(rt, "_epoch", 0)),
                (
                    "committed epoch",
                    rt.mgr.max_committed_epoch if rt.mgr else 0,
                ),
                ("auto recoveries", getattr(rt, "auto_recoveries", 0)),
                ("p99 barrier ms", round(rt.p99_barrier_ms(), 2)),
                (
                    "p99 checkpoint sync ms",
                    round(rt.p99_checkpoint_sync_ms(), 2),
                ),
            ]
            rows += [
                f"<tr><td>{escape(str(k))}</td><td>{v}</td></tr>"
                for k, v in stats
            ]
        state_rows = "".join(
            f"<tr><td>{escape(d['executor'])}</td>"
            f"<td>{escape(str(d['table_id']))}</td>"
            f"<td style='text-align:right'>{d['bytes']:,}</td></tr>"
            for d in utils_heap.device_state()[:40]
        )
        return f"""<!doctype html><html><head><title>risingwave_tpu</title>
<style>body{{font-family:monospace;margin:2em}}table{{border-collapse:collapse;margin:1em 0}}
td,th{{border:1px solid #999;padding:2px 8px}}h2{{margin-top:1.5em}}</style></head><body>
<h1>risingwave_tpu dashboard</h1>
<h2>runtime</h2><table>{''.join(rows) or '<tr><td>no runtime attached</td></tr>'}</table>
<h2>fragments &rarr; subscribers</h2><table>{frag_rows or '<tr><td>none</td></tr>'}</table>
<h2>device state (top 40)</h2><table><tr><th>executor</th><th>table</th><th>bytes</th></tr>{state_rows}</table>
<p><a href="/metrics">/metrics</a> &middot; <a href="/heap">/heap</a></p>
</body></html>"""

    def serve(self, port: int = 0) -> int:
        """Expose ``/metrics`` over HTTP (the prometheus scrape surface
        the reference serves from each node). Returns the bound port."""
        import http.server

        registry = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                path = self.path.rstrip("/")
                if path == "/heap":
                    # heap profile: device-state accounting + host
                    # tracemalloc top (utils_heap; jeprof analogue)
                    from risingwave_tpu import utils_heap

                    body = utils_heap.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path in ("", "/dashboard"):
                    # the ops dashboard (reference: the meta dashboard
                    # UI, collapsed to one self-contained page)
                    body = registry.render_dashboard().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/html; charset=utf-8"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path != "/metrics":
                    self.send_response(404)
                    self.end_headers()
                    return
                body = registry.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler
        )
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return self._server.server_address[1]

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


# the process-default registry (reference: GLOBAL_METRICS_REGISTRY)
REGISTRY = MetricsRegistry()
