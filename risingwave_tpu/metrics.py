"""Metrics kernel — counters/histograms with labels.

Reference: src/common/metrics/ (prometheus registry + label-guarded
metrics, guarded_metrics.rs) and the per-executor ``StreamingMetrics``
struct (src/stream/src/executor/monitor/streaming_stats.rs:44).

v0: an in-process registry with the prometheus text exposition format
(``render()``), no HTTP endpoint yet. Counters are plain floats on the
host — metric updates must NEVER force a device sync, so executors
record shapes/capacities and host-side timings only.
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from typing import Dict, Tuple

import numpy as np

_Labels = Tuple[Tuple[str, str], ...]


def _labels(kv: Dict[str, str]) -> _Labels:
    return tuple(sorted(kv.items()))


class Counter:
    def __init__(self, registry, name: str):
        self.name = name
        self._values: Dict[_Labels, float] = defaultdict(float)
        self._lock = registry._lock

    def inc(self, value: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._values[_labels(labels)] += value

    def get(self, **labels: str) -> float:
        return self._values.get(_labels(labels), 0.0)

    def total(self) -> float:
        """Sum across every label set, snapshotted under the registry
        lock (safe against a hot-path label insertion mid-iteration) —
        the public surface forensic readers use instead of touching
        ``_values`` directly."""
        with self._lock:
            return sum(self._values.values())


class Histogram:
    """Windowed histogram: quantiles come from a bounded per-label-set
    reservoir (deque of the most recent ``window`` observations) while
    ``_count``/``_sum`` stay exact monotonic totals — a long-running
    node's memory no longer grows with every observation (previously an
    unbounded list per label set)."""

    DEFAULT_WINDOW = 4096

    def __init__(self, registry, name: str, window: int = None):
        self.name = name
        self.window = window or self.DEFAULT_WINDOW
        self._obs: Dict[_Labels, deque] = {}
        self._count: Dict[_Labels, int] = defaultdict(int)
        self._sum: Dict[_Labels, float] = defaultdict(float)
        self._lock = registry._lock

    def observe(self, value: float, **labels: str) -> None:
        key = _labels(labels)
        with self._lock:
            dq = self._obs.get(key)
            if dq is None:
                dq = self._obs[key] = deque(maxlen=self.window)
            dq.append(value)
            self._count[key] += 1
            self._sum[key] += value

    def percentile(self, q: float, **labels: str) -> float:
        obs = self._obs.get(_labels(labels))
        return float(np.percentile(obs, q)) if obs else 0.0

    def count(self, **labels: str) -> int:
        return self._count.get(_labels(labels), 0)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """{label-string: {p50, p99, count, sum}} across every label
        set — the bench's per-stage breakdown surface."""
        with self._lock:
            keys = list(self._obs)
        out = {}
        for key in keys:
            obs = list(self._obs.get(key, ()))
            if not obs:
                continue
            lbl = ",".join(f"{k}={v}" for k, v in key) or "-"
            out[lbl] = {
                "p50": round(float(np.percentile(obs, 50)), 3),
                "p99": round(float(np.percentile(obs, 99)), 3),
                "count": self._count.get(key, len(obs)),
                "sum": round(self._sum.get(key, 0.0), 3),
            }
        return out


class Gauge:
    def __init__(self, registry, name: str):
        self.name = name
        self._values: Dict[_Labels, float] = defaultdict(float)
        self._lock = registry._lock

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_labels(labels)] = value

    def get(self, **labels: str) -> float:
        return self._values.get(_labels(labels), 0.0)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, Counter] = {}
        self.histograms: Dict[str, Histogram] = {}
        self.gauges: Dict[str, Gauge] = {}
        self._server = None

    def counter(self, name: str) -> Counter:
        if name not in self.counters:
            self.counters[name] = Counter(self, name)
        return self.counters[name]

    def histogram(self, name: str) -> Histogram:
        if name not in self.histograms:
            self.histograms[name] = Histogram(self, name)
        return self.histograms[name]

    def gauge(self, name: str) -> Gauge:
        if name not in self.gauges:
            self.gauges[name] = Gauge(self, name)
        return self.gauges[name]

    def render(self) -> str:
        """Prometheus text exposition."""
        lines = []
        for name, c in sorted(self.counters.items()):
            lines.append(f"# TYPE {name} counter")
            for labels, v in sorted(c._values.items()):
                lbl = ",".join(f'{k}="{val}"' for k, val in labels)
                lines.append(f"{name}{{{lbl}}} {v}" if lbl else f"{name} {v}")
        for name, g in sorted(self.gauges.items()):
            lines.append(f"# TYPE {name} gauge")
            for labels, v in sorted(g._values.items()):
                lbl = ",".join(f'{k}="{val}"' for k, val in labels)
                lines.append(f"{name}{{{lbl}}} {v}" if lbl else f"{name} {v}")
        for name, h in sorted(self.histograms.items()):
            lines.append(f"# TYPE {name} summary")
            for labels, obs in sorted(h._obs.items()):
                lbl = ",".join(f'{k}="{val}"' for k, val in labels)
                base = f"{name}{{{lbl}}}" if lbl else name
                win = list(obs)  # quantiles over the bounded window
                for q in (0.5, 0.9, 0.99):
                    ql = (
                        f'{{{lbl},quantile="{q}"}}'
                        if lbl
                        else f'{{quantile="{q}"}}'
                    )
                    lines.append(
                        f"{name}{ql} {float(np.percentile(win, q * 100))}"
                    )
                # count/sum are exact totals (monotonic), not windowed
                lines.append(f"{base}_count {h._count.get(labels, len(win))}")
                lines.append(f"{base}_sum {h._sum.get(labels, sum(win))}")
        return "\n".join(lines) + "\n"

    # the scrape-surface name (ISSUE 16 satellite): freshness and
    # backpressure gauges read as plain prometheus text without custom
    # JSON parsing — same exposition render() always produced
    render_prometheus = render

    def render_dashboard(self) -> str:
        """One self-contained HTML ops page (the reference ships a
        React dashboard from the meta node; this collapses the same
        surfaces — fragments, state sizes, barrier health, recovery
        counters — into a static render per request)."""
        from html import escape

        from risingwave_tpu import utils_heap

        rows = []
        rt = utils_heap._runtime_ref() if utils_heap._runtime_ref else None
        frag_rows = ""
        if rt is not None:
            for name in sorted(getattr(rt, "fragments", {})):
                subs = [
                    f"{d}({s})"
                    for d, s in getattr(rt, "_subs", {}).get(name, ())
                ]
                frag_rows += (
                    f"<tr><td>{escape(name)}</td>"
                    f"<td>{escape(', '.join(subs) or '-')}</td></tr>"
                )
            stats = [
                ("epoch", getattr(rt, "_epoch", 0)),
                (
                    "committed epoch",
                    rt.mgr.max_committed_epoch if rt.mgr else 0,
                ),
                ("auto recoveries", getattr(rt, "auto_recoveries", 0)),
                (
                    "partial recoveries",
                    getattr(rt, "partial_recoveries", 0),
                ),
                ("p99 barrier ms", round(rt.p99_barrier_ms(), 2)),
                (
                    "p99 checkpoint sync ms",
                    round(rt.p99_checkpoint_sync_ms(), 2),
                ),
            ]
            rows += [
                f"<tr><td>{escape(str(k))}</td><td>{v}</td></tr>"
                for k, v in stats
            ]
        state_rows = "".join(
            f"<tr><td>{escape(d['executor'])}</td>"
            f"<td>{escape(str(d['table_id']))}</td>"
            f"<td style='text-align:right'>{d['bytes']:,}</td></tr>"
            for d in utils_heap.device_state()[:40]
        )
        # meta event log tail (reference: the dashboard's event log view)
        from risingwave_tpu.event_log import EVENT_LOG

        event_rows = "".join(
            f"<tr><td>{e['seq']}</td><td>{escape(e['kind'])}</td>"
            f"<td>{escape(', '.join(f'{k}={v}' for k, v in e.items() if k not in ('seq', 'ts', 'kind')))}</td></tr>"
            for e in EVENT_LOG.events(limit=25)
        )
        # per-stage barrier attribution (EpochTrace -> barrier_stage_ms)
        stage_rows = ""
        h = self.histograms.get("barrier_stage_ms")
        if h is not None:
            stage_rows = "".join(
                f"<tr><td>{escape(lbl)}</td><td>{s['p50']}</td>"
                f"<td>{s['p99']}</td><td>{s['count']}</td></tr>"
                for lbl, s in sorted(h.summary().items())
            )
        # dispatch-wall profile (profiler.py): the ranked per-executor
        # cost table, when the profiler has been armed this process
        prof_rows = ""
        if "executor_ms" in self.histograms:
            try:
                from risingwave_tpu.profiler import PROFILER

                prof_rows = "".join(
                    f"<tr><td>{escape(str(d.get('executor', '-')))}</td>"
                    f"<td>{d.get('host_ms', 0.0)}</td>"
                    f"<td>{d.get('device_wait_ms', 0.0)}</td>"
                    f"<td>{d.get('dispatches', 0.0):g}</td></tr>"
                    for d in PROFILER.top_executors(10)
                )
            except Exception:
                prof_rows = ""
        # black box + device sentinel (blackbox.py): the device-health
        # classification and flight-recorder state — the first look
        # when a barrier stalls or the TPU tunnel goes quiet
        bb_rows = ""
        try:
            from risingwave_tpu.blackbox import RECORDER, SENTINEL

            sen = SENTINEL.snapshot()
            rec = RECORDER.snapshot()
            for k, v in (
                ("device state", sen["state"]),
                (
                    "last heartbeat ms",
                    sen["last_latency_ms"]
                    and round(sen["last_latency_ms"], 1),
                ),
                ("heartbeats", sen["beats"]),
                ("wedges", sen["wedges"]),
                ("sentinel running", sen["running"]),
                ("recorder records", rec["records"]),
                ("recorder segment", rec["segment"] or "-"),
            ):
                bb_rows += (
                    f"<tr><td>{escape(str(k))}</td>"
                    f"<td>{escape(str(v))}</td></tr>"
                )
        except Exception:
            bb_rows = ""
        # device roofline + fused telemetry (deviceprof.py): what the
        # compiled programs MODEL (bytes/flops/compile cost per bucket)
        # and what the telemetry lanes MEASURED last barrier — the
        # inside-the-fused-program view PR 10 took away from the
        # per-executor tables above
        dp_rows = tel_rows = ""
        try:
            from risingwave_tpu.deviceprof import DEVICEPROF

            # snapshot WITHOUT flushing: a dashboard page load must
            # never run deferred AOT compiles (seconds on CPU, tens of
            # seconds over a TPU tunnel, possibly mid-measurement)
            rep = DEVICEPROF.report(flush=False)
            for key, p in sorted(rep["programs"].items()):
                if "error" in p:
                    continue
                dp_rows += (
                    f"<tr><td>{escape(key)}</td>"
                    f"<td>{p['compile_ms']}</td>"
                    f"<td style='text-align:right'>{p['bytes_accessed']:,}</td>"
                    f"<td style='text-align:right'>{p['flops']:,.0f}</td>"
                    f"<td style='text-align:right'>{p['temp_bytes']:,}</td></tr>"
                )
            for frag, t in sorted(rep["telemetry"].items()):
                tel_rows += (
                    f"<tr><td>{escape(frag)}</td>"
                    f"<td>{t.get('rows_in', 0)}</td>"
                    f"<td>{t.get('dirty_groups', 0)}</td>"
                    f"<td>{t.get('mv_rows', 0)}</td>"
                    f"<td>{t.get('lane_fill_frac', 0.0)}</td>"
                    f"<td>{t.get('padding_bytes_frac', 0.0)}</td></tr>"
                )
        except Exception:
            dp_rows = tel_rows = ""
        # resilience health: retry pressure + breaker states + degraded
        # mode (resilience.py) — the operator's first look when the
        # store flakes
        res_rows = ""
        for cname in (
            "retries_total",
            "retry_giveups_total",
            "retry_success_after_retry_total",
            "store_fast_fails_total",
            "breaker_transitions_total",
            "degraded_entries_total",
            "degraded_epochs_spilled_total",
            "degraded_epochs_replayed_total",
            "actor_failures_total",
            "partial_recoveries_total",
            "partial_recovery_deferrals_total",
            "replay_buffer_overflows_total",
        ):
            c = self.counters.get(cname)
            if c is None:
                continue
            for labels, v in sorted(c._values.items()):
                lbl = ",".join(f"{k}={val}" for k, val in labels) or "-"
                res_rows += (
                    f"<tr><td>{escape(cname)}</td>"
                    f"<td>{escape(lbl)}</td><td>{v:g}</td></tr>"
                )
        br = self.gauges.get("breaker_state")
        if br is not None:
            names = {0.0: "closed", 1.0: "half_open", 2.0: "open"}
            for labels, v in sorted(br._values.items()):
                lbl = ",".join(f"{k}={val}" for k, val in labels) or "-"
                res_rows += (
                    f"<tr><td>breaker_state</td><td>{escape(lbl)}</td>"
                    f"<td>{escape(names.get(v, str(v)))}</td></tr>"
                )
        # per-MV freshness (freshness.py): the latest commit->visible /
        # source->visible / event-time-lag per MV — the SLO the BASELINE
        # north star is written in
        fresh_rows = ""
        try:
            from risingwave_tpu.freshness import FRESHNESS

            def _f(v):
                return "-" if v is None else f"{v:.1f}"

            fresh_rows = "".join(
                f"<tr><td>{escape(r['mv'])}</td><td>{r['epoch']}</td>"
                f"<td>{_f(r['commit_to_visible_ms'])}</td>"
                f"<td>{_f(r['source_to_visible_ms'])}</td>"
                f"<td>{_f(r['event_time_lag_ms'])}</td>"
                f"<td>{r['barriers']}</td></tr>"
                for r in FRESHNESS.snapshot()
            )
        except Exception:
            fresh_rows = ""
        # memory & overload (runtime/memory_governor.py): the device-
        # state ledger vs budget, the overload ladder's rung and the
        # per-fragment admission credits — the operator's first look
        # when sources start lagging on purpose
        mem_rows = ov_rows = ""
        try:
            gov = getattr(rt, "memory_governor", None) if rt else None
            if gov is not None and gov.enabled:
                snap = gov.snapshot()
                lad, adm = snap["ladder"], snap["admission"]
                for k, v in (
                    ("overload state", lad["state"]),
                    ("pressure score", lad["score"]),
                    ("ladder flaps", lad["flaps"]),
                    ("ledger bytes", f"{snap['ledger_bytes']:,}"),
                    (
                        "budget bytes",
                        f"{snap['budget_bytes']:,}"
                        if snap["budget_bytes"] is not None
                        else "-",
                    ),
                    (
                        "headroom bytes",
                        f"{snap['headroom_bytes']:,}"
                        if snap["headroom_bytes"] is not None
                        else "-",
                    ),
                    ("modeled bytes", f"{snap['modeled_bytes']:,}"),
                    ("sampled bytes", snap["sampled_bytes"] or "-"),
                    ("grow vetoes", snap["vetoes"]),
                    ("spills", snap["spills"]),
                    ("parked polls", adm["parked_polls"]),
                    ("governor host ms", snap["host_ms"]),
                ):
                    mem_rows += (
                        f"<tr><td>{escape(str(k))}</td>"
                        f"<td>{escape(str(v))}</td></tr>"
                    )
                ov_rows = "".join(
                    f"<tr><td>{escape(frag)}</td><td>{c}</td></tr>"
                    for frag, c in sorted(adm["credits"].items())
                )
        except Exception:
            mem_rows = ov_rows = ""
        # backpressure attribution: per-fragment verdict histogram +
        # live channel depths (which fragment slow barriers name)
        bp_rows = ""
        hbp = self.histograms.get("backpressure_ms")
        if hbp is not None:
            depth = self.gauges.get("channel_depth")
            for lbl, s in sorted(hbp.summary().items()):
                frag = lbl.split("=", 1)[-1]
                d = depth.get(fragment=frag) if depth is not None else 0.0
                bp_rows += (
                    f"<tr><td>{escape(frag)}</td><td>{s['p50']}</td>"
                    f"<td>{s['p99']}</td><td>{s['count']}</td>"
                    f"<td>{d:g}</td></tr>"
                )
        # mesh observability (ISSUE 18): per-shard attribution + skew
        # + the (src,dst) exchange matrix for the multi-chip path
        mesh_rows = mesh_xm_rows = ""
        try:
            from risingwave_tpu.parallel.meshprof import MESHPROF

            if MESHPROF.enabled:
                msnap = MESHPROF.table_snapshot()
                lb = msnap.get("last_barrier") or {}
                cov = self.gauges.get("mesh_coverage_frac")
                skg = self.gauges.get("shard_skew_frac")
                for k, v in (
                    ("shards", lb.get("n_shards", "-")),
                    (
                        "last coverage",
                        f"{cov.get():.1%}" if cov is not None else "-",
                    ),
                    (
                        "skew frac (max/mean-1)",
                        f"{skg.get():.3f}" if skg is not None else "-",
                    ),
                    (
                        "last skew verdict",
                        lb.get("skew") or "-",
                    ),
                    ("mesh host ms", msnap.get("host_ms", 0.0)),
                    (
                        "calibration ms",
                        msnap.get("calibration_ms", 0.0),
                    ),
                    ("errors", msnap.get("errors", 0)),
                ):
                    mesh_rows += (
                        f"<tr><td>{escape(str(k))}</td>"
                        f"<td>{escape(str(v))}</td></tr>"
                    )
                xm = (msnap.get("exchange") or {}).get("rows")
                if xm:
                    n = len(xm)
                    hdr_cells = "".join(
                        f"<th>dst{j}</th>" for j in range(n)
                    )
                    mesh_xm_rows = (
                        f"<tr><th>rows</th>{hdr_cells}</tr>"
                    )
                    for src, row in enumerate(xm):
                        cells = "".join(
                            f"<td style='text-align:right'>{int(v):,}</td>"
                            for v in row
                        )
                        mesh_xm_rows += (
                            f"<tr><td>src{src}</td>{cells}</tr>"
                        )
        except Exception:
            mesh_rows = mesh_xm_rows = ""
        return f"""<!doctype html><html><head><title>risingwave_tpu</title>
<style>body{{font-family:monospace;margin:2em}}table{{border-collapse:collapse;margin:1em 0}}
td,th{{border:1px solid #999;padding:2px 8px}}h2{{margin-top:1.5em}}</style></head><body>
<h1>risingwave_tpu dashboard</h1>
<h2>runtime</h2><table>{''.join(rows) or '<tr><td>no runtime attached</td></tr>'}</table>
<h2>fragments &rarr; subscribers</h2><table>{frag_rows or '<tr><td>none</td></tr>'}</table>
<h2>device state (top 40)</h2><table><tr><th>executor</th><th>table</th><th>bytes</th></tr>{state_rows}</table>
<h2>barrier stages (ms)</h2><table><tr><th>stage</th><th>p50</th><th>p99</th><th>n</th></tr>{stage_rows or '<tr><td>no barriers traced</td></tr>'}</table>
<h2>dispatch profile (top executors)</h2><table><tr><th>executor</th><th>host ms</th><th>device-wait ms</th><th>dispatches</th></tr>{prof_rows or '<tr><td>profiler not armed (RW_PROFILE=1)</td></tr>'}</table>
<h2>black box &amp; device sentinel</h2><table>{bb_rows or '<tr><td>blackbox unavailable</td></tr>'}</table>
<h2>device roofline (compiled programs)</h2><table><tr><th>program|bucket</th><th>compile ms</th><th>bytes accessed</th><th>flops</th><th>temp bytes</th></tr>{dp_rows or '<tr><td>deviceprof not armed (RW_DEVICEPROF=1)</td></tr>'}</table>
<h2>fused telemetry (last barrier)</h2><table><tr><th>fragment</th><th>rows in</th><th>dirty groups</th><th>mv rows</th><th>lane fill</th><th>padding frac</th></tr>{tel_rows or '<tr><td>no fused barriers yet</td></tr>'}</table>
<h2>freshness (per MV)</h2><table><tr><th>mv</th><th>epoch</th><th>commit&rarr;visible ms</th><th>source&rarr;visible ms</th><th>event-time lag ms</th><th>barriers</th></tr>{fresh_rows or '<tr><td>no published barriers yet</td></tr>'}</table>
<h2>backpressure attribution</h2><table><tr><th>fragment</th><th>p50 ms</th><th>p99 ms</th><th>verdicts</th><th>channel depth</th></tr>{bp_rows or '<tr><td>no verdicts yet</td></tr>'}</table>
<h2>memory &amp; overload</h2><table>{mem_rows or '<tr><td>governor not armed (RW_HBM_BUDGET_BYTES / RW_OVERLOAD_LADDER)</td></tr>'}</table>
<table><tr><th>fragment</th><th>admission credit</th></tr>{ov_rows or '<tr><td>no credit windows derived</td></tr>'}</table>
<h2>mesh (multi-chip)</h2><table>{mesh_rows or '<tr><td>mesh profiler not armed (MESHPROF.enable())</td></tr>'}</table>
<table>{mesh_xm_rows or '<tr><td>no exchange traffic recorded</td></tr>'}</table>
<h2>resilience</h2><table><tr><th>metric</th><th>labels</th><th>value</th></tr>{res_rows or '<tr><td>no retries / breakers yet</td></tr>'}</table>
<h2>events (last 25)</h2><table><tr><th>#</th><th>kind</th><th>detail</th></tr>{event_rows or '<tr><td>none</td></tr>'}</table>
<p><a href="/metrics">/metrics</a> (prometheus text, <code>render_prometheus()</code>) &middot; <a href="/heap">/heap</a> &middot; <a href="/events">/events</a></p>
</body></html>"""

    def serve(self, port: int = 0) -> int:
        """Expose ``/metrics`` over HTTP (the prometheus scrape surface
        the reference serves from each node). Returns the bound port."""
        import http.server

        registry = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                path = self.path.rstrip("/")
                if path == "/heap":
                    # heap profile: device-state accounting + host
                    # tracemalloc top (utils_heap; jeprof analogue)
                    from risingwave_tpu import utils_heap

                    body = utils_heap.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/events":
                    # meta event log (reference: risectl meta event-log
                    # / the dashboard's event view) as JSON
                    from risingwave_tpu.event_log import EVENT_LOG

                    body = EVENT_LOG.to_json().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path in ("", "/dashboard"):
                    # the ops dashboard (reference: the meta dashboard
                    # UI, collapsed to one self-contained page)
                    body = registry.render_dashboard().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/html; charset=utf-8"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path != "/metrics":
                    self.send_response(404)
                    self.end_headers()
                    return
                body = registry.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler
        )
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return self._server.server_address[1]

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


# the process-default registry (reference: GLOBAL_METRICS_REGISTRY)
REGISTRY = MetricsRegistry()


def render_prometheus() -> str:
    """Module-level scrape shorthand: the default registry's prometheus
    text exposition (``metrics.render_prometheus()``)."""
    return REGISTRY.render_prometheus()


def record_recompiles(deltas: Dict[str, int]) -> None:
    """Per-kernel compiled-fn cache misses (analysis.RecompileWatch
    deltas) -> ``recompiles_total{fn=...}``. Steady-state epochs must
    keep this flat: every increment is a re-trace of a fused step —
    ~30s each on a tunneled TPU, the recompile-storm failure mode the
    fixed-capacity chunk design exists to prevent."""
    c = REGISTRY.counter("recompiles_total")
    for fn, d in deltas.items():
        if d:
            c.inc(d, fn=fn)
