"""Transient-fault resilience kernel — retry, breaker, degraded spill.

Reference: every object-store touch in the reference goes through a
retrying, monitored wrapper (src/object_store/src/object/mod.rs —
``RetryCondition`` + backoff around each op, per-op timeouts from
``ObjectStoreConfig``), and the madsim tier injects faults to assert
the cluster converges anyway. This module is that boundary for the
whole engine:

- ``RetryPolicy``: exponential backoff with deterministic seeded
  jitter, a per-attempt timeout hint, an overall deadline, and a
  transient-vs-fatal error classifier. Every retry loop built on it is
  provably bounded: attempts <= max_attempts AND sleep never crosses
  the deadline.
- ``CircuitBreaker``: closed -> open -> half-open with cooldown, so a
  hard-down dependency fails fast instead of eating a full retry
  budget per op; transitions land in the event log and metrics.
- ``RetryingObjectStore``: the durability-boundary wrapper used by
  ``CheckpointManager`` for SST upload / manifest commit / compaction
  IO. Ops are idempotent (immutable blobs; manifest put overwrites),
  so blind retry is safe.
- ``DeltaSpill``: degraded-mode staging — when the store breaker opens
  mid-epoch, the runtime spills staged checkpoint deltas to a local
  dir and replays them once the breaker half-opens.

Classification contract: ``TransientStoreError`` subclasses OSError so
the storage layer's existing read-race handling treats injected faults
exactly like a GC race. ``CrashPoint`` (sim/chaos.py) is a
BaseException and always propagates — a retry loop must never "handle"
a process death.

Env knobs (also exposed via ``config.ResilienceConfig``):
  RW_RETRY_MAX_ATTEMPTS     (default 8)
  RW_RETRY_BASE_BACKOFF_MS  (default 50)
  RW_RETRY_MAX_BACKOFF_MS   (default 2000)
  RW_RETRY_DEADLINE_S       (default 30)
  RW_RETRY_JITTER           (default 0.5, fraction of the backoff)
  RW_BREAKER_THRESHOLD      (default 5 consecutive failures)
  RW_BREAKER_COOLDOWN_S     (default 5)
  RW_DEGRADED_DIR           (default: a mkdtemp under the tmpdir)
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from risingwave_tpu.metrics import REGISTRY

# NOTE: this module is the resilience KERNEL — it must not import the
# storage package (state_table imports us; the object-store protocol is
# duck-typed here, exactly like every store wrapper in sim/chaos.py).


class TransientStoreError(OSError):
    """A fault the caller should retry: flaky blob store, slow upload,
    connection blip. OSError subclass on purpose — the storage read
    paths already treat OSError as a transient race."""


#: error types retried by default. FileNotFoundError/PermissionError
#: are OSErrors but SEMANTIC (a miss / a config error), never retried
#: unless a caller's classifier says otherwise (storage reads do:
#: there, a missing SST is a compaction-GC race).
DEFAULT_TRANSIENT = (
    TransientStoreError,
    ConnectionError,
    TimeoutError,
    InterruptedError,
)
DEFAULT_FATAL = (FileNotFoundError, PermissionError, IsADirectoryError)


def default_classify(exc: Exception) -> bool:
    return isinstance(exc, DEFAULT_TRANSIENT) and not isinstance(
        exc, DEFAULT_FATAL
    )


def _env_val(name: str, cast, default):
    """One env knob: ``cast(os.environ[name])``, falling back to
    ``default`` when unset or unparseable."""
    v = os.environ.get(name)
    if v is None:
        return default
    try:
        return cast(v)
    except ValueError:
        return default


class RetryBudgetExceeded(RuntimeError):
    """The retry loop's budget (attempts or deadline) ran out. Carries
    the schedule so operators can see WHY it gave up."""

    def __init__(self, op: str, attempts: int, elapsed_s: float,
                 last_error: Optional[BaseException]):
        self.op = op
        self.attempts = attempts
        self.elapsed_s = elapsed_s
        self.last_error = last_error
        super().__init__(
            f"retry budget exceeded for {op!r}: {attempts} attempts over "
            f"{elapsed_s:.3f}s (last: {last_error!r})"
        )


class CircuitOpenError(RuntimeError):
    """Fast-fail: the breaker is open; the dependency is presumed down
    until the cooldown elapses and a half-open probe succeeds."""


@dataclass
class RetryPolicy:
    """Bounded retry: exponential backoff, seeded jitter, deadline.

    ``per_attempt_timeout_s`` is a HINT for callers whose ops accept a
    timeout (socket settimeout, ranged GETs); pure-python attempts
    cannot be preempted, but an overrunning attempt still counts
    against the overall deadline, so the loop stays bounded."""

    max_attempts: int = 8
    base_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    multiplier: float = 2.0
    deadline_s: float = 30.0
    per_attempt_timeout_s: Optional[float] = None
    jitter_frac: float = 0.5
    seed: int = 0
    classify: Callable[[Exception], bool] = field(default=default_classify)

    @classmethod
    def from_env(cls, **defaults) -> "RetryPolicy":
        """Policy from the ``RW_RETRY_*`` knobs. ``defaults`` supply
        the caller's baseline for unset knobs (and pass through fields
        with no env backing, e.g. ``classify``) — a SET env var always
        wins, so the operator's no-restart escape hatch works even for
        callers that pin their own defaults."""
        kw = dict(
            max_attempts=_env_val(
                "RW_RETRY_MAX_ATTEMPTS", int,
                defaults.pop("max_attempts", 8),
            ),
            base_backoff_s=_env_val(
                "RW_RETRY_BASE_BACKOFF_MS",
                lambda v: float(v) / 1e3,
                defaults.pop("base_backoff_s", 0.05),
            ),
            max_backoff_s=_env_val(
                "RW_RETRY_MAX_BACKOFF_MS",
                lambda v: float(v) / 1e3,
                defaults.pop("max_backoff_s", 2.0),
            ),
            deadline_s=_env_val(
                "RW_RETRY_DEADLINE_S", float,
                defaults.pop("deadline_s", 30.0),
            ),
            jitter_frac=_env_val(
                "RW_RETRY_JITTER", float,
                defaults.pop("jitter_frac", 0.5),
            ),
        )
        kw.update(defaults)
        return cls(**kw)

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        """Deterministic backoff for the ``attempt``-th retry (1-based):
        exp growth capped at max, minus a seeded jitter slice (jitter
        shrinks the wait — the cap stays a provable bound)."""
        b = min(
            self.max_backoff_s,
            self.base_backoff_s * (self.multiplier ** (attempt - 1)),
        )
        return b * (1.0 - self.jitter_frac * rng.random())

    def run(
        self,
        fn: Callable[[], object],
        op: str = "op",
        classify: Optional[Callable[[Exception], bool]] = None,
        on_retry: Optional[Callable[[Exception, int], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        """Run ``fn`` with retries. Transient errors (per ``classify``)
        are retried with backoff until success, ``max_attempts``, or
        ``deadline_s`` — whichever comes first. Fatal errors and
        BaseExceptions (CrashPoint!) propagate immediately. ``on_retry``
        fires before each backoff sleep (breaker hookup, manifest
        reload)."""
        classify = classify or self.classify
        rng: Optional[random.Random] = None  # built on first failure:
        t0 = clock()  # the success path stays allocation-light
        last: Optional[Exception] = None
        # "no retries" (max_attempts<=1, incl. a 0 from the env knob)
        # still means ONE attempt — fn always runs at least once
        for attempt in range(1, max(1, self.max_attempts) + 1):
            try:
                out = fn()
                if attempt > 1:
                    REGISTRY.counter(
                        "retry_success_after_retry_total"
                    ).inc(op=op)
                return out
            except Exception as e:
                if not classify(e):
                    raise
                if rng is None:
                    rng = random.Random(self.seed)
                last = e
                REGISTRY.counter("retries_total").inc(op=op)
                if on_retry is not None:
                    on_retry(e, attempt)
                elapsed = clock() - t0
                wait = self.backoff_s(attempt, rng)
                if (
                    attempt >= max(1, self.max_attempts)
                    or elapsed + wait >= self.deadline_s
                ):
                    break
                sleep(wait)
        REGISTRY.counter("retry_giveups_total").inc(op=op)
        raise RetryBudgetExceeded(
            op, attempt, clock() - t0, last
        ) from last


class CircuitBreaker:
    """closed -> open -> half-open with cooldown.

    ``allow()`` gates calls: closed always passes; open fails fast
    until ``cooldown_s`` elapsed, then flips to half-open and lets
    probes through; a half-open success closes, a half-open failure
    re-opens. Transitions are recorded in the event log and as
    ``breaker_state`` / ``breaker_transitions_total`` metrics."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
    _STATE_NUM = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}

    def __init__(
        self,
        name: str = "default",
        failure_threshold: int = 5,
        cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self.transitions: List[Tuple[str, str]] = []

    @classmethod
    def from_env(cls, name: str = "default", **defaults) -> "CircuitBreaker":
        """Breaker from the ``RW_BREAKER_*`` knobs; ``defaults`` are
        the caller's baseline for unset knobs (a SET env var wins)."""
        kw = dict(
            failure_threshold=_env_val(
                "RW_BREAKER_THRESHOLD", int,
                defaults.pop("failure_threshold", 5),
            ),
            cooldown_s=_env_val(
                "RW_BREAKER_COOLDOWN_S", float,
                defaults.pop("cooldown_s", 5.0),
            ),
        )
        kw.update(defaults)
        return cls(name, **kw)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, to: str) -> None:
        # callers hold self._lock
        frm, self._state = self._state, to
        if frm == to:
            return
        self.transitions.append((frm, to))
        REGISTRY.counter("breaker_transitions_total").inc(
            name=self.name, to=to
        )
        REGISTRY.gauge("breaker_state").set(
            self._STATE_NUM[to], name=self.name
        )
        # imported here: event_log -> metrics, and this module is
        # imported by storage — keep the import graph acyclic
        from risingwave_tpu.event_log import EVENT_LOG

        EVENT_LOG.record("breaker", name=self.name, frm=frm, to=to)

    def allow(self) -> bool:
        """May a call proceed right now? (Non-consuming: half-open lets
        probes through and relies on record_success/failure to settle.)"""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._transition(self.HALF_OPEN)
                    return True
                return False
            return True  # half-open: probe away

    def force_probe(self) -> None:
        """Operator/driver override: an EXPLICIT recovery is a manual
        probe — skip the cooldown and let the next call through (it
        settles the breaker via record_success/failure as usual)."""
        with self._lock:
            if self._state == self.OPEN:
                self._transition(self.HALF_OPEN)

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._state != self.CLOSED:
                self._transition(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._state == self.HALF_OPEN or (
                self._state == self.CLOSED
                and self._consecutive >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                self._transition(self.OPEN)
            elif self._state == self.OPEN:
                # a failure while open (late probe) restarts cooldown
                self._opened_at = self._clock()


#: what the runtime treats as "the store is unavailable": degrade, do
#: not die. (RetryBudgetExceeded from a store op, or a fast-fail from
#: an open breaker.)
STORE_UNAVAILABLE = (CircuitOpenError, RetryBudgetExceeded)


class RetryingObjectStore:
    """The durability-boundary wrapper: every op retried per policy,
    gated by an optional shared breaker, counted in metrics. Safe to
    wrap ANY store: ops are idempotent (immutable blobs; manifest put
    overwrites; delete of a deleted path is a no-op). Duck-typed over
    the ObjectStore protocol so the resilience kernel stays free of
    storage imports."""

    def __init__(
        self,
        inner,
        policy: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
    ):
        self.inner = inner
        self.policy = policy or RetryPolicy.from_env()
        self.breaker = breaker

    def _call(self, op: str, fn: Callable[[], object]):
        br = self.breaker
        if br is not None and not br.allow():
            REGISTRY.counter("store_fast_fails_total").inc(op=op)
            raise CircuitOpenError(
                f"object store breaker {br.name!r} is open ({op})"
            )

        def _on_retry(exc, attempt):
            # fires on EVERY transient failure (including the last):
            # the breaker sees each attempt, so a fault storm opens it
            # mid-retry-loop; fatal (semantic) errors bypass on_retry
            # and never poison the breaker
            if br is not None:
                br.record_failure()

        out = self.policy.run(fn, op=f"store.{op}", on_retry=_on_retry)
        if br is not None:
            br.record_success()
        return out

    def put(self, path: str, data: bytes) -> None:
        self._call("put", lambda: self.inner.put(path, data))

    def read(self, path: str) -> bytes:
        return self._call("read", lambda: self.inner.read(path))

    def read_range(self, path: str, off: int, length: int) -> bytes:
        return self._call(
            "read_range", lambda: self.inner.read_range(path, off, length)
        )

    def exists(self, path: str) -> bool:
        return self._call("exists", lambda: self.inner.exists(path))

    def list(self, prefix: str):
        return self._call("list", lambda: self.inner.list(prefix))

    def delete(self, path: str) -> None:
        self._call("delete", lambda: self.inner.delete(path))


class DeltaSpill:
    """Degraded-mode staging: one ``.npz`` per spilled epoch under a
    local dir, replayed in epoch order once the store heals. The spill
    is an extension of the async commit lane's in-memory queue onto
    disk — staged deltas are host-side copies, so committing them later
    (in order) is exactly the lane's normal backlog semantics."""

    def __init__(self, root: Optional[str] = None):
        self._root = root or os.environ.get("RW_DEGRADED_DIR")
        self._made = False

    @property
    def root(self) -> str:
        if self._root is None:
            import tempfile

            self._root = tempfile.mkdtemp(prefix="rw_degraded_")
        if not self._made:
            os.makedirs(self._root, exist_ok=True)
            self._made = True
        return self._root

    def _path(self, epoch: int) -> str:
        return os.path.join(self.root, f"{epoch:020d}.npz")

    def spill(self, epoch: int, staged: Sequence[object]) -> str:
        import numpy as np

        meta = []
        arrays = {}
        for i, d in enumerate(staged):
            meta.append(
                {
                    "table_id": d.table_id,
                    "key_order": list(d.key_order),
                    "key_names": list(d.key_cols),
                    "value_names": list(d.value_cols),
                }
            )
            for k, a in d.key_cols.items():
                arrays[f"d{i}.k.{k}"] = np.asarray(a)
            for v, a in d.value_cols.items():
                arrays[f"d{i}.v.{v}"] = np.asarray(a)
            arrays[f"d{i}.tomb"] = np.asarray(d.tombstone)
        arrays["meta"] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8
        )
        path = self._path(epoch)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
        REGISTRY.counter("degraded_epochs_spilled_total").inc()
        return path

    def load(self, epoch: int) -> List[object]:
        import numpy as np

        from risingwave_tpu.storage.state_table import StateDelta

        with np.load(self._path(epoch), allow_pickle=True) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            out = []
            for i, m in enumerate(meta):
                out.append(
                    StateDelta(
                        m["table_id"],
                        {k: z[f"d{i}.k.{k}"] for k in m["key_names"]},
                        {v: z[f"d{i}.v.{v}"] for v in m["value_names"]},
                        z[f"d{i}.tomb"],
                        tuple(m["key_order"]),
                    )
                )
        return out

    def epochs(self) -> List[int]:
        if self._root is None or not os.path.isdir(self._root):
            return []
        return sorted(
            int(fn.split(".")[0])
            for fn in os.listdir(self._root)
            if fn.endswith(".npz")
        )

    def remove(self, epoch: int) -> None:
        try:
            os.unlink(self._path(epoch))
        except FileNotFoundError:
            pass

    def discard_all(self) -> int:
        n = 0
        for e in self.epochs():
            self.remove(e)
            n += 1
        return n
