"""Named sync points for deterministic concurrency/crash tests.

Reference: src/utils/sync-point/src/lib.rs — instrumented sites call
``sync_point!("name")``; tests attach actions (wait, signal, panic) to
drive exact interleavings. Here: ``hit(name)`` is a no-op unless a test
activated an action for that name — zero overhead in production paths
(one dict lookup against an empty dict).

Instrumented sites (grow this list as tests need them):
- ``before_manifest_commit``   — SSTs uploaded, manifest not yet written
- ``after_manifest_commit``    — epoch just became durable
- ``before_compaction_gc``     — compaction about to delete merged SSTs
"""

from __future__ import annotations

import threading
from typing import Callable, Dict

_ACTIONS: Dict[str, Callable[[], None]] = {}
_LOCK = threading.Lock()


def activate(name: str, action: Callable[[], None]) -> None:
    """Attach an action to a sync point (test-side)."""
    with _LOCK:
        _ACTIONS[name] = action


def deactivate(name: str) -> None:
    with _LOCK:
        _ACTIONS.pop(name, None)


def reset() -> None:
    with _LOCK:
        _ACTIONS.clear()


def hit(name: str) -> None:
    """Called at instrumented sites; runs the test's action if any.
    Actions may raise (crash injection), block on events (interleaving
    control), or record (tracing)."""
    action = _ACTIONS.get(name)
    if action is not None:
        action()
