"""Logical output-type inference for SELECT / CREATE MV.

Reference: the binder/type-inference pass (src/frontend/src/binder/ +
src/frontend/src/expr/type_inference/) — here a deliberately small,
best-effort version: enough to know which output columns are DECIMAL /
VARCHAR / JSONB / INTERVAL so the session can decode device lanes
(scaled ints, dictionary codes) back to SQL values at the result edge.

Columns whose type cannot be inferred (complex expressions) return no
entry and surface as their raw device values.
"""

from __future__ import annotations

from typing import Dict, Optional

from risingwave_tpu.sql import parser as P
from risingwave_tpu.types import DataType, Field


def _from_env(env: Dict[str, Field], name: str) -> Optional[Field]:
    return env.get(name)


def _env_of_rel(rel, catalog) -> Dict[str, Field]:
    """Visible columns (name -> logical Field) of a FROM clause."""
    if isinstance(rel, P.TableRef):
        sch = catalog.tables.get(rel.name)
        if sch is None:
            return {}
        return {f.name: f for f in sch.fields}
    if isinstance(rel, P.Join):
        env = _env_of_rel(rel.left, catalog)
        env.update(_env_of_rel(rel.right, catalog))
        return env
    if isinstance(rel, P.SubQuery):
        inner = infer_output_fields(rel.select, catalog)
        return {n: Field(n, f.dtype, scale=f.scale) for n, f in inner.items()}
    if isinstance(rel, P.WindowTVF):
        env = _env_of_rel(rel.table, catalog)
        # window columns are timestamps
        for extra in ("window_start", "window_end"):
            env.setdefault(extra, Field(extra, DataType.TIMESTAMP))
        return env
    return {}


def infer_output_fields(stmt, catalog) -> Dict[str, Field]:
    """Best-effort output column name -> logical Field for a Select."""
    if not isinstance(stmt, P.Select):
        return {}
    env = _env_of_rel(stmt.from_, catalog) if stmt.from_ is not None else {}
    out: Dict[str, Field] = {}
    for i, item in enumerate(stmt.items):
        expr = item.expr
        if isinstance(expr, P.Ident):
            f = _from_env(env, expr.name)
            if f is not None:
                name = item.alias or expr.name
                out[name] = Field(name, f.dtype, scale=f.scale)
            continue
        if isinstance(expr, P.FuncCall):
            name = item.alias or f"{expr.name}_{i}"
            if expr.name in ("count",):
                out[name] = Field(name, DataType.INT64)
            elif expr.name in ("sum", "min", "max", "avg") and expr.args:
                arg = expr.args[0]
                if isinstance(arg, P.Ident):
                    f = _from_env(env, arg.name)
                    if f is not None:
                        if expr.name == "avg":
                            out[name] = Field(name, DataType.FLOAT64)
                        else:
                            # sum/min/max keep the argument's logical
                            # type; DECIMAL keeps its scale (scaled-int
                            # sums stay exact at the same scale)
                            out[name] = Field(
                                name, f.dtype, scale=f.scale
                            )
    return out
