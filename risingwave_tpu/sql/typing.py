"""Logical output-type inference for SELECT / CREATE MV.

Reference: the binder/type-inference pass (src/frontend/src/binder/ +
src/frontend/src/expr/type_inference/) — here a deliberately small,
best-effort version: enough to know which output columns are DECIMAL /
VARCHAR / JSONB / INTERVAL so the session can decode device lanes
(scaled ints, dictionary codes) back to SQL values at the result edge.

Columns whose type cannot be inferred (complex expressions) return no
entry and surface as their raw device values.
"""

from __future__ import annotations

from typing import Dict, Optional

from risingwave_tpu.sql import parser as P
from risingwave_tpu.types import DataType, Field


def _from_env(env: Dict[str, Field], name: str) -> Optional[Field]:
    return env.get(name)


def _env_of_rel(rel, catalog) -> Dict[str, Field]:
    """Visible columns (name -> logical Field) of a FROM clause."""
    if isinstance(rel, P.TableRef):
        sch = catalog.tables.get(rel.name)
        if sch is None:
            return {}
        return {f.name: f for f in sch.fields}
    if isinstance(rel, P.Join):
        env = _env_of_rel(rel.left, catalog)
        env.update(_env_of_rel(rel.right, catalog))
        return env
    if isinstance(rel, P.SubQuery):
        inner = infer_output_fields(rel.select, catalog)
        return {n: Field(n, f.dtype, scale=f.scale) for n, f in inner.items()}
    if isinstance(rel, P.WindowTVF):
        env = _env_of_rel(rel.table, catalog)
        # window columns are timestamps
        for extra in ("window_start", "window_end"):
            env.setdefault(extra, Field(extra, DataType.TIMESTAMP))
        return env
    return {}


def output_name(item: P.SelectItem, i: int) -> str:
    """The output column name of one select item — shared by the
    inference pass, the batch engine, and pgwire Describe so names
    never drift between layers."""
    if item.alias:
        return item.alias
    expr = item.expr
    if isinstance(expr, P.Ident):
        return expr.name
    if isinstance(expr, P.WindowFuncCall):
        return f"{expr.func.name}_{i}"
    if isinstance(expr, P.FuncCall):
        return f"{expr.name}_{i}"
    return f"col{i}"


def infer_output_fields(stmt, catalog) -> Dict[str, Field]:
    """Best-effort output column name -> logical Field for a Select."""
    if isinstance(stmt, P.UnionAll):
        # branches share one schema (the planner enforces it): the
        # first branch types the union's output
        stmt = stmt.selects[0]
    if not isinstance(stmt, P.Select):
        return {}
    stmt = expand_star(stmt, catalog, strict=False)
    env = _env_of_rel(stmt.from_, catalog) if stmt.from_ is not None else {}
    out: Dict[str, Field] = {}
    for i, item in enumerate(stmt.items):
        expr = item.expr
        if isinstance(expr, P.Ident):
            f = _from_env(env, expr.name)
            if f is not None:
                name = item.alias or expr.name
                out[name] = Field(name, f.dtype, scale=f.scale)
            continue
        if isinstance(expr, P.WindowFuncCall):
            name = item.alias or f"{expr.func.name}_{i}"
            fn = expr.func.name
            if fn in ("row_number", "rank", "dense_rank", "count"):
                out[name] = Field(name, DataType.INT64)
            elif expr.func.args and isinstance(expr.func.args[0], P.Ident):
                f = _from_env(env, expr.func.args[0].name)
                if f is not None:  # lag/lead/sum/min/max keep arg type
                    out[name] = Field(name, f.dtype, scale=f.scale)
            continue
        if isinstance(expr, P.FuncCall):
            name = item.alias or f"{expr.name}_{i}"
            from risingwave_tpu.expr.functions import udf_signature

            sig = udf_signature(expr.name)
            if sig is not None:
                rf = sig[0]
                out[name] = Field(name, rf.dtype, scale=rf.scale)
                continue
            if expr.name in ("count", "approx_count_distinct"):
                out[name] = Field(name, DataType.INT64)
            elif expr.name == "string_agg":
                out[name] = Field(name, DataType.VARCHAR)
            elif expr.name in (
                "var_pop", "var_samp", "stddev_pop", "stddev_samp",
            ):
                out[name] = Field(name, DataType.FLOAT64)
            elif expr.name in ("bool_and", "bool_or"):
                out[name] = Field(name, DataType.BOOLEAN)
            elif expr.name in ("sum", "min", "max", "avg") and expr.args:
                arg = expr.args[0]
                if isinstance(arg, P.Ident):
                    f = _from_env(env, arg.name)
                    if f is not None:
                        if expr.name == "avg":
                            out[name] = Field(name, DataType.FLOAT64)
                        else:
                            # sum/min/max keep the argument's logical
                            # type; DECIMAL keeps its scale (scaled-int
                            # sums stay exact at the same scale)
                            out[name] = Field(
                                name, f.dtype, scale=f.scale
                            )
    return out


# ---------------------------------------------------------------------------
# Type-directed statement rewriting / checking
# ---------------------------------------------------------------------------

_CMP_OPS = ("=", "<>", "!=", "<", "<=", ">", ">=", "+", "-")


def _scale_lit(lit: P.Literal, scale: int) -> P.Literal:
    from decimal import Decimal

    if lit.value is None:
        return lit
    return P.Literal(
        int(Decimal(repr(lit.value)).scaleb(scale).to_integral_value())
    )


def _field_of(env, ident: P.Ident):
    return env.get(ident.name)


def _lane_lit(lit: P.Literal, field, strings) -> P.Literal:
    """A literal compared against a column, rewritten into the column's
    LANE domain: DECIMAL scales; VARCHAR/JSONB encode to a dictionary
    code (a fresh code matches no stored row — exactly right for
    equality on an unseen string)."""
    if lit.value is None:
        return lit
    if field.dtype is DataType.DECIMAL:
        return _scale_lit(lit, field.scale)
    if field.dtype is DataType.VARCHAR and isinstance(lit.value, str):
        if strings is None:
            raise ValueError("VARCHAR literal needs the session dictionary")
        return P.Literal(int(strings.encode_one(lit.value)))
    if field.dtype is DataType.JSONB and isinstance(lit.value, str):
        import json

        if strings is None:
            raise ValueError("JSONB literal needs the session dictionary")
        canon = json.dumps(
            json.loads(lit.value), sort_keys=True, separators=(",", ":")
        )
        return P.Literal(int(strings.encode_one(canon)))
    return lit


def _rewrite_pred(pred, env, strings=None):
    """Rewrite literals compared against DECIMAL/VARCHAR/JSONB columns
    into the lane domain (scaled ints / dictionary codes) — a raw
    literal would silently compare at the wrong magnitude or crash on
    the int32 code lane."""
    if isinstance(pred, P.BinaryOp):
        left = _rewrite_pred(pred.left, env, strings)
        right = _rewrite_pred(pred.right, env, strings)
        if pred.op in _CMP_OPS:
            lf = _field_of(env, left) if isinstance(left, P.Ident) else None
            rf = _field_of(env, right) if isinstance(right, P.Ident) else None
            dict_side = next(
                (
                    f
                    for f in (lf, rf)
                    if f is not None
                    and f.dtype in (DataType.VARCHAR, DataType.JSONB)
                ),
                None,
            )
            if dict_side is not None and pred.op not in ("=", "<>", "!="):
                # dictionary codes are insertion-ordered, not
                # collation-ordered: ordered operators over them would
                # silently return wrong rows (mirrors _check_collation)
                raise NotImplementedError(
                    f"operator '{pred.op}' on {dict_side.dtype.name}: "
                    "dictionary codes are equality-only, not "
                    "collation-ordered"
                )
            if lf is not None and isinstance(right, P.Literal):
                right = _lane_lit(right, lf, strings)
            elif rf is not None and isinstance(left, P.Literal):
                left = _lane_lit(left, rf, strings)
        return P.BinaryOp(pred.op, left, right)
    if isinstance(pred, P.UnaryOp):
        return P.UnaryOp(pred.op, _rewrite_pred(pred.operand, env, strings))
    if isinstance(pred, P.FuncCall):
        args = [
            a if isinstance(a, str) else _rewrite_pred(a, env, strings)
            for a in pred.args
        ]
        from risingwave_tpu.expr.functions import udf_signature

        sig = udf_signature(pred.name)
        if sig is not None:
            # typed-signature functions (UDFs + string builtins):
            # literal args coerce into each parameter's lane domain
            _out_f, arg_fs = sig
            args = [
                _lane_lit(a, f, strings)
                if isinstance(a, P.Literal) and f is not None
                else a
                for a, f in zip(args, list(arg_fs) + [None] * len(args))
            ]
        if pred.name in ("between", "in") and args:
            f = _field_of(env, args[0]) if isinstance(args[0], P.Ident) else None
            if f is not None:
                if pred.name == "between" and f.dtype in (
                    DataType.VARCHAR,
                    DataType.JSONB,
                ):
                    raise NotImplementedError(
                        f"{f.dtype.name} BETWEEN: dictionary codes are "
                        "not collation-ordered"
                    )
                args = [args[0]] + [
                    _lane_lit(a, f, strings) if isinstance(a, P.Literal) else a
                    for a in args[1:]
                ]
        return P.FuncCall(pred.name, tuple(args), distinct=pred.distinct)
    if isinstance(pred, P.CaseExpr):
        return P.CaseExpr(
            tuple(
                (_rewrite_pred(c, env, strings), _rewrite_pred(v, env, strings))
                for c, v in pred.branches
            ),
            _rewrite_pred(pred.default, env, strings)
            if pred.default is not None
            else None,
        )
    return pred


def _check_collation(select: P.Select, env, out_fields) -> None:
    """Dictionary codes are equality-complete but NOT ordered: min/max
    and ORDER BY over VARCHAR/JSONB would return the insertion-order
    winner as if it were the collation winner — refuse loudly instead
    (array/dictionary.py documents the limitation)."""
    dict_types = (DataType.VARCHAR, DataType.JSONB)
    for item in select.items:
        e = item.expr
        if (
            isinstance(e, P.FuncCall)
            and e.name in ("min", "max")
            and e.args
            and isinstance(e.args[0], P.Ident)
        ):
            f = _field_of(env, e.args[0])
            if f is not None and f.dtype in dict_types:
                raise NotImplementedError(
                    f"{e.name}() over {f.dtype.value} is not supported: "
                    "dictionary codes are not collation-ordered"
                )
    for ident, _desc in select.order_by:
        f = out_fields.get(ident.name) or _field_of(env, ident)
        if f is not None and f.dtype in dict_types:
            raise NotImplementedError(
                f"ORDER BY {ident.name} ({f.dtype.value}) is not "
                "supported: dictionary codes are not collation-ordered"
            )


def _names_of_rel(rel, catalog, strict: bool) -> list:
    """Output column NAMES of a FROM clause. Name-complete even where
    TYPES are uninferrable (star expansion needs names only — the
    best-effort type env would silently drop expression columns)."""
    if isinstance(rel, P.TableRef):
        sch = catalog.tables.get(rel.name)
        if sch is None:
            return []
        if getattr(catalog, "is_mv", lambda n: False)(rel.name):
            # MV schemas carry PLANNER-hidden lanes (_row_id, hidden
            # join keys) — those stay hidden; base-table underscore
            # columns are user-created and expand normally
            return [n for n in sch.names if not n.startswith("_")]
        return list(sch.names)
    if isinstance(rel, P.Join):
        return _names_of_rel(rel.left, catalog, strict) + _names_of_rel(
            rel.right, catalog, strict
        )
    if isinstance(rel, P.SubQuery):
        inner = expand_star(rel.select, catalog, strict=False)
        out = []
        for i, it in enumerate(inner.items):
            if isinstance(it.expr, P.Star):
                return []  # inner couldn't expand: names unknown
            if it.alias:
                out.append(it.alias)
            elif isinstance(it.expr, P.Ident):
                out.append(it.expr.name)
            elif isinstance(it.expr, P.FuncCall):
                out.append(f"{it.expr.name}_{i}")
            elif isinstance(it.expr, P.WindowFuncCall):
                out.append(f"{it.expr.func.name}_{i}")
            elif strict:
                raise ValueError(
                    "SELECT * over a derived table with unnamed "
                    "expression columns: alias them"
                )
            else:
                return []
        return out
    if isinstance(rel, P.WindowTVF):
        return _names_of_rel(rel.table, catalog, strict) + [
            "window_start",
            "window_end",
        ]
    return []


def expand_star(select: P.Select, catalog, strict: bool = True) -> P.Select:
    """SELECT * -> explicit Ident items in relation column order
    (binder star expansion, binder/select.rs). ``strict=False``
    returns the select unchanged when the relation's columns are
    unknown (inner derived tables during best-effort inference).
    Catalog schemas list user-visible columns only, so hidden planner
    lanes never expand — including user columns that happen to start
    with an underscore."""
    if not any(isinstance(it.expr, P.Star) for it in select.items):
        return select
    names = _names_of_rel(select.from_, catalog, strict)
    if not names:
        if not strict:
            return select
        raise ValueError("SELECT *: unknown relation columns")
    items = []
    for it in select.items:
        if isinstance(it.expr, P.Star):
            items.extend(P.SelectItem(P.Ident(n), None) for n in names)
        else:
            items.append(it)
    import dataclasses

    return dataclasses.replace(select, items=tuple(items))


def typecheck_select(select: P.Select, catalog, strings=None) -> P.Select:
    """Type-directed pass run before planning/execution: rewrites
    DECIMAL/VARCHAR/JSONB literals into the lane domain and rejects
    unordered-dictionary min/max/ORDER BY. Recurses into derived
    tables."""
    select = expand_star(select, catalog)
    new_from = _typecheck_rel(select.from_, catalog, strings)
    env = _env_of_rel(new_from, catalog)
    where = (
        _rewrite_pred(select.where, env, strings)
        if select.where is not None
        else None
    )
    items = tuple(
        P.SelectItem(_rewrite_pred(i.expr, env, strings), i.alias)
        for i in select.items
    )
    out = P.Select(
        items=items,
        from_=new_from,
        where=where,
        group_by=select.group_by,
        order_by=select.order_by,
        limit=select.limit,
        grouping_sets=select.grouping_sets,
        distinct=select.distinct,
    )
    out_fields = infer_output_fields(out, catalog)
    if select.having is not None:
        # HAVING references OUTPUT names; group KEYS keep their source
        # lane domains (DECIMAL scaling, dictionary codes), so literals
        # rewrite against the inferred output fields
        import dataclasses

        out = dataclasses.replace(
            out,
            having=_rewrite_pred(select.having, out_fields, strings),
        )
    _check_collation(out, env, out_fields)
    return out


def _typecheck_rel(rel, catalog, strings=None):
    if isinstance(rel, P.SubQuery):
        return P.SubQuery(
            typecheck_select(rel.select, catalog, strings), rel.alias
        )
    if isinstance(rel, P.Join):
        return P.Join(
            _typecheck_rel(rel.left, catalog, strings),
            _typecheck_rel(rel.right, catalog, strings),
            rel.on,
            rel.join_type,
        )
    return rel
