"""SQL lexer + recursive-descent parser (Postgres-dialect subset).

Reference: src/sqlparser/ (21.5k LoC forked Postgres parser). This is
the subset the streaming planner consumes — CREATE MATERIALIZED VIEW,
SELECT with window TVFs (TUMBLE/HOP), JOIN ... ON, WHERE, GROUP BY,
aggregate calls, CASE, and the usual scalar operators. The AST mirrors
the reference's sqlparser AST shapes (Statement/Query/SetExpr/
TableFactor) collapsed to what the planner needs.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

# ---------------------------------------------------------------- AST --


@dataclass(frozen=True)
class Ident:
    name: str
    qualifier: Optional[str] = None


@dataclass(frozen=True)
class Literal:
    value: object  # int | float | str | bool | None


@dataclass(frozen=True)
class FuncCall:
    name: str  # lowercased
    args: Tuple[object, ...]  # exprs; ("*",) for COUNT(*)
    distinct: bool = False  # count(DISTINCT x) / string_agg(DISTINCT x)


@dataclass(frozen=True)
class Star:
    """SELECT * — expanded to the relation's columns before planning
    (the reference's binder star expansion, binder/select.rs)."""


@dataclass(frozen=True)
class UnionAll:
    """<select> UNION ALL <select> [...] (reference: the frontend's
    set-operation binder + stream UnionExecutor, union.rs)."""

    selects: Tuple["Select", ...]


@dataclass(frozen=True)
class UnaryOp:
    op: str
    operand: object


@dataclass(frozen=True)
class BinaryOp:
    op: str  # +,-,*,/,%,=,<>,<,<=,>,>=,and,or
    left: object
    right: object


@dataclass(frozen=True)
class CaseExpr:
    branches: Tuple[Tuple[object, object], ...]
    default: Optional[object]


@dataclass(frozen=True)
class WindowFuncCall:
    """<func>(args) OVER (PARTITION BY ... ORDER BY ... [ROWS frame])
    (reference: binder window_function.rs; planner over_window)."""

    func: "FuncCall"
    partition_by: Tuple["Ident", ...]
    order_by: Tuple[Tuple["Ident", bool], ...]  # (col, desc)
    frame: Optional[Tuple[int, int]] = None  # ROWS (lo, hi) rel offsets


@dataclass(frozen=True)
class SelectItem:
    expr: object
    alias: Optional[str]


@dataclass(frozen=True)
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclass(frozen=True)
class WindowTVF:
    kind: str  # "tumble" | "hop"
    table: TableRef
    ts_col: str
    size_ms: int
    slide_ms: int  # == size_ms for tumble
    alias: Optional[str] = None


@dataclass(frozen=True)
class SubQuery:
    select: "Select"
    alias: str


@dataclass(frozen=True)
class Exists:
    """EXISTS (SELECT ... [WHERE corr]) — decorrelated into a left-semi
    (NOT EXISTS: left-anti) join (binder/expr/subquery.rs Exists)."""

    select: "Select"


@dataclass(frozen=True)
class InSubquery:
    """<expr> [NOT] IN (SELECT col FROM ...) — decorrelated into a
    left-semi/anti join on expr = col. NOT IN assumes the subquery
    column is non-NULL (three-valued NOT IN semantics with NULLs are
    not modeled — the reference warns the same way)."""

    expr: object
    select: "Select"
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubQuery:
    """(SELECT <scalar agg expr> FROM t [WHERE corr]) used as an
    expression (reference: binder/expr/subquery.rs:22). The planner
    decorrelates the supported shapes into joins against grouped-agg
    MVs."""

    select: "Select"


@dataclass(frozen=True)
class Join:
    left: object  # relation
    right: object
    on: object  # expr
    join_type: str = "inner"  # inner|left|right|full|{left,right}_{semi,anti}


@dataclass(frozen=True)
class Select:
    items: Tuple[SelectItem, ...]
    from_: object  # relation or Join
    where: Optional[object]
    group_by: Tuple[Ident, ...]
    order_by: Tuple[Tuple[Ident, bool], ...] = ()  # (col, desc)
    limit: Optional[int] = None
    # GROUP BY GROUPING SETS ((a, b), (a), ()) — empty means plain
    grouping_sets: Tuple[Tuple[Ident, ...], ...] = ()
    # HAVING references OUTPUT names (group keys / agg aliases)
    having: Optional[object] = None
    distinct: bool = False  # SELECT DISTINCT a, b == GROUP BY a, b


@dataclass(frozen=True)
class CreateMaterializedView:
    name: str
    select: Select
    # EMIT ON WINDOW CLOSE (reference: EmitOnWindowClose plans): closed
    # windows finalize (state freed) and final rows are exact; this
    # build still emits intermediate updates before the close
    emit_on_window_close: bool = False


@dataclass(frozen=True)
class CreateTable:
    """CREATE TABLE t (col type, ...) — the DML-writable relation DDL
    (reference: src/frontend/src/handler/create_table.rs)."""

    name: str
    columns: Tuple[Tuple[str, str], ...]  # (name, type word)
    pk: Tuple[str, ...] = ()  # PRIMARY KEY (cols); empty -> hidden row id
    # WATERMARK FOR col AS col - INTERVAL '...': (column, lag_ms)
    watermark: Optional[Tuple[str, int]] = None


@dataclass(frozen=True)
class InsertValues:
    """INSERT INTO t [(cols)] VALUES (...), (...) — the DML surface
    (reference: src/frontend/src/handler/dml.rs -> dml executor)."""

    table: str
    rows: Tuple[Tuple[object, ...], ...]
    columns: Optional[Tuple[str, ...]] = None


@dataclass(frozen=True)
class DeleteFrom:
    """DELETE FROM t [WHERE pred] (reference: handler/dml.rs ->
    batch delete executor feeding the table's DML channel)."""

    table: str
    where: Optional[object] = None


@dataclass(frozen=True)
class UpdateSet:
    """UPDATE t SET c = expr [, ...] [WHERE pred]."""

    table: str
    sets: Tuple[Tuple[str, object], ...]  # (column, value expr)
    where: Optional[object] = None


Statement = Union[
    CreateMaterializedView, CreateTable, Select, InsertValues,
    DeleteFrom, UpdateSet,
]

# -------------------------------------------------------------- lexer --

_TOKEN_RE = re.compile(
    r"""\s*(?:
      (?P<num>\d+(?:\.\d+)?)
    | (?P<str>'(?:[^']|'')*')
    | (?P<ident>[A-Za-z_][A-Za-z_0-9]*)
    | (?P<op><>|<=|>=|!=|\|\||[-+*/%(),.=<>])
    )""",
    re.VERBOSE,
)

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "as", "join", "inner", "on",
    "and", "or", "not", "create", "materialized", "view", "tumble", "hop",
    "interval", "second", "seconds", "millisecond", "milliseconds",
    "minute", "minutes", "case", "when", "then", "else", "end", "null", "order", "limit", "asc", "desc",
    "true", "false", "is", "between", "in", "distinct",
    "insert", "into", "values",
}

# Contextual words (NOT reserved — usable as identifiers; recognized by
# value only in join-type position, like the reference sqlparser's
# non-reserved keywords after LEFT/RIGHT):
_JOIN_WORDS = {"left", "right", "full", "outer", "semi", "anti"}

# INTERVAL unit -> milliseconds — shared with the session's CREATE
# SOURCE clause parsing so the two grammars cannot drift
INTERVAL_SCALES = {
    "millisecond": 1, "milliseconds": 1,
    "second": 1000, "seconds": 1000,
    "minute": 60_000, "minutes": 60_000,
}


@dataclass
class _Tok:
    kind: str  # num | str | ident | kw | op | eof
    value: str


def _lex(sql: str) -> List[_Tok]:
    out, pos = [], 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m or m.end() == pos:
            if sql[pos:].strip() == "":
                break
            raise SyntaxError(f"cannot tokenize at: {sql[pos:pos+20]!r}")
        pos = m.end()
        if m.lastgroup == "num":
            out.append(_Tok("num", m.group("num")))
        elif m.lastgroup == "str":
            out.append(_Tok("str", m.group("str")[1:-1].replace("''", "'")))
        elif m.lastgroup == "ident":
            word = m.group("ident").lower()
            out.append(_Tok("kw" if word in _KEYWORDS else "ident", word))
        else:
            out.append(_Tok("op", m.group("op")))
    out.append(_Tok("eof", ""))
    return out


# ------------------------------------------------------------- parser --


class Parser:
    def __init__(self, sql: str):
        self.toks = _lex(sql)
        self.i = 0

    # -- token helpers ---------------------------------------------------
    def peek(self) -> _Tok:
        return self.toks[self.i]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, value: Optional[str] = None) -> Optional[_Tok]:
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            return self.next()
        return None

    def expect(self, kind: str, value: Optional[str] = None) -> _Tok:
        t = self.accept(kind, value)
        if t is None:
            raise SyntaxError(
                f"expected {value or kind}, got {self.peek().value!r}"
            )
        return t

    # -- entry -----------------------------------------------------------
    def parse(self) -> Statement:
        if self.accept("kw", "create"):
            if self._accept_word("table"):
                name = self.expect("ident").value
                self.expect("op", "(")
                cols = []
                pk: Tuple[str, ...] = ()
                watermark: Optional[Tuple[str, int]] = None
                while True:
                    if self._accept_word("watermark"):
                        # WATERMARK FOR col AS col - INTERVAL '...'
                        # (reference: CREATE ... WATERMARK FOR, the
                        # watermark-definition DDL)
                        if not self._accept_word("for"):
                            raise SyntaxError(
                                "expected FOR after WATERMARK"
                            )
                        wcol = self.expect("ident").value
                        self.expect("kw", "as")
                        wcol2 = self.expect("ident").value
                        if wcol2 != wcol:
                            raise SyntaxError(
                                "WATERMARK expression must be "
                                f"{wcol} - INTERVAL '...'"
                            )
                        self.expect("op", "-")
                        lag = self.interval_ms()
                        if watermark is not None:
                            raise SyntaxError("multiple WATERMARK clauses")
                        watermark = (wcol, lag)
                        if not self.accept("op", ","):
                            break
                        continue
                    if self._accept_word("primary"):
                        if not self._accept_word("key"):
                            raise SyntaxError("expected KEY after PRIMARY")
                        if pk:
                            raise SyntaxError("multiple primary keys")
                        self.expect("op", "(")
                        pkc = [self.expect("ident").value]
                        while self.accept("op", ","):
                            pkc.append(self.expect("ident").value)
                        self.expect("op", ")")
                        pk = tuple(pkc)
                        if not self.accept("op", ","):
                            break
                        continue
                    cname = self.expect("ident").value
                    t = self.next()
                    if t.kind not in ("ident", "kw"):
                        raise SyntaxError(f"expected a type, got {t.value!r}")
                    tword = t.value
                    # parameterized types: DECIMAL(10, 2), VARCHAR(64)
                    if self.accept("op", "("):
                        args = [self.expect("num").value]
                        while self.accept("op", ","):
                            args.append(self.expect("num").value)
                        self.expect("op", ")")
                        tword += "(" + ",".join(args) + ")"
                    # inline single-column PRIMARY KEY
                    if self._accept_word("primary"):
                        if not self._accept_word("key"):
                            raise SyntaxError("expected KEY after PRIMARY")
                        if pk:
                            raise SyntaxError("multiple primary keys")
                        pk = (cname,)
                    cols.append((cname, tword))
                    if not self.accept("op", ","):
                        break
                self.expect("op", ")")
                self.expect("eof")
                unknown = set(pk) - {c for c, _ in cols}
                if unknown:
                    raise SyntaxError(f"PRIMARY KEY over unknown {unknown}")
                if watermark is not None and watermark[0] not in {
                    c for c, _ in cols
                }:
                    raise SyntaxError(
                        f"WATERMARK over unknown column {watermark[0]!r}"
                    )
                return CreateTable(name, tuple(cols), pk, watermark)
            self.expect("kw", "materialized")
            self.expect("kw", "view")
            name = self.expect("ident").value
            self.expect("kw", "as")
            sel = self._select_maybe_union()
            eowc = False
            if self._accept_word("emit"):
                if not (
                    self._accept_word("on")
                    and self._accept_word("window")
                    and self._accept_word("close")
                ):
                    raise SyntaxError("expected EMIT ON WINDOW CLOSE")
                eowc = True
            self.expect("eof")
            return CreateMaterializedView(name, sel, eowc)
        if self.accept("kw", "insert"):
            self.expect("kw", "into")
            table = self.expect("ident").value
            cols = None
            if self.accept("op", "("):
                cols = [self.expect("ident").value]
                while self.accept("op", ","):
                    cols.append(self.expect("ident").value)
                self.expect("op", ")")
            self.expect("kw", "values")
            rows = []
            while True:
                self.expect("op", "(")
                row = [self._literal_value()]
                while self.accept("op", ","):
                    row.append(self._literal_value())
                self.expect("op", ")")
                rows.append(tuple(row))
                if not self.accept("op", ","):
                    break
            self.expect("eof")
            return InsertValues(
                table, tuple(rows), tuple(cols) if cols else None
            )
        if self._accept_word("delete"):
            self.expect("kw", "from")
            table = self.expect("ident").value
            where = self.expr() if self.accept("kw", "where") else None
            self.expect("eof")
            return DeleteFrom(table, where)
        if self._accept_word("update"):
            table = self.expect("ident").value
            if not self._accept_word("set"):
                raise SyntaxError("expected SET after UPDATE <table>")
            sets = []
            while True:
                col = self.expect("ident").value
                self.expect("op", "=")
                sets.append((col, self.expr()))
                if not self.accept("op", ","):
                    break
            where = self.expr() if self.accept("kw", "where") else None
            self.expect("eof")
            return UpdateSet(table, tuple(sets), where)
        sel = self._select_maybe_union()
        self.expect("eof")
        return sel

    def _select_maybe_union(self):
        """select [UNION ALL select ...] — chained branches flatten
        into one UnionAll node."""
        branches = [self.select()]
        while self._accept_word("union"):
            if not self._accept_word("all"):
                raise SyntaxError(
                    "only UNION ALL is supported (UNION implies "
                    "distinct, which needs a dedup over the merge)"
                )
            branches.append(self.select())
        if len(branches) == 1:
            return branches[0]
        return UnionAll(tuple(branches))

    def _literal_value(self):
        """A literal (optionally negated) inside VALUES."""
        neg = bool(self.accept("op", "-"))
        t = self.peek()
        if t.kind == "num":
            self.next()
            v = float(t.value) if "." in t.value else int(t.value)
            return -v if neg else v
        if neg:
            raise SyntaxError("'-' needs a numeric literal")
        if t.kind == "str":
            self.next()
            return t.value
        if self.accept("kw", "null"):
            return None
        if self.accept("kw", "true"):
            return True
        if self.accept("kw", "false"):
            return False
        raise SyntaxError(f"expected literal, got {t.value!r}")

    def _accept_word(self, value: str) -> bool:
        """Accept a contextual word: matches a kw OR ident token by value."""
        t = self.peek()
        if t.kind in ("kw", "ident") and t.value == value:
            self.next()
            return True
        return False

    def _join_type(self) -> Optional[str]:
        """Consume a join-type prefix + JOIN keyword; None if no join follows.

        Grammar (ref src/sqlparser parses the same surface forms):
          [INNER] JOIN | LEFT [OUTER] JOIN | RIGHT [OUTER] JOIN
          | FULL [OUTER] JOIN | LEFT SEMI JOIN | LEFT ANTI JOIN
          | RIGHT SEMI JOIN | RIGHT ANTI JOIN
        LEFT/RIGHT/FULL/OUTER/SEMI/ANTI are contextual (valid identifiers
        elsewhere); only a trailing JOIN keyword commits the parse.
        """
        t = self.peek()
        if not (
            (t.kind == "kw" and t.value in ("join", "inner"))
            or (t.kind in ("kw", "ident") and t.value in ("left", "right", "full"))
        ):
            return None
        if self.accept("kw", "join"):
            return "inner"
        if self.accept("kw", "inner"):
            self.expect("kw", "join")
            return "inner"
        side = self.next().value  # left | right | full
        if side in ("left", "right"):
            if self._accept_word("semi"):
                self.expect("kw", "join")
                return f"{side}_semi"
            if self._accept_word("anti"):
                self.expect("kw", "join")
                return f"{side}_anti"
        self._accept_word("outer")
        self.expect("kw", "join")
        return side

    # -- select ----------------------------------------------------------
    def select(self) -> Select:
        self.expect("kw", "select")
        distinct = bool(self.accept("kw", "distinct"))
        # `*` is valid in ANY item position (expanded against the
        # catalog by the typing layer before planning)
        items = []
        while True:
            if self.accept("op", "*"):
                items.append(SelectItem(Star(), None))
            else:
                items.append(self.select_item())
            if not self.accept("op", ","):
                break
        self.expect("kw", "from")
        rel = self.relation()
        while True:
            jt = self._join_type()
            if jt is None:
                break
            right = self.relation()
            # temporal lookup: JOIN t FOR SYSTEM_TIME AS OF PROCTIME()
            # (reference: temporal_join.rs:44; sqlparser table factor)
            if self._accept_word("for"):
                if not self._accept_word("system_time"):
                    raise SyntaxError("expected SYSTEM_TIME after FOR")
                self.expect("kw", "as")
                if not self._accept_word("of"):
                    raise SyntaxError("expected OF")
                if not self._accept_word("proctime"):
                    raise SyntaxError("expected PROCTIME()")
                self.expect("op", "(")
                self.expect("op", ")")
                if jt not in ("inner", "left"):
                    raise SyntaxError(
                        "temporal joins support INNER / LEFT only"
                    )
                jt = "temporal" if jt == "inner" else "temporal_left"
                # the alias may follow the whole FOR SYSTEM_TIME clause
                alias = self._rel_alias()
                if alias is not None:
                    if not isinstance(right, TableRef):
                        raise SyntaxError("temporal side must be a table")
                    right = TableRef(right.name, alias)
            self.expect("kw", "on")
            rel = Join(rel, right, self.expr(), jt)
        where = self.expr() if self.accept("kw", "where") else None
        group: Tuple[Ident, ...] = ()
        gsets: Tuple[Tuple[Ident, ...], ...] = ()
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            if self._accept_word("grouping"):
                if not self._accept_word("sets"):
                    raise SyntaxError("expected SETS after GROUPING")
                self.expect("op", "(")
                sets = []
                while True:
                    self.expect("op", "(")
                    cols = []
                    if not self.accept("op", ")"):
                        cols.append(self.qualified_ident())
                        while self.accept("op", ","):
                            cols.append(self.qualified_ident())
                        self.expect("op", ")")
                    sets.append(tuple(cols))
                    if not self.accept("op", ","):
                        break
                self.expect("op", ")")
                gsets = tuple(sets)
                # union of all set columns is the working key set
                seen, union = set(), []
                for st in gsets:
                    for c in st:
                        if c.name not in seen:
                            seen.add(c.name)
                            union.append(c)
                group = tuple(union)
            else:
                cols = [self.qualified_ident()]
                while self.accept("op", ","):
                    cols.append(self.qualified_ident())
                group = tuple(cols)
        having = self.expr() if self.accept("kw", "having") else None
        order: Tuple[Tuple[Ident, bool], ...] = ()
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            obs = []
            while True:
                ident = self.qualified_ident()
                desc = bool(self.accept("kw", "desc"))
                if not desc:
                    self.accept("kw", "asc")
                obs.append((ident, desc))
                if not self.accept("op", ","):
                    break
            order = tuple(obs)
        limit = None
        if self.accept("kw", "limit"):
            limit = int(self.expect("num").value)
        return Select(
            tuple(items), rel, where, group, order, limit, gsets,
            having=having, distinct=distinct,
        )

    def select_item(self) -> SelectItem:
        e = self.expr()
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("ident").value
        elif self.peek().kind == "ident":
            alias = self.next().value
        return SelectItem(e, alias)

    # -- relations -------------------------------------------------------
    def relation(self):
        if self.accept("op", "("):
            sel = self.select()
            self.expect("op", ")")
            self.accept("kw", "as")
            alias = self.expect("ident").value
            return SubQuery(sel, alias)
        if self.peek().kind == "kw" and self.peek().value in ("tumble", "hop"):
            kind = self.next().value
            self.expect("op", "(")
            table = TableRef(self.expect("ident").value)
            self.expect("op", ",")
            ts_col = self.expect("ident").value
            self.expect("op", ",")
            first = self.interval_ms()
            slide = size = first
            if kind == "hop":
                self.expect("op", ",")
                size = self.interval_ms()
                slide = first  # HOP(tbl, ts, slide, size) — pg/RW order
            self.expect("op", ")")
            return WindowTVF(
                kind, table, ts_col, size, slide, self._rel_alias()
            )
        name = self.expect("ident").value
        return TableRef(name, self._rel_alias())

    def _rel_alias(self) -> Optional[str]:
        """[AS] alias after a relation. A bare LEFT/RIGHT/FULL is a join
        prefix, not an alias (contextual words; use AS to force)."""
        if self.accept("kw", "as"):
            return self.expect("ident").value
        t = self.peek()
        if t.kind == "ident" and t.value not in (
            "left", "right", "full", "for",
            "union",  # a set-op continuation, not an alias
            "emit",  # EMIT ON WINDOW CLOSE suffix
        ):
            return self.next().value
        return None

    def interval_ms(self) -> int:
        self.expect("kw", "interval")
        raw = self.expect("str").value
        unit_tok = self.accept("kw")
        text = raw.strip()
        m = re.fullmatch(r"(\d+)(?:\s+(\w+))?", text)
        if not m:
            raise SyntaxError(f"bad interval {raw!r}")
        n = int(m.group(1))
        unit = (unit_tok.value if unit_tok else (m.group(2) or "second")).lower()
        scale = INTERVAL_SCALES.get(unit)
        if scale is None:
            raise SyntaxError(f"bad interval unit {unit!r}")
        return n * scale

    def qualified_ident(self) -> Ident:
        a = self.expect("ident").value
        if self.accept("op", "."):
            return Ident(self.expect("ident").value, qualifier=a)
        return Ident(a)

    def _window_spec(self, call: FuncCall) -> WindowFuncCall:
        """OVER ( [PARTITION BY c,...] [ORDER BY c [ASC|DESC],...]
        [ROWS BETWEEN <n> PRECEDING AND CURRENT ROW] )."""
        self.expect("op", "(")
        part: List[Ident] = []
        order: List[Tuple[Ident, bool]] = []
        frame = None
        if self._accept_word("partition"):
            self.expect("kw", "by")
            part.append(self.qualified_ident())
            while self.accept("op", ","):
                part.append(self.qualified_ident())
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            while True:
                ident = self.qualified_ident()
                desc = bool(self.accept("kw", "desc"))
                if not desc:
                    self.accept("kw", "asc")
                order.append((ident, desc))
                if not self.accept("op", ","):
                    break
        if self._accept_word("rows"):
            self.expect("kw", "between")
            if self._accept_word("unbounded"):
                if not self._accept_word("preceding"):
                    raise SyntaxError("expected PRECEDING after UNBOUNDED")
                lo = None
            else:
                lo = -int(self.expect("num").value)
                if not self._accept_word("preceding"):
                    raise SyntaxError("expected PRECEDING")
            self.expect("kw", "and")
            if self._accept_word("current"):
                if not self._accept_word("row"):
                    raise SyntaxError("expected ROW after CURRENT")
                hi = 0
            elif self._accept_word("unbounded"):
                raise SyntaxError("UNBOUNDED FOLLOWING is not supported")
            else:
                hi = int(self.expect("num").value)
                if not self._accept_word("following"):
                    raise SyntaxError("expected FOLLOWING")
            # lo None = UNBOUNDED PRECEDING (running; frame stays None only
            # when hi == 0, the executor's running default)
            if lo is None:
                if hi != 0:
                    raise SyntaxError(
                        "UNBOUNDED PRECEDING .. n FOLLOWING is unsupported"
                    )
                frame = None
            else:
                frame = (lo, hi)
        self.expect("op", ")")
        return WindowFuncCall(call, tuple(part), tuple(order), frame)

    # -- expressions (precedence climbing) -------------------------------
    def expr(self):
        return self.or_expr()

    def or_expr(self):
        e = self.and_expr()
        while self.accept("kw", "or"):
            e = BinaryOp("or", e, self.and_expr())
        return e

    def and_expr(self):
        e = self.not_expr()
        while self.accept("kw", "and"):
            e = BinaryOp("and", e, self.not_expr())
        return e

    def not_expr(self):
        if self.accept("kw", "not"):
            return UnaryOp("not", self.not_expr())
        return self.cmp_expr()

    def cmp_expr(self):
        e = self.add_expr()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "<>", "!=", "<", "<=", ">", ">="):
            op = self.next().value
            return BinaryOp("=" if op == "=" else op, e, self.add_expr())
        if self.accept("kw", "is"):
            neg = bool(self.accept("kw", "not"))
            self.expect("kw", "null")
            return UnaryOp("is not null" if neg else "is null", e)
        if self.accept("kw", "between"):
            lo = self.add_expr()
            self.expect("kw", "and")
            hi = self.add_expr()
            return FuncCall("between", (e, lo, hi))
        negated = False
        if (
            self.peek().kind == "kw"
            and self.peek().value == "not"
            and self.toks[self.i + 1].kind == "kw"
            and self.toks[self.i + 1].value == "in"
        ):
            self.next()  # NOT (only as a prefix of IN here)
            negated = True
        if self.accept("kw", "in"):
            self.expect("op", "(")
            if self.peek().kind == "kw" and self.peek().value == "select":
                sub = self.select()
                self.expect("op", ")")
                return InSubquery(e, sub, negated)
            vals = [self.expr()]
            while self.accept("op", ","):
                vals.append(self.expr())
            self.expect("op", ")")
            inlist = FuncCall("in", (e, *vals))
            return UnaryOp("not", inlist) if negated else inlist
        return e

    def add_expr(self):
        e = self.mul_expr()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                e = BinaryOp(self.next().value, e, self.mul_expr())
            else:
                return e

    def mul_expr(self):
        e = self.unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                e = BinaryOp(self.next().value, e, self.unary())
            else:
                return e

    def unary(self):
        if self.accept("op", "-"):
            return UnaryOp("-", self.unary())
        return self.primary()

    def primary(self):
        t = self.peek()
        if t.kind == "num":
            self.next()
            return Literal(float(t.value) if "." in t.value else int(t.value))
        if t.kind == "str":
            self.next()
            return Literal(t.value)
        if self.accept("kw", "null"):
            return Literal(None)
        if self.accept("kw", "true"):
            return Literal(True)
        if self.accept("kw", "false"):
            return Literal(False)
        if self.accept("kw", "case"):
            branches = []
            while self.accept("kw", "when"):
                cond = self.expr()
                self.expect("kw", "then")
                branches.append((cond, self.expr()))
            default = self.expr() if self.accept("kw", "else") else None
            self.expect("kw", "end")
            return CaseExpr(tuple(branches), default)
        if self.accept("op", "("):
            if self.peek().kind == "kw" and self.peek().value == "select":
                # scalar subquery: (SELECT <agg expr> FROM ... [WHERE ...])
                # (reference: binder/expr/subquery.rs:22)
                sub = self.select()
                self.expect("op", ")")
                return ScalarSubQuery(sub)
            e = self.expr()
            self.expect("op", ")")
            return e
        if t.kind == "ident":
            self.next()
            if t.value == "exists" and (
                self.peek().kind == "op" and self.peek().value == "("
            ):
                # EXISTS (SELECT ...) — only the subquery form; a
                # function named exists() would shadow it, none exists
                save = self.i
                self.next()  # (
                if self.peek().kind == "kw" and self.peek().value == "select":
                    sub = self.select()
                    self.expect("op", ")")
                    return Exists(sub)
                self.i = save
            if self.accept("op", "("):
                if t.value == "extract":
                    # EXTRACT(FIELD FROM expr) — pg special form
                    f = self.next()
                    if f.kind not in ("ident", "kw"):
                        raise SyntaxError("EXTRACT needs a field name")
                    self.expect("kw", "from")
                    inner = self.expr()
                    self.expect("op", ")")
                    return FuncCall("extract", (Literal(f.value), inner))
                if self.accept("op", "*"):
                    self.expect("op", ")")
                    call = FuncCall(t.value, ("*",))
                    if self._accept_word("over"):
                        return self._window_spec(call)
                    return call
                args = []
                dis = bool(self.accept("kw", "distinct"))
                if not self.accept("op", ")"):
                    args.append(self.expr())
                    while self.accept("op", ","):
                        args.append(self.expr())
                    self.expect("op", ")")
                call = FuncCall(t.value, tuple(args), distinct=dis)
                if self._accept_word("over"):
                    return self._window_spec(call)
                return call
            if self.accept("op", "."):
                return Ident(self.expect("ident").value, qualifier=t.value)
            return Ident(t.value)
        raise SyntaxError(f"unexpected token {t.value!r}")


def parse(sql: str) -> Statement:
    return Parser(sql).parse()
