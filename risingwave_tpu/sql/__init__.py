"""SQL frontend — parser, binder, streaming planner.

Reference: src/sqlparser/ (parser), src/frontend/src/{binder,planner,
optimizer,stream_fragmenter}/. See parser.py / planner.py docs.
"""

from risingwave_tpu.sql.parser import parse
from risingwave_tpu.sql.planner import Catalog, PlannedMV, StreamPlanner

__all__ = ["parse", "Catalog", "StreamPlanner", "PlannedMV"]
